"""On-chip block/dtype sweep for the pallas KNN kernels.

Usage: python tools/knn_sweep.py [d]
Prints qps + TF/s per config using the memoization-safe timing methodology
from bench.py (lax.map over rolled inputs, scalar-forced).
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

KNN_QUERIES = 8_192
KNN_TRAIN = 131_072
STEPS = 8
K = 5


def timed(many_fn, *args, repeats=3):
    import jax.numpy as jnp

    _ = float(many_fn(*args))
    best = np.inf
    for s in range(1, repeats + 1):
        shifted = (jnp.roll(args[0], s, axis=-1),) + args[1:]
        t0 = time.perf_counter()
        _ = float(many_fn(*shifted))
        best = min(best, time.perf_counter() - t0)
    return best


def run(dim):
    import jax
    import jax.numpy as jnp
    from avenir_tpu.ops.pallas_knn import knn_topk_lanes, knn_topk_pallas

    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(KNN_QUERIES, dim)).astype(np.float32))
    t = jnp.asarray(rng.normal(size=(KNN_TRAIN, dim)).astype(np.float32))

    configs = [
        ("old_packed", knn_topk_pallas, 512, 4096, "float32", {"packed": True}),
        ("old_packed", knn_topk_pallas, 512, 4096, "bfloat16", {"packed": True}),
        ("lanes", knn_topk_lanes, 512, 4096, "float32", {}),
        ("lanes", knn_topk_lanes, 512, 4096, "bfloat16", {}),
        ("lanes", knn_topk_lanes, 256, 4096, "bfloat16", {}),
        ("lanes", knn_topk_lanes, 256, 8192, "bfloat16", {}),
        ("lanes", knn_topk_lanes, 512, 2048, "bfloat16", {}),
        ("lanes", knn_topk_lanes, 1024, 4096, "bfloat16", {}),
    ]
    for name, fn, bq, bt, cdt, extra in configs:
        @jax.jit
        def many(q, t):
            def step(i):
                qi = jnp.roll(q, i, axis=0)
                dist, idx = fn(qi, t, k=K, block_q=bq, block_t=bt,
                               metric="euclidean", compute_dtype=cdt, **extra)
                return jnp.sum(dist) + jnp.sum(idx).astype(jnp.float32)
            return jax.lax.map(step, jnp.arange(1, STEPS + 1)).sum()

        try:
            dt = timed(many, q, t)
        except Exception as exc:
            print(f"{name} bq={bq} bt={bt} {cdt}: FAILED {type(exc).__name__}: "
                  f"{str(exc)[:200]}")
            continue
        qps = KNN_QUERIES * STEPS / dt
        tfs = 2.0 * KNN_QUERIES * KNN_TRAIN * dim * STEPS / dt / 1e12
        print(f"{name} bq={bq} bt={bt} {cdt}: {qps:.3e} q/s  {tfs:.1f} TF/s")


if __name__ == "__main__":
    run(int(sys.argv[1]) if len(sys.argv) > 1 else 128)
