"""On-chip block/dtype sweep for the pallas KNN kernels.

Usage: python tools/knn_sweep.py [d]
Prints qps + TF/s per config using the memoization-safe timing methodology
from bench.py (lax.map over rolled inputs, scalar-forced).
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

KNN_QUERIES = 8_192
KNN_TRAIN = 131_072
STEPS = 8
K = 5


def timed(many_fn, *args, repeats=3):
    import jax.numpy as jnp

    _ = float(many_fn(*args))
    best = np.inf
    for s in range(1, repeats + 1):
        shifted = (jnp.roll(args[0], s, axis=-1),) + args[1:]
        t0 = time.perf_counter()
        _ = float(many_fn(*shifted))
        best = min(best, time.perf_counter() - t0)
    return best


def run(dim):
    import jax
    import jax.numpy as jnp
    from avenir_tpu.models.knn import _vote
    from avenir_tpu.ops.pallas_knn import (knn_classify_lanes,
                                           knn_topk_lanes, knn_topk_pallas)

    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(KNN_QUERIES, dim)).astype(np.float32))
    t = jnp.asarray(rng.normal(size=(KNN_TRAIN, dim)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 2, KNN_TRAIN).astype(np.int32))

    configs = [
        ("old_packed", knn_topk_pallas, 512, 4096, "float32", {"packed": True}),
        ("old_packed", knn_topk_pallas, 512, 4096, "bfloat16", {"packed": True}),
        ("lanes", knn_topk_lanes, 512, 4096, "float32", {}),
        ("lanes", knn_topk_lanes, 512, 4096, "bfloat16", {}),
        ("lanes", knn_topk_lanes, 256, 4096, "bfloat16", {}),
        ("lanes", knn_topk_lanes, 256, 8192, "bfloat16", {}),
        ("lanes", knn_topk_lanes, 512, 2048, "bfloat16", {}),
        ("lanes", knn_topk_lanes, 1024, 4096, "bfloat16", {}),
    ]
    for name, fn, bq, bt, cdt, extra in configs:
        @jax.jit
        def many(q, t):
            def step(i):
                qi = jnp.roll(q, i, axis=0)
                dist, idx = fn(qi, t, k=K, block_q=bq, block_t=bt,
                               metric="euclidean", compute_dtype=cdt, **extra)
                return jnp.sum(dist) + jnp.sum(idx).astype(jnp.float32)
            return jax.lax.map(step, jnp.arange(1, STEPS + 1)).sum()

        try:
            dt = timed(many, q, t)
        except Exception as exc:
            print(f"{name} bq={bq} bt={bt} {cdt}: FAILED {type(exc).__name__}: "
                  f"{str(exc)[:200]}")
            continue
        qps = KNN_QUERIES * STEPS / dt
        tfs = 2.0 * KNN_QUERIES * KNN_TRAIN * dim * STEPS / dt / 1e12
        print(f"{name} bq={bq} bt={bt} {cdt}: {qps:.3e} q/s  {tfs:.1f} TF/s")

    # fused-vs-composed A/B at the same block configs (VERDICT item: the
    # fused in-kernel vote must beat topk+XLA-vote on hardware, or its
    # bench default stays off). Same timing methodology.
    ab_configs = [(1024, 4096), (512, 4096), (1024, 2048), (512, 8192)]
    for bq, bt in ab_configs:
        @jax.jit
        def composed(q, t, labels):
            def step(i):
                qi = jnp.roll(q, i, axis=0)
                dist, idx = knn_topk_lanes(
                    qi, t, k=K, block_q=bq, block_t=bt,
                    metric="euclidean", compute_dtype="bfloat16")
                scores = _vote(dist, labels[idx], jnp.ones_like(dist),
                               "gaussian", 30.0, 2, False, False)
                return jnp.sum(scores).astype(jnp.float32)
            return jax.lax.map(step, jnp.arange(1, STEPS + 1)).sum()

        @jax.jit
        def fused(q, t, labels):
            def step(i):
                scores = knn_classify_lanes(
                    jnp.roll(q, i, axis=0), t, labels, k=K, n_classes=2,
                    kernel_fn="gaussian", kernel_param=30.0, block_q=bq,
                    block_t=bt, metric="euclidean",
                    compute_dtype="bfloat16")
                return jnp.sum(scores)
            return jax.lax.map(step, jnp.arange(1, STEPS + 1)).sum()

        for label, fn2 in (("composed", composed), ("fused", fused)):
            try:
                dt = timed(fn2, q, t, labels)
                print(f"{label} bq={bq} bt={bt}: "
                      f"{KNN_QUERIES * STEPS / dt:.3e} classify q/s")
            except Exception as exc:
                print(f"{label} bq={bq} bt={bt}: FAILED "
                      f"{type(exc).__name__}: {str(exc)[:200]}")


if __name__ == "__main__":
    run(int(sys.argv[1]) if len(sys.argv) > 1 else 128)
