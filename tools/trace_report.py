"""Roll an avenir-trace Chrome-trace file into per-phase tables.

The span flight recorder (avenir_tpu.obs.trace) exports ``traceEvents``
JSON that Perfetto / chrome://tracing render on a timeline; this tool is
the terminal view of the same file: a per-phase rollup (count, total,
mean, p95, max per span name), a per-chunk breakdown of the streaming
phases (read / parse / fold), and a stall-attribution section that ranks
the producer/consumer stall sources by total blocked time — the first
question profiling-guided tuning asks ("where does the time go per
chunk, and who is waiting on whom").

Usage:
    python tools/trace_report.py TRACE.json [--top N] [--json]

The rollup quantiles come from the same log-bucketed accumulator the
job server's latency surface uses (avenir_tpu.obs.histogram), so a number
printed here and one printed by ``python -m avenir_tpu stats`` mean the
same thing.
"""

import argparse
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avenir_tpu.obs.histogram import LatencyHistogram  # noqa: E402

#: span names whose duration is time BLOCKED, not time working — ranked
#: separately so a stall can never hide inside a work phase's mean
STALL_PREFIX = "stream.stall."


def load_events(path):
    """The complete-event spans of a Chrome-trace file as dicts with
    millisecond durations (other event types are skipped)."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, list):            # the bare JSON-array trace form
        events, meta = doc, {}
    else:
        events, meta = doc.get("traceEvents", []), doc.get("metadata", {})
    out = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        out.append({"name": ev.get("name", "?"),
                    "dur_ms": float(ev.get("dur", 0.0)) / 1000.0,
                    "ts": float(ev.get("ts", 0.0)),
                    "tid": ev.get("tid"),
                    "args": ev.get("args") or {}})
    return out, meta


def rollup(events):
    """{name: LatencyHistogram-of-ms} across all spans."""
    hists = defaultdict(LatencyHistogram)
    for ev in events:
        hists[ev["name"]].add(ev["dur_ms"])
    return dict(hists)


def phase_table(hists, wall_ms):
    """The per-phase rows, widest total first. `wall_ms` (trace extent)
    scales the %-of-wall column; phases overlap across threads, so the
    percentages legitimately sum past 100 on a fused run."""
    rows = []
    for name, h in hists.items():
        rows.append({"phase": name, "count": h.count,
                     "total_ms": round(h.total, 3),
                     "mean_ms": round(h.mean, 3),
                     "p95_ms": round(h.quantile(95), 3),
                     "max_ms": round(h.max_val, 3),
                     "pct_wall": round(100.0 * h.total / wall_ms, 1)
                     if wall_ms else 0.0})
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def chunk_table(events):
    """Per-sink fold totals: the ``stream.fold`` spans carry their sink
    label, so this is the 'which fold owns the chunk time' answer."""
    per_sink = defaultdict(LatencyHistogram)
    for ev in events:
        if ev["name"] == "stream.fold":
            per_sink[str(ev["args"].get("sink", "?"))].add(ev["dur_ms"])
    rows = [{"sink": sink, "chunks": h.count,
             "total_ms": round(h.total, 3),
             "mean_ms": round(h.mean, 3),
             "p95_ms": round(h.quantile(95), 3)}
            for sink, h in per_sink.items()]
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def stall_table(events):
    """Stall sources ranked by total blocked time. ``producer`` stalls
    mean the consumer (fold/parse downstream) is the bottleneck;
    ``consumer`` stalls mean the producer (read/parse upstream) is."""
    per_name = defaultdict(LatencyHistogram)
    for ev in events:
        if ev["name"].startswith(STALL_PREFIX):
            per_name[ev["name"]].add(ev["dur_ms"])
    rows = [{"stall": name, "count": h.count,
             "total_ms": round(h.total, 3),
             "mean_ms": round(h.mean, 3),
             "max_ms": round(h.max_val, 3)}
            for name, h in per_name.items()]
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def build_report(path, top=20):
    events, meta = load_events(path)
    if not events:
        return {"trace": path, "spans": 0, "error": "no complete events"}
    t_lo = min(ev["ts"] for ev in events)
    t_hi = max(ev["ts"] + ev["dur_ms"] * 1000.0 for ev in events)
    wall_ms = (t_hi - t_lo) / 1000.0
    work = [ev for ev in events
            if not ev["name"].startswith(STALL_PREFIX)]
    return {"trace": path,
            "spans": len(events),
            "dropped_spans": int(meta.get("dropped_spans", 0)),
            "wall_ms": round(wall_ms, 3),
            "threads": len({ev["tid"] for ev in events}),
            "phases": phase_table(rollup(work), wall_ms)[:top],
            "folds": chunk_table(events)[:top],
            "stalls": stall_table(events)[:top]}


def _print_rows(rows, cols, title):
    if not rows:
        return
    print(f"\n{title}")
    widths = {c: max(len(c), *(len(str(r[c])) for r in rows))
              for c in cols}
    print("  " + "  ".join(c.rjust(widths[c]) for c in cols))
    for r in rows:
        print("  " + "  ".join(str(r[c]).rjust(widths[c]) for c in cols))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="trace_report",
        description="per-phase/per-chunk rollup of an avenir-trace file")
    ap.add_argument("trace", help="Chrome-trace JSON (obs export, or a "
                                  "directory containing trace.json)")
    ap.add_argument("--top", type=int, default=20,
                    help="rows per table (default 20)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    args = ap.parse_args(argv)
    path = args.trace
    if os.path.isdir(path):
        path = os.path.join(path, "trace.json")
    try:
        report = build_report(path, top=args.top)
    except (OSError, ValueError) as e:
        print(f"cannot read trace {path!r}: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=1))
        return 0 if "error" not in report else 1
    if "error" in report:
        print(f"{path}: {report['error']}")
        return 1
    print(f"trace {path}: {report['spans']} spans "
          f"({report['dropped_spans']} dropped) across "
          f"{report['threads']} thread(s), {report['wall_ms']:.1f}ms wall")
    _print_rows(report["phases"],
                ["phase", "count", "total_ms", "mean_ms", "p95_ms",
                 "max_ms", "pct_wall"], "per-phase rollup (ms):")
    _print_rows(report["folds"],
                ["sink", "chunks", "total_ms", "mean_ms", "p95_ms"],
                "per-sink fold time (ms):")
    _print_rows(report["stalls"],
                ["stall", "count", "total_ms", "mean_ms", "max_ms"],
                "stall attribution (ms, top sources first):")
    if report["stalls"]:
        top = report["stalls"][0]
        side = ("consumer is the bottleneck (folds can't keep up)"
                if top["stall"].endswith("producer")
                else "producer is the bottleneck (read/parse can't keep up)")
        print(f"\ntop stall: {top['stall']} "
              f"({top['total_ms']:.1f}ms total) -> {side}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
