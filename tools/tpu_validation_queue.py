"""One-shot TPU validation queue for work that landed during an outage.

Runs, in order, everything that needs the real chip and prints a PASS/FAIL
line per stage plus one summary JSON line:

  1. fused knn_classify_lanes compiles + runs (f32 and bf16) — the kernel
     was rebuilt (argmin-free epilogue, full-tile label OR, vmem cap)
     without hardware available;
  2. tools/tpu_kernel_check.py (the full compiled-kernel sweep, including
     the exhausted-rounds edge);
  3. the reworked bench sections one by one (apriori device-resident scan,
     forest-batched RF, resident-state bandit, 1B-row NB stream, 1B-row
     streaming KNN);
  4. (optional, --full) the whole bench.py.

Usage: python tools/tpu_validation_queue.py [--full]
Exit 0 iff every attempted stage passes.
"""

import json
import subprocess
import sys
import time

sys.path.insert(0, ".")


def main() -> int:
    from __graft_entry__ import _probe_accelerator

    ok, why = _probe_accelerator(120)
    if not ok:
        print(json.dumps({"queue": "aborted", "reason": why}))
        return 1

    results = {}

    def stage(name, fn):
        t0 = time.perf_counter()
        try:
            out = fn()
            results[name] = {"ok": True, "s": round(time.perf_counter() - t0, 1)}
            if out is not None:
                results[name]["value"] = out
            print(f"PASS {name} ({results[name]['s']}s)", flush=True)
        except Exception as e:  # keep draining the queue
            results[name] = {"ok": False, "error": repr(e)[:300]}
            print(f"FAIL {name}: {e!r}", flush=True)

    def fused_kernel():
        import numpy as np
        import jax.numpy as jnp
        from avenir_tpu.ops.pallas_knn import knn_classify_lanes

        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.normal(size=(8192, 128)).astype(np.float32))
        t = jnp.asarray(rng.normal(size=(131072, 128)).astype(np.float32))
        tl = jnp.asarray(rng.integers(0, 2, 131072).astype(np.int32))
        sums = {}
        for dt in ("float32", "bfloat16"):
            s = knn_classify_lanes(q, t, tl, k=5, n_classes=2,
                                   kernel_fn="gaussian", kernel_param=30.0,
                                   block_q=1024, block_t=4096,
                                   metric="euclidean", compute_dtype=dt)
            sums[dt] = float(jnp.sum(s))
            assert np.isfinite(sums[dt])
        return sums

    def kernel_check():
        proc = subprocess.run([sys.executable, "tools/tpu_kernel_check.py"],
                              capture_output=True, text=True, timeout=3600)
        tail = proc.stdout.strip().splitlines()[-1] if proc.stdout else ""
        assert proc.returncode == 0, tail or proc.stderr[-300:]
        return tail

    stage("fused_classify_kernel", fused_kernel)
    stage("kernel_check_sweep", kernel_check)

    import bench

    stage("bench_apriori", lambda: bench.bench_apriori()[0])
    stage("bench_random_forest", lambda: bench.bench_random_forest()[0])
    stage("bench_bandit", bench.bench_bandit)
    stage("bench_nb_stream_1b", lambda: bench.bench_nb_stream()[0])
    stage("bench_knn_stream_1b", lambda: bench.bench_knn_stream()[0])

    if "--full" in sys.argv[1:]:
        def full_bench():
            proc = subprocess.run([sys.executable, "bench.py"],
                                  capture_output=True, text=True,
                                  timeout=5400)
            assert proc.returncode == 0, proc.stderr[-300:]
            return json.loads(proc.stdout.strip().splitlines()[-1])
        stage("bench_full", full_bench)

    print(json.dumps({"queue": "done", "stages": results}))
    return 0 if all(r.get("ok") for r in results.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
