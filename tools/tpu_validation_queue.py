"""One-shot TPU validation queue for work that landed during an outage.

Round-5 rework: the tunnel FLAPS (it answered a probe at 03:49 and wedged
15 seconds later, hanging the previous in-process version of this queue
indefinitely). All hardware measurement now lives in bench.py's section
bank — every section runs in its OWN subprocess with a hard timeout and
each success is persisted to TPU_BANK_r05.json the moment it lands. This
queue is the operator entry point over that machinery:

  python tools/tpu_validation_queue.py          # drain unbanked sections
  python tools/tpu_validation_queue.py --full   # re-measure everything

It prints one PASS/FAIL line per section from the bank plus a summary
JSON line. Exit 0 iff every section is banked ok. The fused classify
kernel (f32/bf16 correctness vs an XLA oracle) and the exhausted-rounds
edge are covered inside the kernel_sweep section
(tools/tpu_kernel_check.py).
"""

import json
import sys

sys.path.insert(0, ".")


def main() -> int:
    from bench import SECTIONS, _load_bank, drain

    drain(force="--full" in sys.argv[1:])
    bank = _load_bank()
    all_ok = True
    for name, _fn, _timeout, _needs_tpu in SECTIONS:
        entry = bank.get(name, {})
        if entry.get("ok"):
            print(f"PASS {name} ({entry.get('s', '?')}s)", flush=True)
        else:
            all_ok = False
            print(f"FAIL {name}: {entry.get('error', 'not measured')}",
                  flush=True)
    print(json.dumps({"queue": "done", "banked": bank}))
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
