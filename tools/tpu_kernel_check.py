"""Compiled-path sweep of every pallas KNN kernel on the real TPU.

Interpret-mode tests (tests/test_pallas_knn.py) prove the algorithms; this
script proves the Mosaic-compiled artifacts: bitcast/int-key ops, pack-bit
quantization, n_valid masking, sentinel laundering, same-lane collisions,
and both compute dtypes, each checked against a NumPy oracle ON DEVICE.

Usage: python tools/tpu_kernel_check.py   (needs jax.default_backend()=tpu)
Exit code 0 iff every case passes; prints one summary JSON line.
"""

import json
import sys

import numpy as np

sys.path.insert(0, ".")


def oracle(q, t, k, metric):
    if metric == "euclidean":
        full = np.sqrt(((q[:, None, :] - t[None, :, :]) ** 2).mean(-1))
    else:
        full = np.abs(q[:, None, :] - t[None, :, :]).sum(-1) / q.shape[1]
    order = np.argsort(full, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(full, order, axis=1), order


def check(name, got_d, got_i, q, t, k, metric, rtol):
    got_d, got_i = np.asarray(got_d), np.asarray(got_i)
    ref_d, ref_i = oracle(q, t, k, metric)
    kk = min(k, t.shape[0])
    ok = True
    msg = []
    if not np.allclose(got_d[:, :kk], ref_d[:, :kk], rtol=rtol, atol=1e-5):
        ok = False
        msg.append(f"dist err {np.abs(got_d[:, :kk]-ref_d[:, :kk]).max():.2e}")
    # tie-tolerant recall: a returned neighbor counts if its TRUE distance
    # is within the mode's quantization tolerance of the kth-best — the
    # packed/bf16 modes may legally swap near-ties
    if metric == "euclidean":
        full = np.sqrt(((q[:, None, :] - t[None, :, :]) ** 2).mean(-1))
    else:
        full = np.abs(q[:, None, :] - t[None, :, :]).sum(-1) / q.shape[1]
    hits = 0
    for r in range(q.shape[0]):
        bar = ref_d[r, kk - 1] * (1.0 + 2 * rtol) + 1e-6
        hits += sum(full[r, i] <= bar for i in got_i[r, :kk] if i >= 0) / kk
    recall = hits / q.shape[0]
    if recall < 0.999:
        ok = False
        msg.append(f"tie-tolerant recall {recall:.3f}")
    if kk < k and not (np.isinf(got_d[:, kk:]).all()
                       and (got_i[:, kk:] == -1).all()):
        ok = False
        msg.append("bad sentinel slots")
    if (got_i[:, :kk] >= t.shape[0]).any() or (got_i[:, :kk] < 0).any():
        ok = False
        msg.append("index out of range")
    print(f"{'PASS' if ok else 'FAIL'} {name}" + (": " + "; ".join(msg) if msg else ""))
    return ok


def main():
    import jax
    import jax.numpy as jnp
    from avenir_tpu.ops.distance import pad_train
    from avenir_tpu.ops.pallas_knn import knn_topk_lanes, knn_topk_pallas

    from avenir_tpu.utils.profiling import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()

    if jax.default_backend() != "tpu":
        print(json.dumps({"metric": "tpu_kernel_check", "skipped": True,
                          "reason": "no TPU backend"}))
        return 0

    rng = np.random.default_rng(7)
    results = []

    cases = [
        # (label, nq, nt_real, d, k, block_q, block_t, metric)
        ("basic", 256, 4096, 16, 5, 256, 512, "euclidean"),
        ("pad", 256, 3000, 16, 5, 256, 512, "euclidean"),
        ("multiblock", 256, 16384, 32, 5, 256, 2048, "euclidean"),
        ("tiny_train", 128, 3, 8, 5, 128, 256, "euclidean"),
        ("k1", 128, 2048, 8, 1, 128, 512, "euclidean"),
        ("manhattan", 128, 1024, 8, 4, 128, 512, "manhattan"),
    ]
    for label, nq, nt, d, k, bq, bt, metric in cases:
        q = rng.normal(size=(nq, d)).astype(np.float32)
        t = rng.normal(size=(nt, d)).astype(np.float32)
        t_pad, _, n_valid = pad_train(t, None, bt)
        qd, td = jnp.asarray(q), jnp.asarray(t_pad)

        de, ie = knn_topk_pallas(qd, td, k=k, block_q=bq, block_t=bt,
                                 metric=metric, n_valid=n_valid)
        results.append(check(f"exact/{label}", de, ie, q, t, k, metric, 1e-3))
        if bt <= 4096:
            dp, ip = knn_topk_pallas(qd, td, k=k, block_q=bq, block_t=bt,
                                     metric=metric, n_valid=n_valid,
                                     packed=True)
            results.append(
                check(f"packed/{label}", dp, ip, q, t, k, metric, 3e-3))
        dl, il = knn_topk_lanes(qd, td, k=k, block_q=bq, block_t=bt,
                                metric=metric, n_valid=n_valid)
        results.append(check(f"lanes/{label}", dl, il, q, t, k, metric, 3e-3))
        if metric == "euclidean":
            db, ib = knn_topk_lanes(qd, td, k=k, block_q=bq, block_t=bt,
                                    metric=metric, n_valid=n_valid,
                                    compute_dtype="bfloat16")
            # bf16 cross term: ~2^-8 relative on distances
            results.append(
                check(f"lanes-bf16/{label}", db, ib, q, t, k, metric, 2e-2))

    # fused in-kernel vote vs composed top-k + _vote, compiled
    from avenir_tpu.models.knn import _vote
    from avenir_tpu.ops.pallas_knn import knn_classify_lanes

    for kernel_fn, metric in (("none", "euclidean"), ("gaussian", "euclidean"),
                              ("linearAdditive", "manhattan")):
        nq, d, k, C = 256, 8, 5, 3
        q = rng.normal(size=(nq, d)).astype(np.float32)
        t = rng.normal(size=(3000, d)).astype(np.float32)
        labels = rng.integers(0, C, 3000).astype(np.int32)
        t_pad, _, n_valid = pad_train(t, None, 512)
        lab_pad = np.zeros(t_pad.shape[0], np.int32)
        lab_pad[:3000] = labels
        scores = np.asarray(knn_classify_lanes(
            jnp.asarray(q), jnp.asarray(t_pad), jnp.asarray(lab_pad), k=k,
            n_classes=C, kernel_fn=kernel_fn, kernel_param=30.0, block_q=256,
            block_t=512, metric=metric, n_valid=n_valid))
        dist, idx = knn_topk_lanes(jnp.asarray(q), jnp.asarray(t_pad), k=k,
                                   block_q=256, block_t=512, metric=metric,
                                   n_valid=n_valid)
        ref = np.asarray(_vote(dist, jnp.asarray(lab_pad)[jnp.maximum(idx, 0)],
                               jnp.ones_like(dist), kernel_fn, 30.0, C,
                               False, False))
        agree = float((scores.argmax(1) == ref.argmax(1)).mean())
        ok = agree >= 0.99 and np.abs(scores - ref).max() <= 2.0
        print(f"{'PASS' if ok else 'FAIL'} fused-vote/{kernel_fn}-{metric}"
              + ("" if ok else f": agree={agree:.3f}"))
        results.append(ok)

    # exhausted-rounds edge: a corpus smaller than k forces the epilogue
    # through the int32-max fill (whose label-masked bits bitcast to NaN);
    # with a non-'none' kernel the scores must stay finite and the vote
    # mass must equal the real-neighbor count (regression for the
    # duplicate-count extraction fix)
    for dtype in ("float32", "bfloat16"):
        q = rng.normal(size=(256, 4)).astype(np.float32)
        t3 = rng.normal(size=(3, 4)).astype(np.float32)
        lab3 = np.array([0, 1, 1], np.int32)
        t_pad, _, n_valid = pad_train(t3, None, 512)
        lab_pad = np.zeros(t_pad.shape[0], np.int32)
        lab_pad[:3] = lab3
        scores = np.asarray(knn_classify_lanes(
            jnp.asarray(q), jnp.asarray(t_pad), jnp.asarray(lab_pad), k=5,
            n_classes=2, kernel_fn="gaussian", kernel_param=30.0,
            block_q=256, block_t=512, n_valid=n_valid,
            compute_dtype=dtype))
        ok = bool(np.isfinite(scores).all())
        print(f"{'PASS' if ok else 'FAIL'} fused-vote-exhausted/{dtype}"
              + ("" if ok else ": non-finite scores"))
        results.append(ok)

    # mixed categorical data through the one-hot expansion, compiled
    from avenir_tpu.models.knn import _expand_mixed
    from avenir_tpu.ops.distance import blocked_topk_neighbors

    bins = (4, 3)
    x_num = rng.normal(size=(2000, 3)).astype(np.float32) * 5
    ranges = np.full(3, 10.0, np.float32)
    x_cat = np.stack([rng.integers(0, b, 2000) for b in bins], 1).astype(
        np.int32)
    q_num, q_cat = x_num[:256], x_cat[:256]
    for metric in ("euclidean", "manhattan"):
        ref_d, _ = blocked_topk_neighbors(
            jnp.asarray(q_num), jnp.asarray(x_num), jnp.asarray(q_cat),
            jnp.asarray(x_cat), cat_bins=bins, num_ranges=jnp.asarray(ranges),
            k=4, block=2000, metric=metric)
        xe, n_attrs = _expand_mixed(x_num, ranges, x_cat, bins, metric)
        qe, _ = _expand_mixed(q_num, ranges, q_cat, bins, metric)
        t_pad, _, n_valid = pad_train(xe, None, 512)
        got_d, _ = knn_topk_lanes(
            jnp.asarray(np.ascontiguousarray(qe)), jnp.asarray(t_pad), k=4,
            block_q=256, block_t=512, metric=metric, n_valid=n_valid,
            n_attrs=n_attrs)
        ok = np.allclose(np.asarray(got_d), np.asarray(ref_d), rtol=3e-3,
                         atol=1e-4)
        print(f"{'PASS' if ok else 'FAIL'} mixed-onehot/{metric}")
        results.append(ok)

    # same-lane collision stress for the lane kernel, compiled
    q = np.zeros((128, 4), np.float32)
    t = rng.normal(size=(2048, 4)).astype(np.float32) * 10
    cols = [3, 131, 259, 515, 899]
    for rank, c in enumerate(cols):
        t[c] = 0.01 * (rank + 1)
    import jax.numpy as jnp2
    dl, il = knn_topk_lanes(jnp2.asarray(q), jnp2.asarray(t), k=5,
                            block_q=128, block_t=256)
    ok = set(np.asarray(il)[0].tolist()) == set(cols)
    print(f"{'PASS' if ok else 'FAIL'} lanes/same-lane-collision")
    results.append(ok)

    n_pass = sum(results)
    print(json.dumps({"metric": "tpu_kernel_check", "passed": n_pass,
                      "total": len(results)}))
    return 0 if n_pass == len(results) else 1


if __name__ == "__main__":
    sys.exit(main())
