#!/usr/bin/env python
"""Open-loop load harness for the job-server fleet.

Synthetic tenants fire requests at the serving surface the way real
traffic does — on a Poisson arrival clock that does NOT wait for
completions (open loop: a slow server faces the same offered load as a
fast one, so queue-wait tails are honest), over a corpus population
with Zipfian popularity (a few hot corpora, a long cold tail — the
distribution that makes warm-affinity routing matter).

Arms:

- ``inproc``  — one in-process JobServer (no subprocess, the fast arm
  for tests and tier-1).
- ``solo``    — a 1-host fleet: one ``serve --spool`` subprocess.
- ``fleet``   — an N-host fleet behind the affinity router.
- ``query``   — QUERY-shaped traffic (PR 20): mixed ``POST /score``
  + job submits against ``--hosts`` in-process listener pairs, score
  routing by MODEL affinity through :class:`net.fleet.ScoreFront`.
  Model popularity is Zipf (hot models stay warm on their pinned
  host), arrivals are the same open-loop Poisson clock, and
  ``--score-fraction`` of arrivals are scores. Reports score p50/p99
  (folded from the servers' merged per-model ``score_*_total_ms``
  histograms) NEXT TO jobs/min — the queries-are-jobs-too view.

Per arm it prints ONE JSON line: offered vs served jobs/min, p50/p99
queue wait and p99 chunk latency (the PR 10 histograms, read from the
server's merged metrics — never recomputed client-side), shed count
(fleet arms shed when every host is over its budget-vector entry), the
retry count, a ``lost_requests`` column that MUST be zero, and the
router's affinity hit rate.

Shed handling honors the edge's shed contract the way a well-behaved
client does: a shed request is NOT dropped — it backs off by the
Retry-After hint with capped exponential growth and ±20% jitter (the
listener's own jitter policy, so a cohort of shed harness tenants does
not retry in lockstep) and resubmits until served. That makes every
load run double as a soak test: offered = served + failed, always, and
``lost_requests`` (offered minus accounted) is asserted 0 by the exit
code.

    python tools/fleet_load.py --requests 40 --tenants 20 --corpora 6 \
        --rows 2000 --rate 5 --arms inproc,fleet --hosts 2
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

MST_CONF = {"mst.model.states": "L,M,H",
            "mst.class.label.field.ord": "1",
            "mst.skip.field.count": "2",
            "mst.class.labels": "T,F"}

#: the scoring view of the same classifier (server/score.py conf keys)
MARKOV_SCORE_CONF = {"field.delim": ",", "class.labels": "T,F",
                     "log.odds.threshold": "0", "skip.field.count": "2"}


def write_corpus(path: str, rows: int, seed: int) -> None:
    """A small markov-sequence corpus (the cheap byte-fold workload)."""
    rng = np.random.default_rng(seed)
    states = ["L", "M", "H"]
    with open(path, "w") as fh:
        for i in range(rows):
            up = i % 2 == 0
            s, toks = 1, []
            for _ in range(6):
                p = [0.1, 0.3, 0.6] if up else [0.6, 0.3, 0.1]
                s = int(np.clip(s + rng.choice([-1, 0, 1], p=p), 0, 2))
                toks.append(states[s])
            fh.write(f"c{i},{'T' if up else 'F'}," + ",".join(toks) + "\n")


def plan_load(args, corpora, out_dir):
    """The open-loop schedule: (arrival_s, request_obj) rows, fixed by
    the seed BEFORE any arm runs so every arm faces the identical
    offered load. Corpus popularity is Zipf(s) over the corpus list;
    arrivals are Poisson at --rate req/s."""
    rng = np.random.default_rng(args.seed)
    ranks = np.arange(1, len(corpora) + 1, dtype=float)
    pmf = ranks ** -args.zipf_s
    pmf /= pmf.sum()
    gaps = rng.exponential(1.0 / args.rate, size=args.requests)
    arrivals = np.cumsum(gaps)
    load = []
    for i in range(args.requests):
        corpus = corpora[int(rng.choice(len(corpora), p=pmf))]
        tenant = f"t{int(rng.integers(args.tenants)):04d}"
        load.append((float(arrivals[i]), {
            "job": "markovStateTransitionModel", "conf": MST_CONF,
            "inputs": [corpus],
            "output": os.path.join(out_dir, f"out_{i:05d}.txt"),
            "tenant": tenant,
        }))
    return load


def _hist_stats(hists, name):
    h = hists.get(name) or {}
    return {f"p50_{name}": h.get("p50", 0.0),
            f"p99_{name}": h.get("p99", 0.0)}


def run_inproc(args, load):
    from avenir_tpu.server import JobRequest, JobServer
    from avenir_tpu.server.spool import request_from_json

    with tempfile.TemporaryDirectory(prefix="fleet_load_state_") as sr:
        server = JobServer(workers=args.workers,
                           state_root=sr).start()
        tickets = []
        t0 = time.perf_counter()
        for arrival, obj in load:
            _sleep_until(t0, arrival)
            tickets.append(server.submit(request_from_json(obj)))
        server.drain(timeout=args.drain_timeout)
        wall = time.perf_counter() - t0
        served = sum(1 for t in tickets if _ok(t))
        stats = server.stats()
        server.shutdown()
    row = {"arm": "inproc", "hosts": 1, "served": served, "shed": 0,
           "retries": 0,
           "lost_requests": len(load) - len(tickets),
           "wall_s": round(wall, 2),
           "jobs_per_min": round(served / (wall / 60.0), 2)}
    row.update(_hist_stats(stats["hists"], "queue_wait_ms"))
    return row


def _ok(ticket):
    try:
        ticket.result(timeout=0)
        return True
    except BaseException:  # noqa: BLE001 — the count IS the report
        return False


#: shed-retry backoff: the Retry-After analog of the listener edge
#: (its EdgePolicy default), doubled per attempt, capped, ±20% jitter
RETRY_AFTER_S = 1.0
RETRY_CAP_S = 8.0
RETRY_JITTER = 0.2


def _backoff_s(attempt, rng):
    """Capped-jittered backoff before retry `attempt` (0-based) of a
    shed request — the client half of the edge's Retry-After contract."""
    nominal = min(RETRY_AFTER_S * (2.0 ** attempt), RETRY_CAP_S)
    return nominal * rng.uniform(1.0 - RETRY_JITTER, 1.0 + RETRY_JITTER)


def run_fleet(args, load, hosts):
    from avenir_tpu.net.fleet import Fleet

    root = tempfile.mkdtemp(prefix=f"fleet_load_{hosts}h_")
    fleet = Fleet(root, hosts=hosts, workers=args.workers,
                  budget_mb=args.budget_mb)
    rng = np.random.default_rng(args.seed + 1)
    shed = retries = 0
    names = []
    #: shed requests waiting out their backoff: (due_s, attempt, obj)
    parked = []

    def pump(now_s):
        """Resubmit every parked request whose backoff elapsed."""
        nonlocal shed, retries
        due = [p for p in parked if p[0] <= now_s]
        for item in due:
            parked.remove(item)
            _due, attempt, obj = item
            retries += 1
            name = fleet.submit(obj, block=False, count_held=False)
            if name is None:
                parked.append((now_s + _backoff_s(attempt + 1, rng),
                               attempt + 1, obj))
            else:
                names.append(name)

    with fleet:
        t0 = time.perf_counter()
        for arrival, obj in load:
            _sleep_until(t0, arrival)
            pump(time.perf_counter() - t0)
            # open loop: a fleet with no budget headroom sheds the
            # arrival (the listener's 429 analog) — the harness backs
            # off and retries like a well-behaved client, so the run
            # doubles as a soak test: nothing is ever dropped
            name = fleet.submit(obj, block=False)
            if name is None:
                shed += 1
                parked.append((time.perf_counter() - t0
                               + _backoff_s(0, rng), 0, obj))
            else:
                names.append(name)
        deadline = time.perf_counter() + args.drain_timeout
        while parked:
            if time.perf_counter() > deadline:
                break              # lost_requests column goes nonzero
            pump(time.perf_counter() - t0)
            time.sleep(0.05)
        try:
            rows = fleet.collect(names, timeout=args.drain_timeout)
        except TimeoutError:
            # a submitted request that never completed is exactly the
            # loss the lost_requests column exists to report — collect
            # what DID land and let the column (and rc=1) say the rest
            done = [n for n in fleet.ready() if n in set(names)]
            rows = fleet.collect(done, timeout=30.0) if done else {}
        wall = time.perf_counter() - t0
        snap = fleet.merged_metrics()
        hit_rate = fleet.router.affinity_hit_rate()
    served = sum(1 for r in rows.values() if r.get("ok"))
    row = {"arm": "fleet" if hosts > 1 else "solo", "hosts": hosts,
           "served": served, "shed": shed, "retries": retries,
           "lost_requests": len(load) - len(rows),
           "wall_s": round(wall, 2),
           "jobs_per_min": round(served / (wall / 60.0), 2),
           "affinity_hit_rate": round(hit_rate, 3)}
    row.update(_hist_stats(snap.get("hists", {}), "queue_wait_ms"))
    row.update(_hist_stats(snap.get("hists", {}), "chunk_latency_ms"))
    return row


def _sleep_until(t0, arrival):
    delay = arrival - (time.perf_counter() - t0)
    if delay > 0:
        time.sleep(delay)


# ------------------------------------------------------------ query arm
def train_models(corpora, work):
    """One markov classifier per corpus — the model POPULATION the
    Zipf popularity draw runs over."""
    from avenir_tpu.runner import run_job

    models = []
    for i, corpus in enumerate(corpora):
        path = os.path.join(work, f"model_{i:03d}.txt")
        run_job("markovStateTransitionModel", dict(MST_CONF), [corpus],
                path)
        models.append(path)
    return models


def plan_query_load(args, corpora, models, out_dir):
    """The mixed schedule: (arrival_s, ("score", model, row)) or
    (arrival_s, ("job", request_obj)) — model popularity Zipf(s),
    arrivals Poisson, ``--score-fraction`` of arrivals are scores.
    Fixed by the seed before any arm runs (the plan_load contract)."""
    rng = np.random.default_rng(args.seed + 2)
    ranks = np.arange(1, len(models) + 1, dtype=float)
    pmf = ranks ** -args.zipf_s
    pmf /= pmf.sum()
    arrivals = np.cumsum(rng.exponential(1.0 / args.rate,
                                         size=args.requests))
    rows_by_model = []
    for corpus in corpora:
        with open(corpus) as fh:
            rows_by_model.append([ln.rstrip("\n") for ln in fh][:512])
    load = []
    for i in range(args.requests):
        if rng.random() < args.score_fraction:
            m = int(rng.choice(len(models), p=pmf))
            row = rows_by_model[m][int(rng.integers(
                len(rows_by_model[m])))]
            load.append((float(arrivals[i]), ("score", models[m], row)))
        else:
            corpus = corpora[int(rng.choice(len(corpora), p=pmf))]
            load.append((float(arrivals[i]), ("job", {
                "job": "markovStateTransitionModel", "conf": MST_CONF,
                "inputs": [corpus],
                "output": os.path.join(out_dir, f"qout_{i:05d}.txt"),
                "tenant": f"t{int(rng.integers(args.tenants)):04d}",
            })))
    return load


def _score_hist(snap):
    """Fold every per-model ``score_*_total_ms`` raw histogram into ONE
    end-to-end score-latency distribution (the exact-merge algebra —
    client-side we only fold, never recompute)."""
    from avenir_tpu.obs.histogram import LatencyHistogram

    h = LatencyHistogram()
    for name, raw in (snap.get("hists_raw") or {}).items():
        if name.startswith("score_") and name.endswith("_total_ms"):
            h.merge(LatencyHistogram.from_dict(raw))
    return h.summary()


def run_query(args, qload, hosts):
    from avenir_tpu.net.fleet import FleetError, ScoreFront
    from avenir_tpu.net.listener import NetListener
    from avenir_tpu.obs.report import merge_snapshots
    from avenir_tpu.server import JobServer
    from avenir_tpu.server.spool import request_from_json

    import tempfile as _tf
    import threading

    roots = [_tf.mkdtemp(prefix=f"query_load_h{i}_")
             for i in range(hosts)]
    servers = [JobServer(workers=args.workers, state_root=r).start()
               for r in roots]
    listeners = [NetListener(s, port=0).start() for s in servers]
    score_errors = 0
    err_lock = threading.Lock()
    tickets, threads = [], []
    try:
        front = ScoreFront([f"http://127.0.0.1:{lis.port}"
                            for lis in listeners])

        def one_score(model, row):
            nonlocal score_errors
            try:
                front.score("markov", model, row,
                            conf=dict(MARKOV_SCORE_CONF))
            except (FleetError, OSError):
                with err_lock:
                    score_errors += 1

        t0 = time.perf_counter()
        for arrival, item in qload:
            _sleep_until(t0, arrival)
            if item[0] == "score":
                # open loop: the arrival never waits for the answer
                t = threading.Thread(target=one_score,
                                     args=(item[1], item[2]))
                t.start()
                threads.append(t)
            else:
                srv = servers[len(tickets) % hosts]
                tickets.append(srv.submit(request_from_json(item[1])))
        for t in threads:
            t.join(args.drain_timeout)
        for srv in servers:
            srv.drain(timeout=args.drain_timeout)
        wall = time.perf_counter() - t0
        served = sum(1 for t in tickets if _ok(t))
        snap = merge_snapshots([s.metrics_snapshot() for s in servers])
        hit_rate = front.router.affinity_hit_rate()
        front.close()
    finally:
        for lis in listeners:
            lis.stop()
        for srv in servers:
            srv.shutdown()
    scores = sum(1 for _a, item in qload if item[0] == "score")
    jobs = len(qload) - scores
    sh = _score_hist(snap)
    score_section = snap.get("score") or {}
    stats = score_section.get("stats", {})
    row = {"arm": "query", "hosts": hosts, "scores": scores,
           "jobs": jobs, "served_jobs": served,
           "score_errors": score_errors,
           "lost_requests": (jobs - len(tickets))
           + (scores - int(sh.get("count", 0)) - score_errors),
           "wall_s": round(wall, 2),
           "jobs_per_min": round(served / (wall / 60.0), 2),
           "scores_per_s": round(
               int(sh.get("count", 0)) / max(wall, 1e-9), 2),
           "score_p50_ms": round(sh.get("p50", 0.0), 3),
           "score_p99_ms": round(sh.get("p99", 0.0), 3),
           "score_predict_calls": int(stats.get("predict_calls", 0)),
           "score_model_loads": int(stats.get("model_loads", 0)),
           "score_affinity_hit_rate": round(hit_rate, 3)}
    row.update(_hist_stats(snap.get("hists", {}), "queue_wait_ms"))
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop Zipf/Poisson load against the job-server "
                    "fleet (module docstring)")
    ap.add_argument("--requests", type=int, default=40)
    ap.add_argument("--tenants", type=int, default=200)
    ap.add_argument("--corpora", type=int, default=8)
    ap.add_argument("--rows", type=int, default=5_000,
                    help="rows per corpus (default 5000)")
    ap.add_argument("--rate", type=float, default=5.0,
                    help="Poisson arrival rate, requests/s (default 5)")
    ap.add_argument("--zipf-s", type=float, default=1.1,
                    help="Zipf exponent of corpus popularity")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--budget-mb", type=float, default=3072.0)
    ap.add_argument("--arms", default="inproc,fleet",
                    help="comma list of inproc,solo,fleet,query")
    ap.add_argument("--score-fraction", type=float, default=0.8,
                    help="query arm: fraction of arrivals that are "
                         "scores (rest are job submits)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--drain-timeout", type=float, default=1800.0)
    args = ap.parse_args(argv)

    work = tempfile.mkdtemp(prefix="fleet_load_")
    corpora = []
    for i in range(args.corpora):
        path = os.path.join(work, f"corpus_{i:03d}.csv")
        write_corpus(path, args.rows, seed=100 + i)
        corpora.append(path)
    out_dir = os.path.join(work, "out")
    os.makedirs(out_dir, exist_ok=True)
    load = plan_load(args, corpora, out_dir)
    offered = args.requests / (load[-1][0] / 60.0)
    print(json.dumps({"offered_jobs_per_min": round(offered, 2),
                      "requests": args.requests,
                      "corpora": args.corpora, "tenants": args.tenants,
                      "zipf_s": args.zipf_s, "workdir": work}))
    rc = 0
    for arm in args.arms.split(","):
        arm = arm.strip()
        if arm == "inproc":
            row = run_inproc(args, load)
        elif arm == "solo":
            row = run_fleet(args, load, hosts=1)
        elif arm == "fleet":
            row = run_fleet(args, load, hosts=args.hosts)
        elif arm == "query":
            models = train_models(corpora, work)
            qload = plan_query_load(args, corpora, models, out_dir)
            row = run_query(args, qload, hosts=args.hosts)
        else:
            print(f"unknown arm {arm!r}", file=sys.stderr)
            return 2
        row["offered_jobs_per_min"] = round(offered, 2)
        if row["lost_requests"] > 0:
            rc = 1          # a dropped request: the soak contract broke
        print(json.dumps(row))
    return rc


if __name__ == "__main__":
    sys.exit(main())
