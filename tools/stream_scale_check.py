"""100M-row streaming-scale demonstration (VERDICT r4 #3/#6 'done when').

Runs, each in its own subprocess (so peak-RSS is per-job):
  1. mutualInformation over 100M real on-disk churn rows (~3.8GB CSV);
  2. markovStateTransitionModel (per-class) over 100M sequence rows (~2GB);
asserting host RSS stays O(block) — a whole-file ingest of either input
would need >2x the file size resident; the streamed jobs are asserted
under 3GB regardless of input size.

With --extra, also runs the multi-pass miners over the same 100M rows:
  3. frequentItemsApriori (one streamed scan per itemset length; per-k
     re-scans replay the pass-1 encoded-block cache);
  4. candidateGenerationWithSelfJoin / GSP (one scan per sequence length,
     same cache replay).

With --fused, additionally measures the scan-sharing executor: NB + MI +
discriminant over the churn corpus run three-jobs-sequential (three full
CSV scans) and then FUSED through runner.run_shared (ONE scan, three fold
sinks), recording the speedup ratio and asserting the fused outputs are
byte-identical to the sequential ones.

With --incremental, additionally measures the delta-scan driver: a copy
of the churn corpus is cold-seeded through runner.run_incremental (block
fingerprints + final fold-state checkpoint), ~1% of rows are appended,
and the incremental refresh is timed against a cold full re-scan of the
appended file — byte-identity asserted, speedup recorded as the
incremental anchor of the round's STREAM_SCALE record. Both sides run
in a fresh child process, so ~8s of interpreter+jit startup is priced
into each: the anchor is meaningful at the 10M/100M-row scales this
tool exists for (bench_scaling.incremental_tripwire is the in-process
>=5x gate at the 10M proxy).

Writes one JSON line per job and a summary to STREAM_SCALE_r05.json
(merged into any existing records, so a partial re-run never erases
previously recorded jobs). Works on CPU (pins the platform; the point is
ingest scale, not device speed — bench.py measures the TPU fold rates).

The summary also carries the two streaming-correctness audit columns —
chunk-invariance (graftlint --flow) and shard-merge/resume (graftlint
--merge) status, as validated/total strings — so every scale record
states whether the folds it measured are still deterministic AND still
a merge algebra. --no-audits skips them (they add a couple of minutes
of proxy-scale runs next to an hours-long 100M anchor).

With --server, additionally measures the resident job server: the same
3-tenant mixed-kind open-loop load as bench_scaling.server_tripwire
(churn profilers + sequence jobs + one duplicate request), served by an
in-process JobServer vs sequential one-job-at-a-time execution, in a
fresh child — recording jobs/min both ways, the speedup, p50/p99 queue
wait, the per-request Server:* counters (Server:QueueWaitMs /
Server:BatchSize / Server:CompileHits / Server:AdmissionHeldMs) the
served JobResults carry, the avenir-trace latency histograms (the
summary prints queue-wait p99 and per-chunk scan-latency p99 columns
from the streaming accumulators), and a metrics.json snapshot written
next to the served artifacts — the same file a resident server
refreshes live for `python -m avenir_tpu stats`.

With --shard, additionally measures the multi-process sharded driver
(avenir_tpu.dist.run_sharded): mutualInformation (Dataset-chunk family)
and markovStateTransitionModel (raw-byte-block family) re-run with the
scan split across 2 worker processes through the block ledger, in a
fresh child — byte-identity vs the solo anchors asserted, the
Shard:Blocks/StolenBlocks/DedupBlocks/MergeMs counters recorded as
columns, and the summary gains `shard_speedup` (solo anchor seconds /
sharded scan seconds per job; the scan clock starts at the workers' go
barrier, matching the solo children's boot-excluded convention). A
MINER anchor rides along: frequentItemsApriori re-runs sharded with
its per-k candidate rounds distributed through the level-namespaced
ledger (workers replay their own encoded-block caches), byte-identity
per itemset file asserted, the Shard:PerKRounds/PerKBlocks/
PerKSeconds counters recorded, and the summary gains
`shard_miner_speedup`.

With --sidecar, additionally measures the columnar sidecar: each anchor
family runs three passes in one child — a jit-warmup pass with the
sidecar disabled, a cold pass that packs a fresh sidecar next to the
corpus, and a warm pass that replays it parse-free — recording
`sidecar_speedup` (cold seconds / warm seconds, both jit-warm so the
ratio prices ONLY the parse elimination), the sidecar's bytes-on-disk
ratio vs the CSV, and the Sidecar:HitBlocks / Sidecar:DeltaBlocks
counters; warm output asserted byte-identical to cold.

Usage: python tools/stream_scale_check.py [--rows N_MILLION] [--extra]
                                          [--fused] [--incremental]
                                          [--server] [--shard]
                                          [--sidecar] [--no-audits]
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, ".")

ROWS_M = int(sys.argv[sys.argv.index("--rows") + 1]) \
    if "--rows" in sys.argv else 100
CHURN_CSV = f"/tmp/avenir_scale_churn_{ROWS_M}m.csv"
SEQ_CSV = f"/tmp/avenir_scale_seq_{ROWS_M}m.csv"
RSS_LIMIT_MB = 3072
# only the canonical 100M run updates the tracked record file; proxy
# sizes (e.g. --rows 10, the CPU acceptance proxy) write a sibling so a
# 10M run can never clobber the 100M rows the record is anchored to
RECORD = ("STREAM_SCALE_r05.json" if ROWS_M == 100
          else f"/tmp/avenir_stream_scale_{ROWS_M}m.json")

_CHILD = r'''
import json, os, resource, sys, time
sys.path.insert(0, ".")
import jax
jax.config.update("jax_platforms", "cpu")
from avenir_tpu.runner import run_job

job, conf_json, inp, out = sys.argv[1:5]
t0 = time.perf_counter()
res = run_job(job, json.loads(conf_json), [inp], out)
dt = time.perf_counter() - t0
rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
rows = next((v for k, v in res.counters.items() if "Records" in k), None)
print(json.dumps({"job": job, "seconds": round(dt, 1),
                  "rows": rows, "peak_rss_mb": round(rss, 1),
                  "counters": res.counters}))
'''


_CHILD_SHARED = r'''
import json, os, resource, sys, time
sys.path.insert(0, ".")
import jax
jax.config.update("jax_platforms", "cpu")
from avenir_tpu.runner import run_shared

specs_json, inp, outdir = sys.argv[1:4]
specs = [(job, conf, os.path.join(outdir, job))
         for job, conf in json.loads(specs_json)]
t0 = time.perf_counter()
res = run_shared(specs, [inp])
dt = time.perf_counter() - t0
rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
print(json.dumps({"job": "sharedScan", "jobs": sorted(res),
                  "seconds": round(dt, 1), "peak_rss_mb": round(rss, 1),
                  "outputs": sorted(p for r in res.values()
                                    for p in r.outputs)}))
'''


_CHILD_INCR = r'''
import json, os, resource, sys, time
sys.path.insert(0, ".")
import jax
jax.config.update("jax_platforms", "cpu")
from avenir_tpu.runner import run_incremental

job, conf_json, inp, out, state = sys.argv[1:6]
t0 = time.perf_counter()
res = run_incremental(job, json.loads(conf_json), [inp], out,
                      state_dir=state)
dt = time.perf_counter() - t0
rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
print(json.dumps({"job": job, "seconds": round(dt, 1),
                  "peak_rss_mb": round(rss, 1),
                  "counters": res.counters, "outputs": res.outputs}))
'''


_CHILD_SERVER = r'''
import json, os, resource, sys, time
sys.path.insert(0, ".")
import jax
jax.config.update("jax_platforms", "cpu")
from avenir_tpu.analysis.mem import _RssSampler
from avenir_tpu.runner import run_job
from avenir_tpu.server import JobRequest, JobServer
from bench_scaling import server_load

churn, seq, schema, outdir = sys.argv[1:5]
# the ONE canonical load table — the anchor must measure exactly the
# load bench_scaling.server_tripwire gates
load = server_load(churn, seq, schema)
# jit warmup on a newline-aligned head slice of each corpus so neither
# phase pays first-compile costs (the bench tripwire's own protocol)
warm_dir = os.path.join(outdir, "warm")
os.makedirs(warm_dir, exist_ok=True)
warm = {}
for corpus in {c for _t, _j, _cf, c, _tag in load}:
    with open(corpus, "rb") as fh:
        blob = fh.read(1 << 18)
    dst = os.path.join(warm_dir, os.path.basename(corpus))
    with open(dst, "wb") as fh:
        fh.write(blob[:blob.rfind(b"\n") + 1])
    warm[corpus] = dst
seen = set()
for _tenant, job, cf, corpus, tag in load:
    key = (job, json.dumps(cf, sort_keys=True))
    if key not in seen:
        seen.add(key)
        run_job(job, cf, [warm[corpus]], os.path.join(warm_dir, f"w_{tag}"))
# served phase FIRST, its RSS sampled in isolation: the sequential twin
# is deliberately unbudgeted and CPython RSS is sticky, so running it
# first would attribute ITS peak to the admission-controlled server
server = JobServer(state_root=os.path.join(outdir, "state"))
tickets = {tag: server.submit(JobRequest(
               job, cf, [corpus], os.path.join(outdir, f"srv_{tag}"),
               tenant=tenant))
           for tenant, job, cf, corpus, tag in load}
t0 = time.perf_counter()
with _RssSampler() as sampler:
    server.start()
    server.drain(timeout=7200)
t_srv = time.perf_counter() - t0
served = {tag: t.result(timeout=60) for tag, t in tickets.items()}
stats = server.stats()
# the live metrics surface at anchor scale: the snapshot a resident
# server would be renaming every few seconds, written once here so the
# record keeps the full histogram summaries (queue wait, admission
# hold, dispatch, chunk latency) next to the per-request counters
server.metrics_path = os.path.join(outdir, "metrics.json")
server.write_metrics()
hists = server.metrics_snapshot()["hists"]
server.shutdown()
t0 = time.perf_counter()
for tenant, job, cf, corpus, tag in load:
    run_job(job, cf, [corpus], os.path.join(outdir, f"seq_{tag}"))
t_seq = time.perf_counter() - t0
for tag, res in served.items():
    for pa in sorted(res.outputs):
        rel = os.path.relpath(pa, os.path.join(outdir, f"srv_{tag}"))
        pb = os.path.join(outdir, f"seq_{tag}")
        pb = pb if rel == "." else os.path.join(pb, rel)
        assert open(pa, "rb").read() == open(pb, "rb").read(), (pa, pb)
rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
waits = sorted(r.counters["Server:QueueWaitMs"] for r in served.values())
print(json.dumps({
    "job": "jobServer", "requests": len(load),
    "sequential_seconds": round(t_seq, 1),
    "served_seconds": round(t_srv, 1),
    "jobs_per_min_sequential": round(len(load) / (t_seq / 60.0), 2),
    "jobs_per_min_served": round(len(load) / (t_srv / 60.0), 2),
    "speedup": round(t_seq / max(t_srv, 1e-9), 2),
    "p50_queue_wait_ms": waits[len(waits) // 2],
    "p99_queue_wait_ms": waits[-1],
    "peak_rss_mb": round(rss, 1),
    "server_peak_rss_mb": round(sampler.peak_rss / (1 << 20), 1),
    "outputs_byte_identical": True,
    "server_counters": {tag: {k: v for k, v in r.counters.items()
                              if k.startswith("Server:")}
                        for tag, r in served.items()},
    "hists": hists,
    "stats": {k: v for k, v in stats.items() if v},
}))
'''


_CHILD_SHARDED = r'''
import json, os, resource, sys, time
sys.path.insert(0, ".")
import jax
jax.config.update("jax_platforms", "cpu")
from avenir_tpu.dist import run_sharded

job, conf_json, inp, out, procs = sys.argv[1:6]
t0 = time.perf_counter()
res = run_sharded(job, json.loads(conf_json), [inp], out,
                  procs=int(procs))
dt = time.perf_counter() - t0
rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
print(json.dumps({"job": job, "seconds": round(dt, 1),
                  "scan_seconds": res.counters["Shard:ScanSeconds"],
                  "peak_rss_mb": round(rss, 1),
                  "counters": res.counters, "outputs": res.outputs}))
'''


_CHILD_SIDECAR = r'''
import json, os, resource, shutil, sys, time
sys.path.insert(0, ".")
import jax
jax.config.update("jax_platforms", "cpu")
from avenir_tpu.runner import run_job

job, conf_json, inp, outdir = sys.argv[1:5]
conf = json.loads(conf_json)
prefix = next(iter(conf)).split(".", 1)[0]
scdir = os.path.join(outdir, "sidecar")
shutil.rmtree(scdir, ignore_errors=True)

def blobs(path):
    if os.path.isdir(path):
        return {f: open(os.path.join(path, f), "rb").read()
                for f in sorted(os.listdir(path))}
    with open(path, "rb") as fh:
        return {".": fh.read()}

# pass 0: jit warmup with the sidecar DISABLED, so the cold pass below
# times parsing, not first-compile — the speedup must price only the
# parse elimination
run_job(job, {**conf, prefix + ".stream.sidecar": "false"}, [inp],
        os.path.join(outdir, job + "_jitwarm"))
conf[prefix + ".stream.sidecar.dir"] = scdir
cold_out = os.path.join(outdir, job + "_cold")
t0 = time.perf_counter()
cold = run_job(job, conf, [inp], cold_out)
t_cold = time.perf_counter() - t0
warm_out = os.path.join(outdir, job + "_warm")
t0 = time.perf_counter()
warm = run_job(job, conf, [inp], warm_out)
t_warm = time.perf_counter() - t0
assert blobs(cold_out) == blobs(warm_out), "warm output != cold output"
assert cold.counters.get("Sidecar:DeltaBlocks", 0) > 0, cold.counters
assert warm.counters.get("Sidecar:HitBlocks", 0) > 0, warm.counters
sc_bytes = sum(os.path.getsize(os.path.join(r, f))
               for r, _d, fs in os.walk(scdir) for f in fs)
rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
print(json.dumps({
    "job": job, "cold_seconds": round(t_cold, 2),
    "warm_seconds": round(t_warm, 2),
    "sidecar_speedup": round(t_cold / max(t_warm, 1e-9), 2),
    "sidecar_bytes": sc_bytes,
    "bytes_on_disk_ratio": round(sc_bytes / os.path.getsize(inp), 3),
    "hit_blocks": warm.counters.get("Sidecar:HitBlocks"),
    "delta_blocks": cold.counters.get("Sidecar:DeltaBlocks"),
    "peak_rss_mb": round(rss, 1),
    "outputs_byte_identical": True}))
'''


def ensure_file(path, blob, reps):
    want = len(blob.encode()) * reps
    if os.path.exists(path) and os.path.getsize(path) == want:
        return
    with open(path + ".tmp", "w") as fh:
        for _ in range(reps):
            fh.write(blob)
    os.replace(path + ".tmp", path)


def run_child(job, conf, inp, out, incremental_state=None):
    env = dict(os.environ, AVENIR_SKIP_DEVICE_PROBE="1")
    argv = ([sys.executable, "-c", _CHILD_INCR, job, json.dumps(conf),
             inp, out, incremental_state] if incremental_state
            else [sys.executable, "-c", _CHILD, job, json.dumps(conf),
                  inp, out])
    proc = subprocess.run(argv,
                          capture_output=True, text=True, timeout=7200,
                          env=env)
    if proc.returncode != 0:
        raise RuntimeError(f"{job} failed: {proc.stderr[-500:]}")
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    # memory-oracle delta column: the runner attaches
    # Mem:PredictedPeakBytes (analysis/mem footprint model) next to the
    # measured Mem:PeakRSS, so every 100M anchor records the model's
    # error over time — the real-scale complement of the CI-scale
    # graftlint --mem band
    predicted = line.get("counters", {}).get("Mem:PredictedPeakBytes")
    if predicted:
        pred_mb = predicted / (1 << 20)
        line["predicted_peak_mb"] = round(pred_mb, 1)
        line["mem_model_delta_pct"] = round(
            100.0 * (line["peak_rss_mb"] - pred_mb) / pred_mb, 1)
    print(json.dumps(line), flush=True)
    assert line["peak_rss_mb"] < RSS_LIMIT_MB, \
        f"{job} RSS {line['peak_rss_mb']}MB not O(block)"
    return line


def residual_trend(job: str, inp: str) -> list:
    """The predicted-vs-measured RSS residual ratios the runner's
    always-on recording (runner._add_mem_counters -> avenir_tpu.tune)
    has accumulated for (job, corpus) — newest last. Every anchor run
    appends one, so across rounds this column shows whether the
    footprint model's real-scale error is drifting; [] when no profile
    exists (first round, or the store dir was cleaned)."""
    try:
        from avenir_tpu.tune import ProfileStore, corpus_digest, resolve_dir

        store = ProfileStore(resolve_dir(None, [inp]))
        prof = store.load(job, corpus_digest([inp]))
        if not prof:
            return []
        return [round(float(r["measured"]) / float(r["predicted"]), 3)
                for r in prof.get("residuals", [])
                if float(r.get("predicted", 0)) > 0]
    except Exception as e:                        # noqa: BLE001
        return [f"unavailable ({type(e).__name__})"]


def audit_status(mode: str) -> str:
    """"validated/total" of one graftlint streaming audit (--flow
    chunk-invariance or --merge shard-merge/resume), run in a child so
    this process stays jax-free; "unavailable (...)" instead of a raise
    because a broken auditor must not block recording a finished
    100M-row measurement — the bench tripwire is the hard gate."""
    key, flag = (("invariance_audit", "--flow") if mode == "invariance"
                 else ("merge_audit", "--merge"))
    verdict = ("invariance_validated" if mode == "invariance"
               else "merge_validated")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "graftlint.py"),
             flag, "--json"],
            capture_output=True, text=True, timeout=1800,
            env=dict(os.environ, AVENIR_SKIP_DEVICE_PROBE="1"))
        rows = json.loads(proc.stdout)[key]
        ok = sum(1 for r in rows if r[verdict])
        return f"{ok}/{len(rows)}"
    except Exception as e:                        # noqa: BLE001
        return f"unavailable ({type(e).__name__})"


def main():
    import numpy as np

    jax_free_env = dict(os.environ)  # generation needs no jax at all
    del jax_free_env

    from avenir_tpu.data import churn_schema, generate_churn

    t0 = time.perf_counter()
    schema_path = "/tmp/avenir_scale_churn.json"
    churn_schema().save(schema_path)
    churn_blob = generate_churn(100_000, seed=9, as_csv=True)
    ensure_file(CHURN_CSV, churn_blob, ROWS_M * 10)

    rng = np.random.default_rng(12)
    states = ["L", "M", "H"]
    lines = []
    for i in range(100_000):
        up = i % 2 == 0
        s, toks = 1, []
        for _ in range(6):
            p = [0.1, 0.3, 0.6] if up else [0.6, 0.3, 0.1]
            s = int(np.clip(s + rng.choice([-1, 0, 1], p=p), 0, 2))
            toks.append(states[s])
        lines.append(f"c{i},{'T' if up else 'F'}," + ",".join(toks))
    ensure_file(SEQ_CSV, "\n".join(lines) + "\n", ROWS_M * 10)
    print(f"# inputs ready in {time.perf_counter()-t0:.0f}s: "
          f"{os.path.getsize(CHURN_CSV)>>20}MB churn, "
          f"{os.path.getsize(SEQ_CSV)>>20}MB sequences", flush=True)

    results = {"rows": ROWS_M * 1_000_000,
               "churn_csv_mb": os.path.getsize(CHURN_CSV) >> 20,
               "seq_csv_mb": os.path.getsize(SEQ_CSV) >> 20,
               "rss_limit_mb": RSS_LIMIT_MB}
    results["mutualInformation"] = run_child(
        "mutualInformation",
        {"mut.feature.schema.file.path": schema_path,
         "mut.mutual.info.score.algorithms": "mutual.info.maximization"},
        CHURN_CSV, "/tmp/avenir_scale_mi.txt")
    results["markovStateTransitionModel"] = run_child(
        "markovStateTransitionModel",
        {"mst.model.states": "L,M,H", "mst.class.label.field.ord": "1",
         "mst.skip.field.count": "2", "mst.class.labels": "T,F"},
        SEQ_CSV, "/tmp/avenir_scale_mst.txt")
    if "--extra" in sys.argv:
        # the multi-pass miners: one streamed scan per k over the same
        # 100M-row file (transactions reuse the sequence rows: tokens
        # after the meta fields are the items / the sequence)
        results["frequentItemsApriori"] = run_child(
            "frequentItemsApriori",
            {"fia.support.threshold": "0.3", "fia.item.set.length": "2",
             "fia.skip.field.count": "2",
             "fia.stream.block.size.mb": "64"},
            SEQ_CSV, "/tmp/avenir_scale_fia")
        results["candidateGenerationWithSelfJoin"] = run_child(
            "candidateGenerationWithSelfJoin",
            {"cgs.support.threshold": "0.3", "cgs.item.set.length": "2",
             "cgs.skip.field.count": "2",
             "cgs.stream.block.size.mb": "64"},
            SEQ_CSV, "/tmp/avenir_scale_gsp")
    if "--fused" in sys.argv:
        # scan-sharing A/B: the three churn profilers sequentially (one
        # full CSV scan EACH) vs fused through run_shared (ONE scan,
        # three fold sinks); outputs must be byte-identical
        jobs3 = [
            ("bayesianDistr",
             {"bad.feature.schema.file.path": schema_path}, "bad"),
            ("mutualInformation",
             {"mut.feature.schema.file.path": schema_path,
              "mut.mutual.info.score.algorithms":
                  "mutual.info.maximization"}, "mut"),
            ("fisherDiscriminant",
             {"fid.feature.schema.file.path": schema_path}, "fid"),
        ]
        seq_s, seq_outs = 0.0, []
        for job, conf, _p in jobs3:
            line = run_child(job, conf, CHURN_CSV,
                             f"/tmp/avenir_scale_seq_{job}.txt")
            seq_s += line["seconds"]
            results[f"sequential_{job}"] = line
            seq_outs.append(f"/tmp/avenir_scale_seq_{job}.txt")
        outdir = "/tmp/avenir_scale_fused"
        os.makedirs(outdir, exist_ok=True)
        env = dict(os.environ, AVENIR_SKIP_DEVICE_PROBE="1")
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD_SHARED,
             json.dumps([(j, c) for j, c, _p in jobs3]), CHURN_CSV, outdir],
            capture_output=True, text=True, timeout=7200, env=env)
        if proc.returncode != 0:
            raise RuntimeError(f"fused scan failed: {proc.stderr[-500:]}")
        fused = json.loads(proc.stdout.strip().splitlines()[-1])
        print(json.dumps(fused), flush=True)
        assert fused["peak_rss_mb"] < RSS_LIMIT_MB
        for job, _conf, _p in jobs3:
            seq_out = f"/tmp/avenir_scale_seq_{job}.txt"
            fused_out = os.path.join(outdir, job)
            with open(seq_out, "rb") as fa, open(fused_out, "rb") as fb:
                assert fa.read() == fb.read(), \
                    f"fused output {fused_out} != sequential {seq_out}"
        fused["sequential_seconds"] = round(seq_s, 1)
        fused["speedup"] = round(seq_s / fused["seconds"], 2)
        fused["outputs_byte_identical"] = True
        results["sharedScan"] = fused
    if "--incremental" in sys.argv:
        # delta-scan anchor: cold-seed the driver's state on a COPY of
        # the churn corpus (the shared cached corpus file must keep its
        # exact size for ensure_file), append ~1% of rows, then time
        # incremental refresh vs cold full re-scan — byte-identical
        import shutil

        base = CHURN_CSV.replace(".csv", "_incr.csv")
        shutil.copyfile(CHURN_CSV, base)
        state = f"/tmp/avenir_scale_incr_state_{ROWS_M}m"
        shutil.rmtree(state, ignore_errors=True)
        conf = {"mut.feature.schema.file.path": schema_path,
                "mut.mutual.info.score.algorithms":
                    "mutual.info.maximization"}
        seed = run_child("mutualInformation", conf, base,
                         "/tmp/avenir_scale_incr_seed.txt",
                         incremental_state=state)
        from avenir_tpu.data import generate_churn as _gen

        append_blob = _gen(100_000, seed=10, as_csv=True)
        with open(base, "a") as fh:
            for _ in range(max(ROWS_M // 10, 1)):   # ~1% of the corpus
                fh.write(append_blob)
        cold = run_child("mutualInformation", conf, base,
                         "/tmp/avenir_scale_incr_cold.txt")
        incr = run_child("mutualInformation", conf, base,
                         "/tmp/avenir_scale_incr_refresh.txt",
                         incremental_state=state)
        with open("/tmp/avenir_scale_incr_cold.txt", "rb") as fa, \
                open("/tmp/avenir_scale_incr_refresh.txt", "rb") as fb:
            assert fa.read() == fb.read(), \
                "incremental refresh output != cold full re-scan"
        results["incremental"] = {
            "seed_seconds": seed["seconds"],
            "cold_seconds": cold["seconds"],
            "incremental_seconds": incr["seconds"],
            "speedup": round(cold["seconds"]
                             / max(incr["seconds"], 0.1), 2),
            "skipped_bytes": incr["counters"].get("Resume:SkippedBytes"),
            "hit_blocks": incr["counters"].get("Cache:HitBlocks"),
            "delta_blocks": incr["counters"].get("Cache:DeltaBlocks"),
            "outputs_byte_identical": True,
        }
        os.remove(base)
    if "--shard" in sys.argv:
        # sharded-scan A/B: the two anchor families re-run with the
        # scan split across 2 worker processes (block ledger, plan-
        # ordered merge), in a fresh child; byte-identity asserted
        # against the solo anchors above, shard counters recorded
        env = dict(os.environ, AVENIR_SKIP_DEVICE_PROBE="1")
        shard_jobs = [
            ("mutualInformation",
             {"mut.feature.schema.file.path": schema_path,
              "mut.mutual.info.score.algorithms":
                  "mutual.info.maximization"},
             CHURN_CSV, "/tmp/avenir_scale_mi_sharded.txt",
             "/tmp/avenir_scale_mi.txt"),
            ("markovStateTransitionModel",
             {"mst.model.states": "L,M,H",
              "mst.class.label.field.ord": "1",
              "mst.skip.field.count": "2", "mst.class.labels": "T,F"},
             SEQ_CSV, "/tmp/avenir_scale_mst_sharded.txt",
             "/tmp/avenir_scale_mst.txt"),
        ]
        for job, conf, inp, out, solo_out in shard_jobs:
            proc = subprocess.run(
                [sys.executable, "-c", _CHILD_SHARDED, job,
                 json.dumps(conf), inp, out, "2"],
                capture_output=True, text=True, timeout=7200, env=env)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"sharded {job} failed: {proc.stderr[-500:]}")
            line = json.loads(proc.stdout.strip().splitlines()[-1])
            print(json.dumps(line), flush=True)
            assert line["peak_rss_mb"] < RSS_LIMIT_MB, \
                f"sharded {job} RSS {line['peak_rss_mb']}MB not O(block)"
            with open(solo_out, "rb") as fa, open(out, "rb") as fb:
                assert fa.read() == fb.read(), \
                    f"sharded {job} output != solo anchor {solo_out}"
            line["outputs_byte_identical"] = True
            line["solo_seconds"] = results[job]["seconds"]
            line["shard_speedup"] = round(
                results[job]["seconds"]
                / max(line["scan_seconds"], 1e-9), 2)
            results[f"sharded_{job}"] = line
        # miner anchor: the distributed per-k rounds at anchor scale —
        # solo fia (the --extra anchor when it already ran this
        # invocation, a fresh child otherwise) vs run_sharded;
        # byte-identity per itemset file, the Shard:PerK* counters and
        # the shard_miner_speedup column recorded
        fia_conf = {"fia.support.threshold": "0.3",
                    "fia.item.set.length": "2",
                    "fia.skip.field.count": "2",
                    "fia.stream.block.size.mb": "64"}
        solo_fia_out = "/tmp/avenir_scale_fia"
        if "frequentItemsApriori" not in results:
            results["frequentItemsApriori"] = run_child(
                "frequentItemsApriori", fia_conf, SEQ_CSV, solo_fia_out)
        out = "/tmp/avenir_scale_fia_sharded"
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD_SHARDED,
             "frequentItemsApriori", json.dumps(fia_conf), SEQ_CSV,
             out, "2"],
            capture_output=True, text=True, timeout=7200, env=env)
        if proc.returncode != 0:
            raise RuntimeError(
                f"sharded miner failed: {proc.stderr[-500:]}")
        line = json.loads(proc.stdout.strip().splitlines()[-1])
        print(json.dumps(line), flush=True)
        assert line["peak_rss_mb"] < RSS_LIMIT_MB, \
            f"sharded miner RSS {line['peak_rss_mb']}MB not O(block)"
        assert line["counters"].get("Shard:PerKRounds", 0) >= 1, \
            "sharded miner ran zero distributed per-k rounds"
        solo_files = sorted(os.path.join(solo_fia_out, f)
                            for f in os.listdir(solo_fia_out))
        assert len(solo_files) == len(line["outputs"]), \
            (solo_files, line["outputs"])
        for pa, pb in zip(solo_files, sorted(line["outputs"])):
            with open(pa, "rb") as fa, open(pb, "rb") as fb:
                assert fa.read() == fb.read(), \
                    f"sharded miner output {pb} != solo {pa}"
        line["outputs_byte_identical"] = True
        line["solo_seconds"] = results["frequentItemsApriori"]["seconds"]
        line["shard_speedup"] = round(
            line["solo_seconds"] / max(line["scan_seconds"], 1e-9), 2)
        results["sharded_frequentItemsApriori"] = line
    if "--sidecar" in sys.argv:
        # columnar-sidecar A/B: cold pack (parse + write sidecar) vs
        # warm replay (parse-free) per anchor family, in one child with
        # a jit-warmup pass so the ratio prices only the parse work
        import shutil

        outdir = f"/tmp/avenir_scale_sidecar_{ROWS_M}m"
        shutil.rmtree(outdir, ignore_errors=True)
        os.makedirs(outdir, exist_ok=True)
        env = dict(os.environ, AVENIR_SKIP_DEVICE_PROBE="1")
        sc_jobs = [
            ("mutualInformation",
             {"mut.feature.schema.file.path": schema_path,
              "mut.mutual.info.score.algorithms":
                  "mutual.info.maximization"},
             CHURN_CSV),
            ("markovStateTransitionModel",
             {"mst.model.states": "L,M,H",
              "mst.class.label.field.ord": "1",
              "mst.skip.field.count": "2", "mst.class.labels": "T,F"},
             SEQ_CSV),
        ]
        for job, conf, inp in sc_jobs:
            proc = subprocess.run(
                [sys.executable, "-c", _CHILD_SIDECAR, job,
                 json.dumps(conf), inp, outdir],
                capture_output=True, text=True, timeout=7200, env=env)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"sidecar {job} failed: {proc.stderr[-500:]}")
            line = json.loads(proc.stdout.strip().splitlines()[-1])
            print(json.dumps(line), flush=True)
            assert line["peak_rss_mb"] < RSS_LIMIT_MB, \
                f"sidecar {job} RSS {line['peak_rss_mb']}MB not O(block)"
            results[f"sidecar_{job}"] = line
    if "--server" in sys.argv:
        # resident-server anchor: the 3-tenant mixed-kind open-loop
        # load served by an in-process JobServer vs one-job-at-a-time,
        # in a fresh child (so both sides price the same startup), with
        # byte-identity asserted per served artifact and the Server:*
        # counters recorded per request
        outdir = f"/tmp/avenir_scale_server_{ROWS_M}m"
        import shutil

        shutil.rmtree(outdir, ignore_errors=True)
        os.makedirs(outdir, exist_ok=True)
        env = dict(os.environ, AVENIR_SKIP_DEVICE_PROBE="1")
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD_SERVER,
             CHURN_CSV, SEQ_CSV, schema_path, outdir],
            capture_output=True, text=True, timeout=7200, env=env)
        if proc.returncode != 0:
            raise RuntimeError(f"server load failed: {proc.stderr[-800:]}")
        line = json.loads(proc.stdout.strip().splitlines()[-1])
        print(json.dumps(line), flush=True)
        # the served phase is the admission-controlled one; the lifetime
        # peak_rss_mb (also recorded) includes the unbudgeted sequential
        # twin and would assert the wrong phase
        assert line["server_peak_rss_mb"] < RSS_LIMIT_MB, \
            f"server RSS {line['server_peak_rss_mb']}MB not admission-bounded"
        results["jobServer"] = line
    merged = {}
    if os.path.exists(RECORD):
        try:
            merged = json.load(open(RECORD))
        except ValueError:
            merged = {}
    merged.update(results)
    with open(RECORD, "w") as fh:
        json.dump(merged, fh, indent=1)
    summary = {"stream_scale": "done",
               "mi_rows_per_sec": round(
                   results["rows"]
                   / results["mutualInformation"]["seconds"], 1),
               "mst_rows_per_sec": round(
                   results["rows"]
                   / results["markovStateTransitionModel"]["seconds"], 1)}
    # the miners carry their own Basic:RowsPerSec tripwire counter now —
    # surface it so a throughput regression shows in this summary line too
    for key, job in (("fia_rows_per_sec", "frequentItemsApriori"),
                     ("gsp_rows_per_sec", "candidateGenerationWithSelfJoin")):
        if job in results:
            summary[key] = results[job]["counters"].get("Basic:RowsPerSec")
    # predicted-vs-measured memory column per streamed job (model error
    # at real scale; the record file keeps the full per-job numbers)
    summary["mem_model_delta_pct"] = {
        job: line["mem_model_delta_pct"] for job, line in results.items()
        if isinstance(line, dict) and "mem_model_delta_pct" in line}
    # the residual TREND next to the single-run delta: every anchor's
    # predicted-vs-measured pair lands in the per-(job, corpus) autotune
    # profile store, so this column shows the model error across rounds
    # (the history the tuner's admission-correction factor learns from)
    summary["mem_residual_trend"] = {
        job: residual_trend(job, inp) for job, inp in
        (("mutualInformation", CHURN_CSV),
         ("markovStateTransitionModel", SEQ_CSV))}
    if "sharedScan" in results:
        summary["shared_scan_speedup"] = results["sharedScan"]["speedup"]
    # the incremental-speedup column: O(delta) refresh vs O(corpus)
    # re-scan after a ~1% append, byte-identity already asserted above
    if "incremental" in results:
        summary["incremental_speedup"] = results["incremental"]["speedup"]
    # the sharded-scan columns: solo anchor vs 2-process sharded scan
    # per family, plus the Shard:* ledger counters the sharded
    # JobResults carry (blocks / stolen / dedup / merge ms)
    shard_cols = {job: line for job, line in results.items()
                  if job.startswith("sharded_")}
    if shard_cols:
        summary["shard_speedup"] = {
            job[len("sharded_"):]: line["shard_speedup"]
            for job, line in shard_cols.items()}
        summary["shard_counters"] = {
            job[len("sharded_"):]: {
                k: line["counters"][k] for k in
                ("Shard:Blocks", "Shard:StolenBlocks",
                 "Shard:DedupBlocks", "Shard:MergeMs",
                 "Shard:PerKRounds", "Shard:PerKBlocks",
                 "Shard:PerKSeconds")
                if k in line.get("counters", {})}
            for job, line in shard_cols.items()}
        # the miner anchor's own column: the distributed per-k phase
        # is the throughput this PR exists for
        miner = shard_cols.get("sharded_frequentItemsApriori")
        if miner is not None:
            summary["shard_miner_speedup"] = miner["shard_speedup"]
    # the sidecar columns: parse-free warm replay vs cold pack per
    # family, the on-disk cost of the cache, and the hit/delta block
    # counters the two JobResults carried
    sc_cols = {job[len("sidecar_"):]: line for job, line in results.items()
               if job.startswith("sidecar_")}
    if sc_cols:
        summary["sidecar_speedup"] = {
            job: line["sidecar_speedup"] for job, line in sc_cols.items()}
        summary["sidecar_bytes_ratio"] = {
            job: line["bytes_on_disk_ratio"]
            for job, line in sc_cols.items()}
        summary["sidecar_counters"] = {
            job: {"hit_blocks": line["hit_blocks"],
                  "delta_blocks": line["delta_blocks"]}
            for job, line in sc_cols.items()}
    # the served-jobs/min column: batched multi-tenant serving vs
    # one-job-at-a-time, plus the served requests' Server:* counters
    if "jobServer" in results:
        summary["server_speedup"] = results["jobServer"]["speedup"]
        summary["server_jobs_per_min"] = \
            results["jobServer"]["jobs_per_min_served"]
        summary["server_p99_queue_wait_ms"] = \
            results["jobServer"]["p99_queue_wait_ms"]
        # the avenir-trace histogram columns: queue-wait p99 from the
        # server's streaming accumulator (not the sorted per-request
        # scalars above — same data, distribution view) and per-chunk
        # scan latency p99 from the process-global obs histogram
        hists = results["jobServer"].get("hists", {})
        for col, name in (("server_hist_queue_wait_p99_ms",
                           "queue_wait_ms"),
                          ("server_hist_admission_held_p99_ms",
                           "admission_held_ms"),
                          ("server_chunk_latency_p99_ms",
                           "chunk_latency_ms")):
            if name in hists:
                summary[col] = hists[name]["p99"]
    # the two streaming-correctness columns, side by side: the folds the
    # numbers above measured are chunk-layout-invariant AND a merge
    # algebra (shard-merge + checkpoint-resume byte-identical)
    if "--no-audits" not in sys.argv:
        summary["invariance_audit"] = audit_status("invariance")
        summary["merge_audit"] = audit_status("merge")
        merged.update({"invariance_audit": summary["invariance_audit"],
                       "merge_audit": summary["merge_audit"]})
        with open(RECORD, "w") as fh:
            json.dump(merged, fh, indent=1)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
