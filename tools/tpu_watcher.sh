#!/bin/bash
# Probe-and-drain loop for a flapping TPU tunnel: every pass runs
# `bench.py --drain`, which probes the backend (120s hard timeout) and
# measures every not-yet-banked section, EACH in its own subprocess with
# its own timeout, banking every success to TPU_BANK_r05.json
# immediately. A flap mid-pass therefore costs one section, not the
# round (round 4 lost all its numbers to one in-process hang).
#
# Exit codes from --drain: 0 = all sections banked (stop); 2 = tunnel
# down or a section hung (keep probing indefinitely — outages last
# hours); 1 = a section failed crisply for a non-tunnel reason (e.g. a
# Mosaic lowering bug). Crisp failures are deterministic and cheap, so
# give up after 5 of them WITHOUT forward progress in between — a pass
# that banked something new resets the strike count.
set -o pipefail
cd /root/repo
hard_fails=0
last_banked=-1
while true; do
  python bench.py --drain >> tpu_watch_r05.log 2>&1
  rc=$?
  banked=$(python -c "
import json
try: print(sum(1 for v in json.load(open('TPU_BANK_r05.json')).values() if v.get('ok')))
except Exception: print(0)")
  echo "drain exit ${rc} (banked ${banked}) at $(date -u +%H:%M:%S)" >> tpu_watch_r05.log
  [ "$rc" -eq 0 ] && break
  if [ "$banked" -gt "$last_banked" ]; then
    hard_fails=0
  fi
  last_banked=$banked
  if [ "$rc" -eq 1 ]; then
    hard_fails=$((hard_fails + 1))
    if [ "$hard_fails" -ge 5 ]; then
      echo "GIVING UP after ${hard_fails} no-progress crisp failures at $(date -u +%H:%M:%S)" >> tpu_watch_r05.log
      exit 1
    fi
  fi
  sleep 180
done
echo "BANK COMPLETE at $(date -u +%H:%M:%S)" >> tpu_watch_r05.log
