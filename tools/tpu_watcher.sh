#!/bin/bash
# Probe-and-drain loop for a flapping TPU tunnel: every pass runs
# `bench.py --drain`, which probes the backend (120s hard timeout) and
# measures every not-yet-banked section, EACH in its own subprocess with
# its own timeout, banking every success to TPU_BANK_r05.json
# immediately. A flap mid-pass therefore costs one section, not the
# round (round 4 lost all its numbers to one in-process hang).
#
# Exit codes from --drain: 0 = all sections banked (stop); 2 = tunnel
# down (keep probing indefinitely — outages last hours); 1 = a section
# failed for a non-tunnel reason (retry a bounded number of times: a
# flap can kill the last section of a pass and still exit 1, but a
# DETERMINISTIC failure, e.g. a Mosaic lowering bug, would otherwise
# re-run the same expensive section every 3 min forever).
set -o pipefail
cd /root/repo
hard_fails=0
while true; do
  python bench.py --drain >> tpu_watch_r05.log 2>&1
  rc=$?
  echo "drain exit ${rc} at $(date -u +%H:%M:%S)" >> tpu_watch_r05.log
  [ "$rc" -eq 0 ] && break
  if [ "$rc" -eq 1 ]; then
    hard_fails=$((hard_fails + 1))
    if [ "$hard_fails" -ge 5 ]; then
      echo "GIVING UP after ${hard_fails} non-tunnel failures at $(date -u +%H:%M:%S)" >> tpu_watch_r05.log
      exit 1
    fi
  fi
  sleep 180
done
echo "BANK COMPLETE at $(date -u +%H:%M:%S)" >> tpu_watch_r05.log
