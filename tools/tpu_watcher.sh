#!/bin/bash
# Probe the TPU tunnel every 5 min; the moment it is up, run the full
# validation queue (fused kernel, kernel sweep, reworked bench sections,
# whole bench.py) and bank the evidence in tpu_queue_r05.log.
set -o pipefail
cd /root/repo
while true; do
  if python -c "
from __graft_entry__ import _accelerator_reachable
import sys
sys.exit(0 if _accelerator_reachable(90) else 1)
" 2>/dev/null; then
    echo "=== TUNNEL UP at $(date -u +%H:%M:%S) — running validation queue ===" | tee -a tpu_queue_r05.log
    python tools/tpu_validation_queue.py --full 2>&1 | tee -a tpu_queue_r05.log
    rc=${PIPESTATUS[0]}
    echo "=== QUEUE EXIT ${rc} at $(date -u +%H:%M:%S) ===" | tee -a tpu_queue_r05.log
    break
  fi
  echo "probe: tunnel down at $(date -u +%H:%M:%S)" >> tpu_watch_r05.log
  sleep 300
done
