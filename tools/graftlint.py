#!/usr/bin/env python
"""graftlint launcher for source checkouts (no install needed):

    python tools/graftlint.py avenir_tpu/ [--json] [--baseline FILE]
    python tools/graftlint.py --ir [--json]     # kernel-manifest IR audit
    python tools/graftlint.py --flow [--json]   # concurrency + invariance
    python tools/graftlint.py --mem [--json]    # footprint rules + audit
    python tools/graftlint.py --merge [--json]  # merge algebra + audit
    python tools/graftlint.py --proto [--json]  # protocol + crash audit
    python tools/graftlint.py --race [--json]   # race rules + interleavings
    python tools/graftlint.py --keys [--json]   # key rules + perturbations
    python tools/graftlint.py --all [--json]    # all eight tiers, worst-of
    python tools/graftlint.py --all --parallel  # same, tiers as subprocesses

A failing --race schedule prints a replayable trace; replay it with
``python tools/graftlint.py --race --schedule <site>:<digits>``.

Same entry point as the `graftlint` console script. Exit codes: 0 clean,
1 findings/stale/parse errors, 2 usage-or-trace errors. See
docs/graftlint.md for the rule catalog and allowlisting policy."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avenir_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
