"""Distributed algorithm kernels over 2-D (data x model) meshes.

The reference distributes KNN by materializing all-pairs distances through a
MapReduce shuffle (sifarish + knn.sh pipeline). The TPU-native form shards
the *query* rows over the 'data' mesh axis and the *train* rows over the
'model' axis: each device computes a local streaming top-k against its train
shard, then an all_gather over 'model' merges the per-shard candidate sets —
k*P candidates per query instead of n_train, so the ICI traffic is tiny.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from avenir_tpu.ops.distance import pairwise_distance
from avenir_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def distributed_topk_fn(
    mesh: Mesh,
    k: int,
    metric: str = "manhattan",
):
    """Build a jitted distributed top-k: queries sharded over 'data', train
    rows sharded over 'model' (replicated if the mesh has no model axis).

    Returned fn(q_num, t_num, t_labels) -> (dist [nq, k], labels [nq, k])
    with q row-sharded and outputs row-sharded the same way. Numeric
    features only for now; route mixed categorical data through
    NeighborIndex on a single chip or encode categoricals numerically.
    """
    has_model = MODEL_AXIS in mesh.axis_names

    def kernel(q_num, t_num, t_labels):
        # local block: all queries in my data shard vs my train shard
        d = pairwise_distance(q_num, t_num, metric=metric)
        loc_d, loc_i = lax.top_k(-d, k)
        loc_d = -loc_d
        loc_lab = jnp.take(t_labels, loc_i)                     # [nq_loc, k]
        if has_model:
            # merge candidate sets across train shards: [P*k] per query
            all_d = lax.all_gather(loc_d, MODEL_AXIS, axis=1, tiled=True)
            all_lab = lax.all_gather(loc_lab, MODEL_AXIS, axis=1, tiled=True)
            neg, pos = lax.top_k(-all_d, k)
            return -neg, jnp.take_along_axis(all_lab, pos, axis=1)
        return loc_d, loc_lab

    in_specs = (
        P(DATA_AXIS, None),
        P(MODEL_AXIS, None) if has_model else P(),
        P(MODEL_AXIS) if has_model else P(),
    )
    out_specs = (P(DATA_AXIS, None), P(DATA_AXIS, None))
    return jax.jit(
        jax.shard_map(kernel, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
    )


def distributed_nb_train_fn(mesh: Mesh, num_classes: int, bmax: int):
    """Build a jitted mesh-wide Naive Bayes sufficient-stat step: row shards
    count locally (one-hot einsum on the MXU), psum over 'data' (and 'model'
    if present, so every device holds the global counts)."""
    axes = tuple(a for a in (DATA_AXIS, MODEL_AXIS) if a in mesh.axis_names)

    def kernel(codes, labels, w):
        oh_k = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32) * w[:, None]
        oh_b = jax.nn.one_hot(codes, bmax, dtype=jnp.float32)
        post = jnp.einsum("nk,nfb->fkb", oh_k, oh_b)
        cls = oh_k.sum(axis=0)
        return (
            lax.psum(post, axes),
            lax.psum(cls, axes),
        )

    row_spec = P(axes)  # rows sharded over all mesh axes jointly
    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(row_spec, row_spec, row_spec),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )
