"""Distributed algorithm kernels over 2-D (data x model) meshes.

The reference distributes KNN by materializing all-pairs distances through a
MapReduce shuffle (sifarish + knn.sh pipeline). The TPU-native form shards
the *query* rows over the 'data' mesh axis and the *train* rows over the
'model' axis: each device computes a local streaming top-k against its train
shard, then an all_gather over 'model' merges the per-shard candidate sets —
k*P candidates per query instead of n_train, so the ICI traffic is tiny.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from avenir_tpu.ops.distance import pairwise_distance
from avenir_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, shard_map


def distributed_topk_fn(
    mesh: Mesh,
    k: int,
    metric: str = "manhattan",
):
    """Build a jitted distributed top-k: queries sharded over 'data', train
    rows sharded over 'model' (replicated if the mesh has no model axis).

    Returned fn(q_num, t_num, t_labels) -> (dist [nq, k], labels [nq, k])
    with q row-sharded and outputs row-sharded the same way. Numeric
    features only for now; route mixed categorical data through
    NeighborIndex on a single chip or encode categoricals numerically.
    """
    has_model = MODEL_AXIS in mesh.axis_names

    def kernel(q_num, t_num, t_labels):
        # local block: all queries in my data shard vs my train shard
        d = pairwise_distance(q_num, t_num, metric=metric)
        loc_d, loc_i = lax.top_k(-d, k)
        loc_d = -loc_d
        loc_lab = jnp.take(t_labels, loc_i)                     # [nq_loc, k]
        if has_model:
            # merge candidate sets across train shards: [P*k] per query
            all_d = lax.all_gather(loc_d, MODEL_AXIS, axis=1, tiled=True)
            all_lab = lax.all_gather(loc_lab, MODEL_AXIS, axis=1, tiled=True)
            neg, pos = lax.top_k(-all_d, k)
            return -neg, jnp.take_along_axis(all_lab, pos, axis=1)
        return loc_d, loc_lab

    in_specs = (
        P(DATA_AXIS, None),
        P(MODEL_AXIS, None) if has_model else P(),
        P(MODEL_AXIS) if has_model else P(),
    )
    out_specs = (P(DATA_AXIS, None), P(DATA_AXIS, None))
    return jax.jit(
        shard_map(kernel, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


def distributed_nb_train_fn(mesh: Mesh, num_classes: int, bmax: int):
    """Build a jitted mesh-wide Naive Bayes sufficient-stat step: row shards
    count locally (one-hot einsum on the MXU), psum over 'data' (and 'model'
    if present, so every device holds the global counts)."""
    axes = tuple(a for a in (DATA_AXIS, MODEL_AXIS) if a in mesh.axis_names)

    def kernel(codes, labels, w):
        oh_k = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32) * w[:, None]
        oh_b = jax.nn.one_hot(codes, bmax, dtype=jnp.float32)
        post = jnp.einsum("nk,nfb->fkb", oh_k, oh_b)
        cls = oh_k.sum(axis=0)
        return (
            lax.psum(post, axes),
            lax.psum(cls, axes),
        )

    row_spec = P(axes)  # rows sharded over all mesh axes jointly
    return jax.jit(
        shard_map(
            kernel,
            mesh=mesh,
            in_specs=(row_spec, row_spec, row_spec),
            out_specs=(P(), P()),
        )
    )


def distributed_tree_level_fn(mesh: Mesh, n_leaves: int, n_splits: int,
                              smax: int, num_classes: int):
    """Build a jitted mesh-wide tree-level histogram step: every row shard
    computes its [L, NS, S, K] class-histogram block locally (the
    segment_sum that replaces one whole MR tree level, SURVEY §3.4), then a
    psum over the mesh replicates the global histogram — the host picks
    splits from a tensor that is tiny regardless of row count."""
    from avenir_tpu.models.tree import _level_histogram

    axes = tuple(a for a in (DATA_AXIS, MODEL_AXIS) if a in mesh.axis_names)

    def kernel(leaf_id, seg_matrix, labels, weights):
        h = _level_histogram(leaf_id, seg_matrix, labels, weights,
                             n_leaves, n_splits, smax, num_classes)
        return lax.psum(h, axes)

    row = P(axes)
    return jax.jit(
        shard_map(kernel, mesh=mesh,
                      in_specs=(row, row, row, row), out_specs=P())
    )


def distributed_lr_step_fn(mesh: Mesh, learning_rate: float = 1.0):
    """Build a jitted data-parallel logistic-regression step: per-shard
    gradient halves (regress._lr_grad, the same core as the single-device
    step), psum'd so every device applies the identical update (the
    reference's mapper-aggregate + single reducer, SURVEY §3.6, as one
    collective). Unlike _lr_step, rows carry weights and the normalizer is
    the weight total — zero-weight padding rows drop out exactly."""
    from avenir_tpu.models.regress import _lr_grad

    axes = tuple(a for a in (DATA_AXIS, MODEL_AXIS) if a in mesh.axis_names)

    def kernel(coeff, x, y, w):
        grad = lax.psum(_lr_grad(coeff, x, y, w), axes)
        n = jnp.maximum(lax.psum(jnp.sum(w), axes), 1.0)
        return coeff + learning_rate * grad / n

    row = P(axes)
    return jax.jit(
        shard_map(kernel, mesh=mesh,
                      in_specs=(P(), row, row, row), out_specs=P())
    )


def distributed_markov_counts_fn(mesh: Mesh, n_states: int,
                                 n_classes: int = 1):
    """Build a jitted mesh-wide Markov bigram counter: padded sequences
    shard over the mesh rows, each shard runs the keyed segment_sum
    (models.markov._bigram_counts — the Hadoop/Spark shuffle of
    MarkovStateTransitionModel as one reduction), psum merges the
    [C, S, S] count tensors so every device holds the global matrix."""
    from avenir_tpu.models.markov import _bigram_counts

    axes = tuple(a for a in (DATA_AXIS, MODEL_AXIS) if a in mesh.axis_names)

    def kernel(padded, labels):
        c = _bigram_counts(padded, labels, n_states, n_classes)
        return lax.psum(c, axes)

    row = P(axes)
    return jax.jit(
        shard_map(kernel, mesh=mesh, in_specs=(row, row), out_specs=P())
    )


def distributed_apriori_support_fn(mesh: Mesh, k: int):
    """Build a jitted mesh-wide Apriori support counter: the multi-hot
    transaction tile shards over the mesh rows, candidates replicate, each
    shard counts containment via the MXU matmul
    (models.association._contain_counts), and a psum yields global
    supports — the per-k MR job (FrequentItemsApriori.java:51) as one
    collective."""
    from avenir_tpu.models.association import _contain_counts

    axes = tuple(a for a in (DATA_AXIS, MODEL_AXIS) if a in mesh.axis_names)

    def kernel(trans, cand):
        return lax.psum(_contain_counts(trans, cand, k), axes)

    return jax.jit(
        shard_map(kernel, mesh=mesh, in_specs=(P(axes), P()),
                      out_specs=P())
    )


def distributed_bandit_select_fn(mesh: Mesh, batch_size: int,
                                 max_reward: float = 100.0):
    """Build a jitted mesh-wide UCB1 bandit round: groups shard over the
    mesh rows (the map-only per-group MR job GreedyRandomBandit.java:148 /
    AuerDeterministic.java:130 is embarrassingly parallel — selection
    reads only the group's own arm stats, so the only collective cost is
    zero), each shard scores and ranks its groups, and the output stays
    group-sharded like the job's per-mapper output files."""
    from avenir_tpu.models.bandits import _ucb1_kernel

    axes = tuple(a for a in (DATA_AXIS, MODEL_AXIS) if a in mesh.axis_names)

    def kernel(counts, rewards, mask, round_num):
        # the shared single-device kernel, per shard (nested jit inlines)
        return _ucb1_kernel(counts, rewards, mask, round_num, max_reward,
                            batch_size)

    row = P(axes)
    return jax.jit(
        shard_map(kernel, mesh=mesh,
                      in_specs=(row, row, row, P()),
                      out_specs=row)
    )


def distributed_crosscount_fn(mesh: Mesh, bins_a: int, bins_b: int):
    """Build a jitted mesh-wide contingency counter: the primitive behind
    mutual information / correlations (SURVEY §2.4) — per-shard one-hot
    einsum, psum-merged [A, B] joint counts."""
    axes = tuple(a for a in (DATA_AXIS, MODEL_AXIS) if a in mesh.axis_names)

    def kernel(a, b, w):
        oa = jax.nn.one_hot(a, bins_a, dtype=jnp.float32) * w[:, None]
        ob = jax.nn.one_hot(b, bins_b, dtype=jnp.float32)
        return lax.psum(jnp.einsum("na,nb->ab", oa, ob), axes)

    row = P(axes)
    return jax.jit(
        shard_map(kernel, mesh=mesh, in_specs=(row, row, row),
                      out_specs=P())
    )


#: every distributed family this module exports, keyed by the short name
#: the collective-payload auditor and scaling harness use. Adding a family
#: here without a manifest entry + analytic payload model fails
#: tests/test_graftlint_ir.py — the auditor's coverage is this dict.
FAMILIES = {
    "knn_topk": distributed_topk_fn,
    "nb_train": distributed_nb_train_fn,
    "tree_level": distributed_tree_level_fn,
    "lr_step": distributed_lr_step_fn,
    "markov_counts": distributed_markov_counts_fn,
    "apriori_support": distributed_apriori_support_fn,
    "bandit_select": distributed_bandit_select_fn,
    "crosscount": distributed_crosscount_fn,
}
