"""Mesh construction + sharded aggregation helpers.

Design (SURVEY §2.12): avenir's only parallel axes are (a) independent rows
-> a 'data' mesh axis, and (b) the all-pairs distance grid of KNN -> an
optional second 'model' axis sharding the train side. Reductions that the
reference routed through the Hadoop shuffle become segment_sum per shard +
psum over 'data'; the resulting model tensors are small and replicated.

Multi-host scale-out: jax.distributed gives one process per host; the same
mesh spans all hosts' devices and the same psum rides ICI within a slice and
DCN across slices — no NCCL/MPI analog needed, XLA owns the transport.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """Version-portable shard_map with replication checking off.

    ``jax.shard_map(..., check_vma=False)`` only exists on newer JAX; on
    0.4.x the same program spells ``jax.experimental.shard_map.shard_map
    (..., check_rep=False)``. Every mesh kernel in this package routes
    through here so the sharding programs build identically on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def data_mesh(devices: Optional[Sequence] = None,
              model_parallel: int = 1) -> Mesh:
    """A (data[, model]) mesh over the given (default: all) devices.

    model_parallel > 1 carves a second axis used to shard the train side of
    all-pairs distance work; everything else uses pure data parallelism.
    """
    devs = np.array(devices if devices is not None else jax.devices())
    n = devs.size
    if model_parallel > 1:
        if n % model_parallel != 0:
            raise ValueError(
                f"device count {n} is not divisible by "
                f"model_parallel={model_parallel}; pass a device list whose "
                "size is a multiple of the model axis (or model_parallel=1)"
            )
        grid = devs.reshape(n // model_parallel, model_parallel)
        return Mesh(grid, (DATA_AXIS, MODEL_AXIS))
    return Mesh(devs.reshape(n), (DATA_AXIS,))


def row_spec(mesh: Mesh) -> P:
    return P(DATA_AXIS)


def shard_rows(mesh: Mesh, arr: jax.Array, pad_value=0) -> jax.Array:
    """Place a host array row-sharded over the data axis, padding the row
    count up to shard divisibility with `pad_value` rows."""
    n_shards = mesh.shape[DATA_AXIS]
    n = arr.shape[0]
    rem = (-n) % n_shards
    if rem:
        pad_rows = np.full((rem,) + arr.shape[1:], pad_value, dtype=arr.dtype)
        arr = np.concatenate([np.asarray(arr), pad_rows], axis=0)
    return jax.device_put(arr, NamedSharding(mesh, P(DATA_AXIS)))


def row_mask(mesh: Mesh, n_valid: int, n_padded: int) -> jax.Array:
    """1.0 for real rows, 0.0 for divisibility padding."""
    mask = (np.arange(n_padded) < n_valid).astype(np.float32)
    return jax.device_put(mask, NamedSharding(mesh, P(DATA_AXIS)))


def replicated(mesh: Mesh, arr) -> jax.Array:
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, P()))


def sharded_keyed_count(
    mesh: Mesh,
    count_fn: Callable[..., jax.Array],
):
    """Wrap a per-shard counting kernel into a mesh program.

    count_fn(*row_sharded_args) -> count pytree computed on the local rows.
    Returns a jitted function over row-sharded inputs whose outputs are the
    global (psum'd over 'data') counts, replicated on every device. This is
    the canonical 'mapper + shuffle + reducer' collapse: XLA inserts an
    all-reduce over ICI where Hadoop ran a disk shuffle.
    """
    def wrapped(*args):
        local = count_fn(*args)
        return jax.tree.map(lambda t: jax.lax.psum(t, DATA_AXIS), local)

    fn = shard_map(wrapped, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P())
    return jax.jit(fn)
