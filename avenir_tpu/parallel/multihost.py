"""Multi-host scale-out: jax.distributed + per-host ingest.

The reference scales ingest by HDFS input splits — each mapper reads its
local block (SURVEY §2.12). The TPU-pod analog: one process per host
(jax.distributed), each host reads its own CSV shard, and
`jax.make_array_from_process_local_data` assembles the global row-sharded
array without any host ever materializing the whole dataset. Collectives
then ride ICI within a slice and DCN across slices — XLA owns the
transport; there is no NCCL/MPI analog to manage.

Single-process usage degrades transparently: `initialize()` is a no-op
with one process and `global_rows` is then just a device_put.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from avenir_tpu.parallel.mesh import DATA_AXIS, data_mesh


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> int:
    """Bring up jax.distributed when running multi-process. On TPU pods the
    three arguments auto-detect from the environment; elsewhere pass them
    explicitly. Returns the process count. Safe to call in a single-process
    run (no-op)."""
    n = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "1"))
    if n <= 1 and coordinator_address is None:
        return 1
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count()


def global_mesh(model_parallel: int = 1) -> Mesh:
    """The pod-wide (data[, model]) mesh over every process's devices."""
    return data_mesh(jax.devices(), model_parallel=model_parallel)


def host_shard_bounds(n_rows_global: int) -> tuple:
    """[lo, hi) row range this host should ingest — the input-split
    assignment, contiguous per process. Delegates to the ONE copy of
    the split arithmetic (core.stream.split_byte_ranges), so the
    boundary edges the shard planner and this path share — corpus
    smaller than the process count (trailing empty shards tile
    gap-free), single-line corpus, no trailing newline — are fixed and
    regression-tested in one place."""
    from avenir_tpu.core.stream import split_byte_ranges

    p, i = jax.process_count(), jax.process_index()
    return split_byte_ranges(n_rows_global, p)[i]


def host_csv_byte_range(path: str) -> tuple:
    """This host's input split of ONE big input file: a contiguous byte
    range to hand to CsvBlockReader(byte_range=...) — or, for the ragged
    sequence jobs, iter_byte_blocks(byte_range=...) — both applying the
    Hadoop LineRecordReader boundary contract so the per-host splits
    partition the lines exactly. With host_shard_bounds this covers both
    ingest layouts the reference's HDFS splits served: one file per host,
    or one huge file split by offset."""
    return host_shard_bounds(os.path.getsize(path))


def global_rows(mesh: Mesh, local_rows: np.ndarray) -> jax.Array:
    """Assemble a globally row-sharded array from this host's local rows
    (each host passes only its own shard; shapes must agree across hosts
    up to the row count). Single-process: a plain sharded device_put."""
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    if jax.process_count() == 1:
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(sharding, local_rows)
