"""Scaling-efficiency measurement over device-mesh subsets.

BASELINE.md's north-star metric includes "scaling efficiency 8->256 chips";
the reference itself scaled by adding Hadoop nodes, with the shuffle as the
scaling bottleneck. Here the equivalent measurement is weak scaling of the
mesh kernels (`parallel/distributed.py`): fix the per-device workload, grow
the device count, and report how close total throughput stays to linear.
XLA's psum/all_gather over the mesh replace the shuffle, so the efficiency
loss is exactly the collective cost.

On a host with fewer real chips than requested the harness runs on virtual
CPU devices (`--xla_force_host_platform_device_count`). Virtual devices
share the host's cores, so absolute rates are meaningless and even relative
efficiency mixes collective overhead with core contention — the numbers are
a smoke-level proxy until real multi-chip hardware is attached; the shape of
the harness (and the sharding programs it runs) is identical either way.
"""

from __future__ import annotations

import re
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from avenir_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, data_mesh

# NB weak-scaling workload dims, shared by _nb_rate and the analytic
# per-device traffic fields in measure_scaling
_NB_CLASSES, _NB_FEAT, _NB_BMAX = 2, 8, 10


def collective_payload_model(family: str, mesh_shape: Dict[str, int],
                             **dims: int) -> int:
    """Analytic collective payload (bytes) of ONE step of a distributed
    family from `parallel/distributed.py` on a mesh of shape `mesh_shape`.

    This is the single source of truth the IR-level auditor
    (`analysis/ir.py`) asserts compiled HLO against, per family. "Payload"
    means the summed byte size of every collective instruction's result
    shapes — exactly what :func:`hlo_collective_payloads` extracts — so
    model and measurement count the same thing regardless of how XLA's
    combiner fuses or splits the ops.

    Family keys match ``distributed.FAMILIES``; `dims` are the family's
    workload dimensions (the manifest pins concrete values):

    - ``nb_train``:     psum of [F, K, B] f32 counts + [K] f32 class counts
    - ``knn_topk``:     two tiled all-gathers over 'model' of the per-query
                        candidate merge: [nq/data, model*k] f32 + i32
                        (0 when the mesh has no model axis — no collective)
    - ``tree_level``:   psum of the [L, NS, S, K] f32 level histogram
    - ``lr_step``:      psum of the [D] f32 gradient + f32 weight total
    - ``markov_counts``: psum of [C, S, S] f32 bigram counts
    - ``apriori_support``: psum of [C] s32 candidate supports
    - ``bandit_select``: 0 — the map-only per-group job has no collective
    - ``crosscount``:   psum of the [A, B] f32 contingency table
    """
    data_n = mesh_shape.get(DATA_AXIS, 1)
    model_n = mesh_shape.get(MODEL_AXIS, 1)
    if family == "nb_train":
        return (dims["n_feat"] * dims["num_classes"] * dims["bmax"]
                + dims["num_classes"]) * 4
    if family == "knn_topk":
        if model_n <= 1:
            return 0
        return (dims["nq"] // data_n) * model_n * dims["k"] * (4 + 4)
    if family == "tree_level":
        return (dims["n_leaves"] * dims["n_splits"] * dims["smax"]
                * dims["num_classes"]) * 4
    if family == "lr_step":
        return (dims["d"] + 1) * 4
    if family == "markov_counts":
        return dims["n_classes"] * dims["n_states"] * dims["n_states"] * 4
    if family == "apriori_support":
        return dims["n_cand"] * 4
    if family == "bandit_select":
        return 0
    if family == "crosscount":
        return dims["bins_a"] * dims["bins_b"] * 4
    raise KeyError(f"no analytic payload model for family {family!r}")


def nb_payload_bytes() -> int:
    """All-reduce payload of the weak-scaling NB step: the [F, K, B] count
    tensor + [K] class counts in f32. The single source of the number the
    compiled-HLO check validates and the projections consume (bench.py,
    tests)."""
    return collective_payload_model(
        "nb_train", {}, n_feat=_NB_FEAT, num_classes=_NB_CLASSES,
        bmax=_NB_BMAX)


def _timed_scalar(many_fn, *args) -> float:
    """Best-of-2 wall clock of the jitted scalar-reducing many_fn, warmup
    excluded, result forced to host with float(). Through the axon tunnel
    jax.block_until_ready has been observed returning before results are
    computed (see bench.py's timing note), so loop-and-block timing is
    banned here; every measurement runs its iterations inside one program
    and forces the scalar out."""
    import jax.numpy as jnp

    _ = float(many_fn(*args))
    best = np.inf
    for s in (1, 2):
        shifted = (jnp.roll(args[0], s, axis=0),) + args[1:]
        t0 = time.perf_counter()
        _ = float(many_fn(*shifted))
        best = min(best, time.perf_counter() - t0)
    return best


_DTYPE_BYTES = {"f64": 8, "s64": 8, "u64": 8, "f32": 4, "s32": 4, "u32": 4,
                "bf16": 2, "f16": 2, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
                "pred": 1}
# matches sync collectives AND the async '-start' form (the XLA:TPU
# default in compiled HLO); '-done' halves are skipped so an async pair
# counts its payload once
_COLLECTIVE_LINE = re.compile(
    r"(?<!%)\b(all-reduce|all-gather|reduce-scatter|all-to-all"
    r"|collective-permute)(-start)?\s*\(")
_SHAPE = re.compile(r"\b(" + "|".join(_DTYPE_BYTES) + r")\[([0-9,]*)\]")


def hlo_collective_payloads(compiled_text: str) -> List[Dict]:
    """Collective ops in a compiled HLO module with their payload bytes.

    This is the VALIDATION side of the scaling story: the analytic
    per-device traffic model (ring all-reduce moves 2(P-1)/P x payload)
    is only as good as its payload numbers, and those can silently grow
    when XLA reduces more than the model assumes. Parsing the compiled
    module pins them to what actually ships over the interconnect.
    Returns [{op, payload_bytes}] for each collective instruction (the
    payload is the summed byte size of the op's result shapes; for a
    tuple all-reduce that is the full reduced state)."""
    out = []
    for ln in compiled_text.splitlines():
        eq = ln.find("=")
        if eq < 0:
            continue
        # the result shapes sit between '=' and the op name; search only
        # the right-hand side, and reject %references to collective
        # instructions appearing as operands of other ops
        rhs = ln[eq + 1:]
        m = _COLLECTIVE_LINE.search(rhs)
        if not m:
            continue
        size = 0
        for dt, dims in _SHAPE.findall(rhs[: m.start()]):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            size += n * _DTYPE_BYTES[dt]
        out.append({"op": m.group(1), "payload_bytes": size})
    return out


def project_efficiency(
    per_device_step_seconds: float,
    allreduce_payload_bytes: float,
    counts: Sequence[int] = (8, 64, 256),
    ici_bytes_per_sec: float = 9.0e10,
    ici_hop_latency_s: float = 1.0e-6,
) -> List[Dict]:
    """Weak-scaling efficiency projection for P chips on one ICI domain.

    efficiency(P) = t_compute / (t_compute + t_comm(P)). The collective
    model is a dimension-wise all-reduce on a (near-)square 2D torus —
    the v5e pod topology: bandwidth term 2(P-1)/P x payload / bw, latency
    term 2 x sum(2(dim-1)) hops. Bandwidth/latency defaults are public
    v5e ICI ballparks (O(100) GB/s per chip, ~1us per hop).

    What the model says for this workload family: payloads are
    sub-kilobyte, so the bandwidth term is always noise and the knee is
    pure hop latency — ~60us at 256 chips. Against the bench's measured
    ~440us NB step (65k rows/device) that costs ~12%; the chunked
    streaming fold (accumulate(defer=True), multi-million-row chunks per
    device between flushes) pushes steps to multi-millisecond and the
    projection back to ~1.0. Scale-out is therefore an amortization knob
    the framework already exposes, not a redesign."""
    rows = []
    for p in counts:
        # near-square 2D torus factorization of p
        d1 = int(np.sqrt(p))
        while p % d1:
            d1 -= 1
        d2 = p // d1
        hops = 2 * ((d1 - 1) + (d2 - 1)) if p > 1 else 0
        t_comm = (2.0 * (p - 1) / p * allreduce_payload_bytes
                  / ici_bytes_per_sec + hops * ici_hop_latency_s)
        eff = per_device_step_seconds / (per_device_step_seconds + t_comm)
        rows.append({"devices": int(p), "projected_efficiency": round(eff, 4),
                     "torus": [d1, d2],
                     "t_compute_us": round(per_device_step_seconds * 1e6, 1),
                     "t_collective_us": round(t_comm * 1e6, 2)})
    return rows


def _nb_rate(mesh, rows: int, iters: int) -> float:
    """Weak-scaling NB sufficient-stat rate (rows/sec) on the given mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from avenir_tpu.parallel.distributed import distributed_nb_train_fn

    k_classes, n_feat, bmax = _NB_CLASSES, _NB_FEAT, _NB_BMAX
    rng = np.random.default_rng(0)
    codes = rng.integers(0, bmax, (rows, n_feat)).astype(np.int32)
    labels = rng.integers(0, k_classes, rows).astype(np.int32)
    w = np.ones((rows,), np.float32)
    shard = NamedSharding(mesh, P(mesh.axis_names))
    step = distributed_nb_train_fn(mesh, k_classes, bmax)

    codes_d = jax.device_put(codes, shard)
    labels_d = jax.device_put(labels, shard)
    w_d = jax.device_put(w, shard)

    # the step index rides as an operand, not a closure: a closure-captured
    # `iters` would bake the shape into the trace and recompile per value
    steps = jnp.arange(1, iters + 1)

    @jax.jit
    def many(codes_d, labels_d, w_d, steps):
        def body(i):
            # distinct data per step: on-device roll along the feature axis
            # keeps the row sharding intact (no cross-shard traffic)
            out = step(jnp.roll(codes_d, i, axis=1), labels_d, w_d)
            return sum(jnp.sum(o) for o in jax.tree.leaves(out))
        return jax.lax.map(body, steps).sum()

    return rows * iters / _timed_scalar(many, codes_d, labels_d, w_d, steps)


def _nb_compiled_collectives(mesh) -> List[Dict]:
    """Compile the sharded NB train step on `mesh` and return its
    collective instructions (hlo_collective_payloads)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from avenir_tpu.parallel.distributed import distributed_nb_train_fn

    rows = 8 * len(mesh.devices.flat)
    shard = NamedSharding(mesh, P(mesh.axis_names))
    step = distributed_nb_train_fn(mesh, _NB_CLASSES, _NB_BMAX)
    args = [
        jax.device_put(np.zeros((rows, _NB_FEAT), np.int32), shard),
        jax.device_put(np.zeros((rows,), np.int32), shard),
        jax.device_put(np.ones((rows,), np.float32), shard),
    ]
    compiled = jax.jit(step).lower(*args).compile()
    return hlo_collective_payloads(compiled.as_text())


def _knn_compiled_collectives(mesh, k: int = 5) -> Tuple[List[Dict], int]:
    """Compile the MODEL-parallel KNN candidate-merge step on `mesh` and
    return (collective instructions, analytic all-gather bytes): each
    device gathers [nq_local, P_model*k] distances (f32) + labels (i32) —
    the k*P candidate merge, NOT the n_train rows."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from avenir_tpu.parallel.distributed import distributed_topk_fn

    data_n = mesh.shape[DATA_AXIS]
    model_n = mesh.shape.get(MODEL_AXIS, 1)
    nq, train, d = 8 * data_n, 16 * model_n, 8
    step = distributed_topk_fn(mesh, k=k, metric="euclidean")
    args = [
        jax.device_put(np.zeros((nq, d), np.float32),
                       NamedSharding(mesh, P(DATA_AXIS, None))),
        jax.device_put(np.zeros((train, d), np.float32),
                       NamedSharding(mesh, P(MODEL_AXIS, None))),
        jax.device_put(np.zeros((train,), np.int32),
                       NamedSharding(mesh, P(MODEL_AXIS))),
    ]
    compiled = step.lower(*args).compile()
    analytic = collective_payload_model(
        "knn_topk", dict(mesh.shape), nq=nq, k=k)
    return hlo_collective_payloads(compiled.as_text()), analytic


def _knn_rate(mesh, queries: int, train: int, iters: int, k: int = 5) -> float:
    """Weak-scaling data-parallel KNN top-k rate (queries/sec)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from avenir_tpu.parallel.distributed import distributed_topk_fn

    d = 8
    rng = np.random.default_rng(1)
    q = rng.normal(size=(queries, d)).astype(np.float32)
    t = rng.normal(size=(train, d)).astype(np.float32)
    t_labels = rng.integers(0, 2, train).astype(np.int32)
    q_spec = NamedSharding(mesh, P(DATA_AXIS, None))
    rep = NamedSharding(mesh, P())
    step = distributed_topk_fn(mesh, k=k, metric="euclidean")

    q_d = jax.device_put(q, q_spec)
    t_d = jax.device_put(t, rep)
    l_d = jax.device_put(t_labels, rep)

    # step indices as an operand for the same no-recompile reason as _nb_rate
    steps = jnp.arange(1, iters + 1)

    @jax.jit
    def many(q_d, t_d, l_d, steps):
        def body(i):
            dist, labs = step(jnp.roll(q_d, i, axis=1), t_d, l_d)
            return jnp.sum(dist) + jnp.sum(labs).astype(jnp.float32)
        return jax.lax.map(body, steps).sum()

    return queries * iters / _timed_scalar(many, q_d, t_d, l_d, steps)


def measure_scaling(
    devices: Optional[Sequence] = None,
    counts: Sequence[int] = (1, 2, 4, 8),
    nb_rows_per_device: int = 65_536,
    knn_queries_per_device: int = 256,
    knn_train: int = 8_192,
    iters: int = 4,
) -> dict:
    """Run the distributed NB + KNN steps on mesh subsets of `counts`
    devices and report weak-scaling rates + efficiency vs linear.

    Returns {"table": [{devices, nb_rows_per_sec, nb_efficiency,
    knn_queries_per_sec, knn_efficiency}, ...], "efficiency_at_max": {...}}
    where efficiency = rate(P) / (P * rate(1)).
    """
    import jax

    devs = list(devices if devices is not None else jax.devices())
    counts = [c for c in counts if c <= len(devs)]
    if not counts:
        raise ValueError(
            f"no requested device count fits the {len(devs)} available "
            f"devices; include a count <= {len(devs)} (e.g. 1)"
        )
    # analytic per-device work/traffic per step — constant per-device work
    # is the weak-scaling invariant, and the ring-all-reduce bytes
    # (2(P-1)/P x tensor bytes) are the collective cost the efficiency
    # number prices in; unlike the wall clock these hold on real chips and
    # let a contended virtual run still validate the harness math
    nb_tensor_bytes = nb_payload_bytes()
    table = []
    for n in counts:
        mesh = data_mesh(devs[:n], model_parallel=1)
        nb = _nb_rate(mesh, nb_rows_per_device * n, iters)
        knn = _knn_rate(mesh, knn_queries_per_device * n, knn_train, iters)
        table.append({
            "devices": n,
            "nb_rows_per_sec": round(nb, 1),
            "knn_queries_per_sec": round(knn, 1),
            "nb_rows_per_device_per_step": nb_rows_per_device,
            "nb_allreduce_bytes_per_device": round(
                2 * (n - 1) / n * nb_tensor_bytes),
            "knn_queries_per_device_per_step": knn_queries_per_device,
        })
    base = table[0]
    for row in table:
        # efficiency vs linear relative to the smallest measured mesh
        scale = row["devices"] / base["devices"]
        row["nb_efficiency"] = round(
            row["nb_rows_per_sec"] / (scale * base["nb_rows_per_sec"]), 3)
        row["knn_efficiency"] = round(
            row["knn_queries_per_sec"] / (scale * base["knn_queries_per_sec"]),
            3)
    last = table[-1]
    virtual = devs[0].platform == "cpu"
    # HLO-validated traffic: parse the compiled sharded program's
    # collectives and check the analytic payload against what XLA emits
    hlo = _nb_compiled_collectives(data_mesh(devs[: last["devices"]],
                                            model_parallel=1))
    hlo_payload = sum(o["payload_bytes"] for o in hlo
                      if o["op"] == "all-reduce")
    # second family: the model-parallel KNN candidate merge (all-gather)
    knn_hlo: List[Dict] = []
    knn_analytic = 0
    if last["devices"] >= 2 and last["devices"] % 2 == 0:
        knn_hlo, knn_analytic = _knn_compiled_collectives(
            data_mesh(devs[: last["devices"]], model_parallel=2))
    knn_gather = sum(o["payload_bytes"] for o in knn_hlo
                     if o["op"] == "all-gather")
    # projection to pod scale from the measured per-device step time; on
    # virtual devices the compute side is contention-distorted, flagged
    step_s = nb_rows_per_device / (base["nb_rows_per_sec"]
                                   / base["devices"])
    out = {
        "table": table,
        "efficiency_at_max": {
            "devices": last["devices"],
            "nb": last["nb_efficiency"],
            "knn": last["knn_efficiency"],
        },
        "nb_hlo_collectives": hlo,
        "nb_hlo_allreduce_payload_bytes": hlo_payload,
        "nb_analytic_payload_bytes": nb_tensor_bytes,
        "payload_model_validated": hlo_payload == nb_tensor_bytes,
        "knn_hlo_collectives": knn_hlo,
        "knn_hlo_allgather_payload_bytes": knn_gather,
        "knn_analytic_allgather_payload_bytes": knn_analytic,
        "knn_payload_model_validated": bool(knn_hlo)
        and knn_gather == knn_analytic,
        "projection_8_to_256": project_efficiency(step_s, hlo_payload),
        "projection_note": (
            "projection_8_to_256 is a MODEL, not a measurement: payload "
            "bytes are HLO-validated and the single-chip step time is "
            "measured, but ICI bandwidth/latency are datasheet "
            "assumptions (project_efficiency) — no multi-chip hardware "
            "exists in this environment to measure against"),
        "virtual_devices": virtual,
    }
    if virtual:
        out["note"] = (
            "virtual CPU devices share one host's cores (the 1-device XLA "
            "run already uses the full host threadpool), so efficiency-vs-"
            "linear is core-contention-bound here; on real chips the same "
            "harness measures true ICI scaling"
        )
    return out
