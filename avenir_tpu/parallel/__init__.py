"""Parallel layer: device meshes + collectives.

Replaces the reference's distribution substrate (Hadoop shuffle/HDFS, Spark
RDD shuffle, Storm workers — SURVEY §2.12) with jax.sharding over an ICI
mesh: row batches shard over a 'data' axis, small model tensors replicate,
and aggregation is lax.psum instead of a shuffle.
"""

from avenir_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_mesh,
    shard_rows,
    row_mask,
    replicated,
    sharded_keyed_count,
)
from avenir_tpu.parallel.distributed import (
    distributed_crosscount_fn,
    distributed_lr_step_fn,
    distributed_nb_train_fn,
    distributed_topk_fn,
    distributed_tree_level_fn,
)
