"""Parallel layer: device meshes + collectives.

Replaces the reference's distribution substrate (Hadoop shuffle/HDFS, Spark
RDD shuffle, Storm workers — SURVEY §2.12) with jax.sharding over an ICI
mesh: row batches shard over a 'data' axis, small model tensors replicate,
and aggregation is lax.psum instead of a shuffle.
"""

from avenir_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_mesh,
    shard_rows,
    row_mask,
    replicated,
    sharded_keyed_count,
)
