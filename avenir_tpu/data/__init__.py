"""Synthetic data generators — the reference's resource/*.py generator scripts
(telecom_churn.py, elearn.py, call_hangup.py, price_opt.py) re-built as
seedable numpy generators that return Datasets directly."""

from avenir_tpu.data.generators import (
    churn_schema,
    generate_churn,
    elearn_schema,
    generate_elearn,
)
