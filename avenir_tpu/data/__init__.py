"""Synthetic data generators — the reference's resource/*.py generator scripts
(telecom_churn.py, elearn.py, call_hangup.py, price_opt.py) re-built as
seedable numpy generators that return Datasets directly."""

from avenir_tpu.data.generators import (
    BUY_STATES,
    call_hangup_schema,
    churn_schema,
    disease_schema,
    elearn_schema,
    generate_buy_xactions,
    generate_call_hangup,
    generate_churn,
    generate_disease,
    generate_elearn,
    generate_event_sequences,
    generate_hosp_readmit,
    generate_price_opt,
    generate_visit_history,
    hosp_readmit_schema,
    xactions_to_state_sequences,
)
