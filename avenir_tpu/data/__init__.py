"""Synthetic data generators — the reference's resource/*.py generator scripts
(telecom_churn.py, elearn.py, call_hangup.py, price_opt.py) re-built as
seedable numpy generators that return Datasets directly."""

from avenir_tpu.data.generators import (
    call_hangup_schema,
    churn_schema,
    elearn_schema,
    generate_call_hangup,
    generate_churn,
    generate_elearn,
    generate_event_sequences,
    generate_price_opt,
)
