"""Synthetic dataset generators with known class structure.

The reference has no tests; its generators (resource/telecom_churn.py,
resource/elearn.py, ...) produce CSV whose class correlates with feature
distributions. These are seedable equivalents producing Datasets (and CSV)
against reference-style schemas, used by the test suite and bench.py.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from avenir_tpu.core.dataset import Dataset
from avenir_tpu.core.schema import FeatureSchema


def churn_schema() -> FeatureSchema:
    """resource/churn.json-shaped schema (categorical features + binary class)."""
    return FeatureSchema.from_json({
        "fields": [
            {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
            {"name": "minUsed", "ordinal": 1, "dataType": "categorical",
             "cardinality": ["low", "med", "high", "overage"], "feature": True},
            {"name": "dataUsed", "ordinal": 2, "dataType": "categorical",
             "cardinality": ["low", "med", "high"], "feature": True},
            {"name": "CSCalls", "ordinal": 3, "dataType": "categorical",
             "cardinality": ["low", "med", "high"], "feature": True},
            {"name": "payment", "ordinal": 4, "dataType": "categorical",
             "cardinality": ["poor", "average", "good"], "feature": True},
            {"name": "acctAge", "ordinal": 5, "dataType": "int", "feature": True,
             "min": 0, "max": 120, "bucketWidth": 12},
            {"name": "status", "ordinal": 6, "dataType": "categorical",
             "cardinality": ["open", "closed"]},
        ]
    })


def generate_churn(n: int, seed: int = 7,
                   as_csv: bool = False) -> "Dataset | str":
    """Telecom churn rows: 'closed' accounts skew to high CSCalls / poor
    payment / high usage, like resource/telecom_churn.py's weighted draws."""
    rng = np.random.default_rng(seed)
    schema = churn_schema()
    y = (rng.random(n) < 0.3).astype(np.int32)        # 30% churn
    def draw(card: int, open_w: List[float], closed_w: List[float]) -> np.ndarray:
        w = np.where(y[:, None] == 0, np.array(open_w), np.array(closed_w))
        c = np.cumsum(w, axis=1) / w.sum(axis=1, keepdims=True)
        return (rng.random(n)[:, None] > c).sum(axis=1).astype(np.int32)

    min_used = draw(4, [3, 4, 2, 1], [1, 2, 3, 4])
    data_used = draw(3, [3, 4, 2], [1, 2, 4])
    cs_calls = draw(3, [5, 2, 1], [1, 2, 5])
    payment = draw(3, [1, 3, 5], [5, 3, 1])
    age = np.where(
        y == 0,
        rng.integers(12, 120, n),
        rng.integers(0, 48, n),
    ).astype(np.int32)

    card = lambda o: schema.field_by_ordinal(o).cardinality
    rows = [
        [
            f"C{i:08d}",
            card(1)[min_used[i]],
            card(2)[data_used[i]],
            card(3)[cs_calls[i]],
            card(4)[payment[i]],
            str(age[i]),
            card(6)[y[i]],
        ]
        for i in range(n)
    ]
    if as_csv:
        return "\n".join(",".join(r) for r in rows) + "\n"
    return Dataset.from_rows(rows, schema)


def elearn_schema(num_numeric: int = 6) -> FeatureSchema:
    """resource/elearnActivity.json-style schema: id + numeric activity
    features + pass/fail class — the KNN benchmark dataset shape."""
    fields = [{"name": "id", "ordinal": 0, "id": True, "dataType": "string"}]
    for i in range(num_numeric):
        fields.append({
            "name": f"act{i}", "ordinal": i + 1, "dataType": "double",
            "feature": True, "min": 0, "max": 100,
        })
    fields.append({
        "name": "grade", "ordinal": num_numeric + 1, "dataType": "categorical",
        "cardinality": ["fail", "pass"],
    })
    return FeatureSchema.from_json({"fields": fields})


def generate_elearn(n: int, num_numeric: int = 6, seed: int = 11) -> Dataset:
    """Two gaussian clusters in activity space -> separable pass/fail."""
    rng = np.random.default_rng(seed)
    schema = elearn_schema(num_numeric)
    y = (rng.random(n) < 0.5).astype(np.int32)
    centers = np.stack([np.full(num_numeric, 30.0), np.full(num_numeric, 65.0)])
    x = centers[y] + rng.normal(0, 12.0, (n, num_numeric))
    x = np.clip(x, 0, 100)
    rows = [
        [f"S{i:08d}"] + [f"{v:.3f}" for v in x[i]] + [["fail", "pass"][y[i]]]
        for i in range(n)
    ]
    return Dataset.from_rows(rows, schema)
