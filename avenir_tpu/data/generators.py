"""Synthetic dataset generators with known class structure.

The reference has no tests; its generators (resource/telecom_churn.py,
resource/elearn.py, ...) produce CSV whose class correlates with feature
distributions. These are seedable equivalents producing Datasets (and CSV)
against reference-style schemas, used by the test suite and bench.py.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from avenir_tpu.core.dataset import Dataset
from avenir_tpu.core.schema import FeatureSchema


def churn_schema() -> FeatureSchema:
    """resource/churn.json-shaped schema (categorical features + binary class)."""
    return FeatureSchema.from_json({
        "fields": [
            {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
            {"name": "minUsed", "ordinal": 1, "dataType": "categorical",
             "cardinality": ["low", "med", "high", "overage"], "feature": True},
            {"name": "dataUsed", "ordinal": 2, "dataType": "categorical",
             "cardinality": ["low", "med", "high"], "feature": True},
            {"name": "CSCalls", "ordinal": 3, "dataType": "categorical",
             "cardinality": ["low", "med", "high"], "feature": True},
            {"name": "payment", "ordinal": 4, "dataType": "categorical",
             "cardinality": ["poor", "average", "good"], "feature": True},
            {"name": "acctAge", "ordinal": 5, "dataType": "int", "feature": True,
             "min": 0, "max": 120, "bucketWidth": 12},
            {"name": "status", "ordinal": 6, "dataType": "categorical",
             "cardinality": ["open", "closed"]},
        ]
    })


def generate_churn(n: int, seed: int = 7,
                   as_csv: bool = False) -> "Dataset | str":
    """Telecom churn rows: 'closed' accounts skew to high CSCalls / poor
    payment / high usage, like resource/telecom_churn.py's weighted draws."""
    rng = np.random.default_rng(seed)
    schema = churn_schema()
    y = (rng.random(n) < 0.3).astype(np.int32)        # 30% churn
    def draw(card: int, open_w: List[float], closed_w: List[float]) -> np.ndarray:
        w = np.where(y[:, None] == 0, np.array(open_w), np.array(closed_w))
        c = np.cumsum(w, axis=1) / w.sum(axis=1, keepdims=True)
        return (rng.random(n)[:, None] > c).sum(axis=1).astype(np.int32)

    min_used = draw(4, [3, 4, 2, 1], [1, 2, 3, 4])
    data_used = draw(3, [3, 4, 2], [1, 2, 4])
    cs_calls = draw(3, [5, 2, 1], [1, 2, 5])
    payment = draw(3, [1, 3, 5], [5, 3, 1])
    age = np.where(
        y == 0,
        rng.integers(12, 120, n),
        rng.integers(0, 48, n),
    ).astype(np.int32)

    card = lambda o: schema.field_by_ordinal(o).cardinality
    rows = [
        [
            f"C{i:08d}",
            card(1)[min_used[i]],
            card(2)[data_used[i]],
            card(3)[cs_calls[i]],
            card(4)[payment[i]],
            str(age[i]),
            card(6)[y[i]],
        ]
        for i in range(n)
    ]
    if as_csv:
        return "\n".join(",".join(r) for r in rows) + "\n"
    return Dataset.from_rows(rows, schema)


def elearn_schema(num_numeric: int = 6) -> FeatureSchema:
    """resource/elearnActivity.json-style schema: id + numeric activity
    features + pass/fail class — the KNN benchmark dataset shape."""
    fields = [{"name": "id", "ordinal": 0, "id": True, "dataType": "string"}]
    for i in range(num_numeric):
        fields.append({
            "name": f"act{i}", "ordinal": i + 1, "dataType": "double",
            "feature": True, "min": 0, "max": 100,
        })
    fields.append({
        "name": "grade", "ordinal": num_numeric + 1, "dataType": "categorical",
        "cardinality": ["fail", "pass"],
    })
    return FeatureSchema.from_json({"fields": fields})


def generate_elearn(n: int, num_numeric: int = 6, seed: int = 11) -> Dataset:
    """Two gaussian clusters in activity space -> separable pass/fail."""
    rng = np.random.default_rng(seed)
    schema = elearn_schema(num_numeric)
    y = (rng.random(n) < 0.5).astype(np.int32)
    centers = np.stack([np.full(num_numeric, 30.0), np.full(num_numeric, 65.0)])
    x = centers[y] + rng.normal(0, 12.0, (n, num_numeric))
    x = np.clip(x, 0, 100)
    rows = [
        [f"S{i:08d}"] + [f"{v:.3f}" for v in x[i]] + [["fail", "pass"][y[i]]]
        for i in range(n)
    ]
    return Dataset.from_rows(rows, schema)


def call_hangup_schema() -> FeatureSchema:
    """resource/call_hangup.json mirror (same ordinals; ordinal 2 = area
    code is present in rows but undeclared, exactly as the reference skips
    it). The class field gets its cardinality declared (deviation: the
    reference file omits it and lets the job infer)."""
    return FeatureSchema.from_json({
        "fields": [
            {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
            {"name": "customer type", "ordinal": 1, "dataType": "categorical",
             "feature": True, "maxSplit": 2,
             "cardinality": ["business", "residence"]},
            {"name": "issue", "ordinal": 3, "dataType": "categorical",
             "feature": True, "maxSplit": 2,
             "cardinality": ["internet", "cable", "billing", "other"]},
            {"name": "time of day", "ordinal": 4, "dataType": "categorical",
             "feature": True, "maxSplit": 2, "cardinality": ["AM", "PM"]},
            {"name": "hold time", "ordinal": 5, "dataType": "int",
             "feature": True, "bucketWidth": 60, "min": 0, "max": 600,
             "splitScanInterval": 60},
            {"name": "hungup", "ordinal": 6, "dataType": "categorical",
             "cardinality": ["F", "T"]},
        ]
    })


def generate_call_hangup(n: int, seed: int = 13,
                         as_csv: bool = False) -> "Dataset | str":
    """resource/call_hangup.py behavior: Gaussian hold times by time of
    day (AM mean 500/80, PM 400/60), hangup likely above a threshold."""
    rng = np.random.default_rng(seed)
    schema = call_hangup_schema()
    rows = []
    for i in range(n):
        cust = "business" if rng.random() < 0.4 else "residence"
        issue = ["internet", "billing", "other"][rng.integers(0, 3)] \
            if cust == "business" else \
            ["internet", "cable", "billing", "other"][rng.integers(0, 4)]
        tod = "AM" if rng.random() < 0.5 else "PM"
        mean, std = (500.0, 80.0) if tod == "AM" else (400.0, 60.0)
        hold = float(np.clip(rng.normal(mean, std), 0, 599))
        threshold = 420.0
        if hold > threshold:
            hungup = "T" if rng.random() < 0.8 else "F"
        else:
            hungup = "F" if rng.random() < 0.9 else "T"
        area = str(rng.choice([408, 607, 336, 646, 206]))
        rows.append([f"{rng.integers(10**9, 10**10)}", cust, area, issue,
                     tod, str(int(hold)), hungup])
    if as_csv:
        return "\n".join(",".join(r) for r in rows) + "\n"
    return Dataset.from_rows(rows, schema)


def generate_price_opt(num_products: int = 10, seed: int = 17
                       ) -> List[List[str]]:
    """resource/price_opt.py behavior: per product a price ladder whose
    revenue rises to a halfway peak then falls — the group bandit round
    input rows (group=product, item=price, count, avgReward)."""
    rng = np.random.default_rng(seed)
    rows: List[List[str]] = []
    for _ in range(num_products):
        prod = str(rng.integers(1_000_000, 8_000_000))
        num_price = int(rng.integers(6, 12))
        price = int(rng.integers(10, 80))
        delta = int(rng.integers(2, 4))
        rev = float(rng.integers(10_000, 30_000))
        rev_delta = float(rng.integers(500, 1_500))
        half = num_price // 2 + int(rng.integers(-2, 2))
        for p in range(num_price):
            rows.append([prod, str(price), "1", f"{rev:.0f}"])
            price += delta
            rev += (rev_delta if p < half else -rev_delta) + float(
                rng.integers(-20, 20))
    return rows


def generate_event_sequences(n: int, states: Optional[List[str]] = None,
                             mean_len: int = 10, seed: int = 19
                             ) -> List[List[str]]:
    """resource/event_seq.rb-style event sequences: per entity a Markov
    walk over event states with a sticky diagonal."""
    rng = np.random.default_rng(seed)
    states = states or ["login", "browse", "cart", "buy", "logout"]
    s = len(states)
    if s < 2:
        raise ValueError("need at least 2 event states")
    trans = np.full((s, s), 0.5 / (s - 1))
    np.fill_diagonal(trans, 0.5)
    seqs = []
    for i in range(n):
        length = max(2, int(rng.poisson(mean_len)))
        cur = int(rng.integers(0, s))
        seq = [states[cur]]
        for _ in range(length - 1):
            cur = int(rng.choice(s, p=trans[cur]))
            seq.append(states[cur])
        seqs.append(seq)
    return seqs
