"""Synthetic dataset generators with known class structure.

The reference has no tests; its generators (resource/telecom_churn.py,
resource/elearn.py, ...) produce CSV whose class correlates with feature
distributions. These are seedable equivalents producing Datasets (and CSV)
against reference-style schemas, used by the test suite and bench.py.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from avenir_tpu.core.dataset import Dataset
from avenir_tpu.core.schema import FeatureSchema


def churn_schema() -> FeatureSchema:
    """resource/churn.json-shaped schema (categorical features + binary class)."""
    return FeatureSchema.from_json({
        "fields": [
            {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
            {"name": "minUsed", "ordinal": 1, "dataType": "categorical",
             "cardinality": ["low", "med", "high", "overage"], "feature": True},
            {"name": "dataUsed", "ordinal": 2, "dataType": "categorical",
             "cardinality": ["low", "med", "high"], "feature": True},
            {"name": "CSCalls", "ordinal": 3, "dataType": "categorical",
             "cardinality": ["low", "med", "high"], "feature": True},
            {"name": "payment", "ordinal": 4, "dataType": "categorical",
             "cardinality": ["poor", "average", "good"], "feature": True},
            {"name": "acctAge", "ordinal": 5, "dataType": "int", "feature": True,
             "min": 0, "max": 120, "bucketWidth": 12},
            {"name": "status", "ordinal": 6, "dataType": "categorical",
             "cardinality": ["open", "closed"]},
        ]
    })


def generate_churn(n: int, seed: int = 7,
                   as_csv: bool = False) -> "Dataset | str":
    """Telecom churn rows: 'closed' accounts skew to high CSCalls / poor
    payment / high usage, like resource/telecom_churn.py's weighted draws."""
    rng = np.random.default_rng(seed)
    schema = churn_schema()
    y = (rng.random(n) < 0.3).astype(np.int32)        # 30% churn
    def draw(card: int, open_w: List[float], closed_w: List[float]) -> np.ndarray:
        w = np.where(y[:, None] == 0, np.array(open_w), np.array(closed_w))
        c = np.cumsum(w, axis=1) / w.sum(axis=1, keepdims=True)
        return (rng.random(n)[:, None] > c).sum(axis=1).astype(np.int32)

    min_used = draw(4, [3, 4, 2, 1], [1, 2, 3, 4])
    data_used = draw(3, [3, 4, 2], [1, 2, 4])
    cs_calls = draw(3, [5, 2, 1], [1, 2, 5])
    payment = draw(3, [1, 3, 5], [5, 3, 1])
    age = np.where(
        y == 0,
        rng.integers(12, 120, n),
        rng.integers(0, 48, n),
    ).astype(np.int32)

    card = lambda o: schema.field_by_ordinal(o).cardinality
    rows = [
        [
            f"C{i:08d}",
            card(1)[min_used[i]],
            card(2)[data_used[i]],
            card(3)[cs_calls[i]],
            card(4)[payment[i]],
            str(age[i]),
            card(6)[y[i]],
        ]
        for i in range(n)
    ]
    if as_csv:
        return "\n".join(",".join(r) for r in rows) + "\n"
    return Dataset.from_rows(rows, schema)


def elearn_schema(num_numeric: int = 6) -> FeatureSchema:
    """resource/elearnActivity.json-style schema: id + numeric activity
    features + pass/fail class — the KNN benchmark dataset shape."""
    fields = [{"name": "id", "ordinal": 0, "id": True, "dataType": "string"}]
    for i in range(num_numeric):
        fields.append({
            "name": f"act{i}", "ordinal": i + 1, "dataType": "double",
            "feature": True, "min": 0, "max": 100,
        })
    fields.append({
        "name": "grade", "ordinal": num_numeric + 1, "dataType": "categorical",
        "cardinality": ["fail", "pass"],
    })
    return FeatureSchema.from_json({"fields": fields})


def generate_elearn(n: int, num_numeric: int = 6, seed: int = 11) -> Dataset:
    """Two gaussian clusters in activity space -> separable pass/fail."""
    rng = np.random.default_rng(seed)
    schema = elearn_schema(num_numeric)
    y = (rng.random(n) < 0.5).astype(np.int32)
    centers = np.stack([np.full(num_numeric, 30.0), np.full(num_numeric, 65.0)])
    x = centers[y] + rng.normal(0, 12.0, (n, num_numeric))
    x = np.clip(x, 0, 100)
    rows = [
        [f"S{i:08d}"] + [f"{v:.3f}" for v in x[i]] + [["fail", "pass"][y[i]]]
        for i in range(n)
    ]
    return Dataset.from_rows(rows, schema)


def call_hangup_schema() -> FeatureSchema:
    """resource/call_hangup.json mirror (same ordinals; ordinal 2 = area
    code is present in rows but undeclared, exactly as the reference skips
    it). The class field gets its cardinality declared (deviation: the
    reference file omits it and lets the job infer)."""
    return FeatureSchema.from_json({
        "fields": [
            {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
            {"name": "customer type", "ordinal": 1, "dataType": "categorical",
             "feature": True, "maxSplit": 2,
             "cardinality": ["business", "residence"]},
            {"name": "issue", "ordinal": 3, "dataType": "categorical",
             "feature": True, "maxSplit": 2,
             "cardinality": ["internet", "cable", "billing", "other"]},
            {"name": "time of day", "ordinal": 4, "dataType": "categorical",
             "feature": True, "maxSplit": 2, "cardinality": ["AM", "PM"]},
            {"name": "hold time", "ordinal": 5, "dataType": "int",
             "feature": True, "bucketWidth": 60, "min": 0, "max": 600,
             "splitScanInterval": 60},
            {"name": "hungup", "ordinal": 6, "dataType": "categorical",
             "cardinality": ["F", "T"]},
        ]
    })


def generate_call_hangup(n: int, seed: int = 13,
                         as_csv: bool = False) -> "Dataset | str":
    """resource/call_hangup.py behavior: Gaussian hold times by time of
    day (AM mean 500/80, PM 400/60), hangup likely above a threshold."""
    rng = np.random.default_rng(seed)
    schema = call_hangup_schema()
    rows = []
    for i in range(n):
        cust = "business" if rng.random() < 0.4 else "residence"
        issue = ["internet", "billing", "other"][rng.integers(0, 3)] \
            if cust == "business" else \
            ["internet", "cable", "billing", "other"][rng.integers(0, 4)]
        tod = "AM" if rng.random() < 0.5 else "PM"
        mean, std = (500.0, 80.0) if tod == "AM" else (400.0, 60.0)
        hold = float(np.clip(rng.normal(mean, std), 0, 599))
        threshold = 420.0
        if hold > threshold:
            hungup = "T" if rng.random() < 0.8 else "F"
        else:
            hungup = "F" if rng.random() < 0.9 else "T"
        area = str(rng.choice([408, 607, 336, 646, 206]))
        rows.append([f"{rng.integers(10**9, 10**10)}", cust, area, issue,
                     tod, str(int(hold)), hungup])
    if as_csv:
        return "\n".join(",".join(r) for r in rows) + "\n"
    return Dataset.from_rows(rows, schema)


def generate_price_opt(num_products: int = 10, seed: int = 17
                       ) -> List[List[str]]:
    """resource/price_opt.py behavior: per product a price ladder whose
    revenue rises to a halfway peak then falls — the group bandit round
    input rows (group=product, item=price, count, avgReward)."""
    rng = np.random.default_rng(seed)
    rows: List[List[str]] = []
    for _ in range(num_products):
        prod = str(rng.integers(1_000_000, 8_000_000))
        num_price = int(rng.integers(6, 12))
        price = int(rng.integers(10, 80))
        delta = int(rng.integers(2, 4))
        rev = float(rng.integers(10_000, 30_000))
        rev_delta = float(rng.integers(500, 1_500))
        half = num_price // 2 + int(rng.integers(-2, 2))
        for p in range(num_price):
            rows.append([prod, str(price), "1", f"{rev:.0f}"])
            price += delta
            rev += (rev_delta if p < half else -rev_delta) + float(
                rng.integers(-20, 20))
    return rows


def generate_event_sequences(n: int, states: Optional[List[str]] = None,
                             mean_len: int = 10, seed: int = 19
                             ) -> List[List[str]]:
    """resource/event_seq.rb-style event sequences: per entity a Markov
    walk over event states with a sticky diagonal."""
    rng = np.random.default_rng(seed)
    states = states or ["login", "browse", "cart", "buy", "logout"]
    s = len(states)
    if s < 2:
        raise ValueError("need at least 2 event states")
    trans = np.full((s, s), 0.5 / (s - 1))
    np.fill_diagonal(trans, 0.5)
    seqs = []
    for i in range(n):
        length = max(2, int(rng.poisson(mean_len)))
        cur = int(rng.integers(0, s))
        seq = [states[cur]]
        for _ in range(length - 1):
            cur = int(rng.choice(s, p=trans[cur]))
            seq.append(states[cur])
        seqs.append(seq)
    return seqs


def _weighted(rng, vals, wts, size):
    """Weighted categorical draw (the reference util.rb's
    CategoricalField / NumericalFieldRange sampling)."""
    p = np.asarray(wts, np.float64)
    return rng.choice(vals, size=size, p=p / p.sum())


def hosp_readmit_schema() -> FeatureSchema:
    """resource/hosp_readmit.json mirror: bucketized numerics WITHOUT a
    declared max (extent is data-discovered, see
    dataset._discover_numeric_range) + undeclared categorical
    vocabularies — the reference's sparsest schema style."""
    def cat(name, o):
        return {"name": name, "ordinal": o, "dataType": "categorical",
                "feature": True}
    return FeatureSchema.from_json({"fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "age", "ordinal": 1, "dataType": "int", "feature": True,
         "bucketWidth": 10},
        {"name": "weight", "ordinal": 2, "dataType": "int", "feature": True,
         "bucketWidth": 10},
        {"name": "height", "ordinal": 3, "dataType": "int", "feature": True,
         "bucketWidth": 5},
        cat("employmentStatus", 4), cat("familyStatus", 5), cat("diet", 6),
        cat("exercise", 7), cat("followUp", 8), cat("smoking", 9),
        cat("alcohol", 10),
        {"name": "readmit", "ordinal": 11, "dataType": "categorical"},
    ]})


def generate_hosp_readmit(n: int, seed: int = 27,
                          as_csv: bool = False) -> "Dataset | str":
    """resource/hosp_readmit.rb behavior: weighted demographic draws and
    an additive readmission probability (age/solitude/followUp dominate)."""
    rng = np.random.default_rng(seed)

    age = _weighted(rng, [15, 25, 35, 45, 55, 65, 75, 85],
                   [2, 3, 6, 10, 14, 19, 25, 21], n) + rng.integers(-4, 5, n)
    weight = _weighted(rng, np.arange(135, 246, 10),
                       [9, 13, 16, 20, 23, 20, 17, 14, 10, 7, 5, 3], n)
    height = _weighted(rng, [52, 58, 63, 68, 73], [9, 12, 16, 23, 14], n)
    emp = _weighted(rng, ["employed", "unemployed", "retired"], [10, 1, 3], n)
    emp = np.where((age > 68) & (rng.random(n) < 0.8), "retired", emp)
    fam = _weighted(rng, ["alone", "with partner"], [10, 15], n)
    diet = _weighted(rng, ["average", "poor", "good"], [10, 4, 2], n)
    diet = np.where((emp == "unemployed") & (rng.random(n) < 0.7),
                    "poor", diet)
    exercise = _weighted(rng, ["average", "low", "high"], [10, 12, 4], n)
    follow = _weighted(rng, ["average", "low", "high"], [10, 14, 3], n)
    smoking = _weighted(rng, ["non smoker", "smoker"], [10, 3], n)
    alcohol = _weighted(rng, ["average", "low", "high"], [10, 16, 4], n)

    prob = np.full(n, 20.0)
    prob += np.select([age > 80, age > 70, age > 60], [10, 5, 3], 0)
    prob += np.where((weight > 200) & (height < 70), 5,
                     np.where((weight > 180) & (height < 60), 3, 0))
    prob += np.select([emp == "unemployed", emp == "retired"], [6, 4], 0)
    prob += np.where(fam == "alone", 9, 0)
    prob += np.select([diet == "poor", diet == "average"], [4, 2], 0)
    prob += np.select([exercise == "low", exercise == "average"], [3, 1], 0)
    prob += np.where(follow == "low", 8, 0)
    prob += np.where(smoking == "smoker", 6, 0)
    prob += np.select([alcohol == "high", alcohol == "average"], [5, 2], 0)
    readmit = np.where(rng.integers(0, 100, n) < prob, "Y", "N")

    rows = [[f"P{i:011d}", str(int(age[i])), str(int(weight[i])),
             str(int(height[i])), emp[i], fam[i], diet[i], exercise[i],
             follow[i], smoking[i], alcohol[i], readmit[i]]
            for i in range(n)]
    if as_csv:
        return "\n".join(",".join(r) for r in rows) + "\n"
    return Dataset.from_rows(rows, hosp_readmit_schema())


def disease_schema() -> FeatureSchema:
    """resource/patient.json mirror (the disease rule-mining meta data)."""
    def cat(name, o):
        return {"name": name, "ordinal": o, "dataType": "categorical",
                "feature": True}
    return FeatureSchema.from_json({"fields": [
        {"name": "patientID", "ordinal": 0, "id": True,
         "dataType": "string"},
        {"name": "age", "ordinal": 1, "dataType": "int", "feature": True,
         "min": 20, "max": 80, "maxSplit": 3, "bucketWidth": 5},
        cat("race", 2),
        {"name": "weight", "ordinal": 3, "dataType": "int", "feature": True},
        cat("diet", 4), cat("family history", 5), cat("domestic life", 6),
        {"name": "disease", "ordinal": 7, "dataType": "categorical"},
    ]})


def generate_disease(n: int, seed: int = 28,
                     as_csv: bool = False) -> "Dataset | str":
    """resource/disease.rb behavior: multiplicative risk by age band, race,
    diet, family history and domestic life."""
    rng = np.random.default_rng(seed)

    age = rng.integers(20, 80, n)
    race = _weighted(rng, ["EUA", "AFA", "LAA", "ASA"], [10, 3, 1, 1], n)
    weight = rng.integers(120, 240, n)
    diet = _weighted(rng, ["LF", "REG", "HF"], [2, 8, 4], n)
    fam = _weighted(rng, ["NFH", "FH"], [5, 1], n)
    dom = _weighted(rng, ["S", "DP"], [2, 4], n)

    pr = np.full(n, 15.0)
    pr *= np.select([age < 40, age < 50, age < 60, age < 70],
                    [1.0, 1.05, 1.15, 1.4], 1.5)
    pr *= np.select([race == "AFA", race == "ASA", race == "LAA"],
                    [1.2, 0.9, 0.95], 1.0)
    pr *= np.where(diet == "HF", 1.15, 1.0)
    pr *= np.where(fam == "FH", 1.2, 1.0)
    pr *= np.where(dom == "S", 1.2, 1.0)
    status = np.where(rng.integers(0, 100, n) < np.minimum(pr, 99.0),
                      "Yes", "No")
    rows = [[f"D{i:011d}", str(int(age[i])), race[i], str(int(weight[i])),
             diet[i], fam[i], dom[i], status[i]] for i in range(n)]
    if as_csv:
        return "\n".join(",".join(r) for r in rows) + "\n"
    return Dataset.from_rows(rows, disease_schema())


BUY_STATES = ["SL", "SE", "SG", "ML", "ME", "MG", "LL", "LE", "LG"]


def generate_buy_xactions(n_cust: int = 400, days: int = 210,
                          daily_frac: float = 0.05, seed: int = 29
                          ) -> List[List[str]]:
    """resource/buy_xaction.rb behavior: per day a fraction of customers
    transacts; the amount depends on recency and prior amount (short gaps
    -> small corrective buys, long gaps -> large restock buys). Rows:
    (custID, xid, date-ordinal, amount), unordered like the raw feed."""
    rng = np.random.default_rng(seed)
    last: dict = {}
    rows: List[List[str]] = []
    xid = 0
    for day in range(days):
        k = int(daily_frac * n_cust * (85 + rng.integers(0, 30)) / 100)
        for c in rng.integers(0, n_cust, k):
            cid = f"C{c:09d}"
            if cid in last:
                gap = day - last[cid][0]
                amt_pr = last[cid][1]
                if gap < 30:
                    amt = (50 if amt_pr < 40 else 30) + int(rng.integers(-10, 10))
                elif gap < 60:
                    amt = (100 if amt_pr < 80 else 60) + int(rng.integers(-20, 20))
                else:
                    amt = (180 if amt_pr < 150 else 120) + int(rng.integers(-30, 30))
            else:
                amt = 40 + int(rng.integers(0, 180))
            amt = max(amt, 5)
            last[cid] = (day, amt)
            rows.append([cid, f"X{xid:09d}", str(day), str(amt)])
            xid += 1
    return rows


def xactions_to_state_sequences(rows: List[List[str]]
                                ) -> List[List[str]]:
    """The Projection-MR + xaction_state.rb steps in one: group
    transactions per customer ordered by date, then encode each
    consecutive pair as a 2-char state — days-gap S/M/L (<30/<60/else) x
    amount-ratio L/E/G (prev <0.9x / within 10% / >1.1x of current).
    Returns [custID, state, state, ...] rows for customers with >=2
    transactions."""
    hist: dict = {}
    for cid, _xid, date, amt in rows:
        hist.setdefault(cid, []).append((int(date), int(amt)))
    out = []
    for cid in hist:
        xs = sorted(hist[cid])
        if len(xs) < 2:
            continue
        seq = [cid]
        for (d0, a0), (d1, a1) in zip(xs[:-1], xs[1:]):
            gap = d1 - d0
            dd = "S" if gap < 30 else ("M" if gap < 60 else "L")
            ad = "L" if a0 < 0.9 * a1 else ("E" if a0 < 1.1 * a1 else "G")
            seq.append(dd + ad)
        out.append(seq)
    return out


def generate_visit_history(n_users: int, conv_rate: int = 10,
                           labeled: bool = True, seed: int = 31
                           ) -> List[List[str]]:
    """resource/visit_history.py behavior: per user a page-visit session
    sequence of 2-char states (elapsed-time x duration, H/M/L each);
    converted users trend low-elapsed/high-duration, non-converted the
    reverse. Rows: [userID, label?, state...]."""
    rng = np.random.default_rng(seed)
    out: List[List[str]] = []
    for i in range(n_users):
        converted = rng.integers(0, 100) < conv_rate
        row = [f"U{i:011d}"]
        if labeled:
            truthful = rng.integers(0, 100) < 90
            row.append("T" if converted == truthful else "F")
        if converted:
            n_sess = int(rng.integers(2, 21))
            el_p, du_p = [0.15, 0.25, 0.60], [0.15, 0.25, 0.60]
            el_v, du_v = ["H", "M", "L"], ["L", "M", "H"]
        else:
            n_sess = int(rng.integers(2, 13))
            el_p, du_p = [0.20, 0.25, 0.55], [0.20, 0.25, 0.55]
            el_v, du_v = ["L", "M", "H"], ["H", "M", "L"]
        for _ in range(n_sess):
            row.append(str(rng.choice(el_v, p=el_p))
                       + str(rng.choice(du_v, p=du_p)))
        out.append(row)
    return out
