"""Block-aligned columnar sidecar: parse a corpus once, stream binary after.

The reference system's whole pipeline is a re-parse loop — every Hadoop job
re-reads delimited text from HDFS and re-splits every line (PAPER.md §0).
PR 10's stall attribution shows the same shape here: CSV parse dominates
cold scans, and the miners' EncodedBlockCache already proves the cure —
region-compacted narrowest-dtype codes replay ~2.5x smaller than the CSV
and skip parsing entirely. This module promotes that private cache into a
general, schema-aware sidecar ANY fold family's repeat scan streams from:

    <dir>/.avenir_sidecar/<basename>.<digest8>/
        MANIFEST.json    atomic (tmp+rename LAST), content-fingerprinted
        columns.bin      per-block packed column segments

Two kinds share one manifest/segment shape:

- ``dataset``: each newline-aligned block of a schema-typed CSV packs per
  column — numeric float32 pages, DECLARED categorical codes at the
  narrowest dtype that fits the cardinality, and string / data-discovered
  categorical columns as the native parser's own compact newline-joined
  token buffers — so replay rebuilds the exact Dataset chunk (including
  the schema-discovery side effects and lazy string thunks) the native
  parser would have produced, without touching the CSV text.
- ``bytes``: each block stores per-row tail-token counts plus the tail
  codes against a sidecar-discovered vocabulary (code+1, 0 = the empty
  token) and the skipped meta columns as raw token buffers, reusing
  BlockScanEncoder's region compaction — the CSR consumers (markov
  fit_csr, the Apriori/GSP discovery scans) rebuild their per-block
  arrays from codes alone.

Trust contract: a manifest is served ONLY after a content re-proof — every
replayed block's (offset, length, hash) fingerprint re-verifies against
the current file bytes (core.incremental.verified_prefix, memoized per
file snapshot), NEVER an mtime shortcut. A verified proper prefix plus a
newline-ending coverage point replays the prefix and re-parses (and
appends) only the tail; an in-place edit invalidates from the edit point;
a torn write never commits (the manifest is written last, and cold
segment writes land under tmp+rename). Every failure path degrades to the
cold parse — the sidecar can make a scan faster, never wrong.

Concurrency contract — last-write-wins: the sidecar is a CACHE, so two
concurrent packers of the same corpus may each publish a manifest and
the later atomic replace wins; the loser's work is wasted, never wrong,
because every served manifest re-proves against the current corpus
bytes. A reader racing the warm store's eviction degrades the same way:
a replay that loses its segment mid-scan finishes COLD from the last
yielded block boundary (graftlint --race, warm.evict site).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import struct
import threading
from typing import Iterator, List, Optional, Tuple

import numpy as np

from avenir_tpu import obs as _obs
from avenir_tpu.core.atomic import (publish_bytes, sched_point,
                                    sweep_stale_tmps)
from avenir_tpu.core.incremental import (block_fingerprint, ends_at_newline,
                                         verified_prefix)

FORMAT = 1
MANIFEST = "MANIFEST.json"
SEGMENT = "columns.bin"
SIDECAR_DIRNAME = ".avenir_sidecar"
#: default on-disk budget per sidecar directory — like the miner cache's,
#: generous but FINITE (an unbudgeted spill is the mem-cache-spill-
#: unbudgeted hazard); `stream.sidecar.budget.mb` overrides per job
DEFAULT_BUDGET_BYTES = 4 << 30

_ENC_DTYPES = {0: np.uint8, 1: np.uint16, 2: np.uint32}


def _dtype_code(max_value: int) -> int:
    if max_value < (1 << 8):
        return 0
    if max_value < (1 << 16):
        return 1
    return 2


# --------------------------------------------------------------------------
# process-global hit/delta counters (JobResult counter surface)
# --------------------------------------------------------------------------
_count_lock = threading.Lock()
_counters = {"hit_blocks": 0, "delta_blocks": 0,
             "hit_bytes": 0, "parse_bytes": 0}


def _count(key: str, n: int = 1) -> None:
    with _count_lock:
        _counters[key] += n


def counters_snapshot() -> dict:
    """Snapshot of the process-global sidecar counters — the runner takes
    one before and one after a scan and reports the delta as the job's
    ``Sidecar:HitBlocks`` / ``Sidecar:DeltaBlocks`` counters."""
    with _count_lock:
        return dict(_counters)


# --------------------------------------------------------------------------
# config surface
# --------------------------------------------------------------------------
def opts_from_cfg(cfg) -> Optional[dict]:
    """The sidecar knobs of one job config, or None when the feature is
    off (`stream.sidecar=false`, the kill switch)."""
    if not cfg.get_bool("stream.sidecar", True):
        return None
    return {"dir": cfg.get("stream.sidecar.dir"),
            "budget": int(cfg.get_float(
                "stream.sidecar.budget.mb",
                float(DEFAULT_BUDGET_BYTES >> 20)) * (1 << 20))}


# --------------------------------------------------------------------------
# digests and directory layout
# --------------------------------------------------------------------------
def schema_digest(schema) -> str:
    """Content digest of a FeatureSchema, NORMALIZED so data-discovery
    side effects don't shift it: a field whose cardinality / numeric max
    was discovered from data hashes as if still undiscovered — the same
    schema object before and after a scan (or a fresh reload of the same
    JSON) must land on the same sidecar."""
    fields = []
    for f in schema.to_json()["fields"]:
        f = dict(f)
        if f.pop("discoveredCardinality", False):
            f.pop("cardinality", None)
        if f.pop("discoveredRange", False):
            f.pop("max", None)
        fields.append(f)
    blob = json.dumps(fields, sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()


def _config_digest(kind: str, delim: str, block_bytes: int,
                   extra: str) -> str:
    from avenir_tpu.core.keys import sidecar_config_digest

    return sidecar_config_digest(FORMAT, kind, delim, block_bytes, extra)


def dataset_dir(opts: dict, path: str, schema, delim: str,
                block_bytes: int) -> str:
    """key-covered: all — the digest is the whole dataset parse view."""
    from avenir_tpu.core.keys import key_site

    key_site("sidecar.dataset")
    return _dir_for(opts, path, _config_digest(
        "dataset", delim, block_bytes, schema_digest(schema)))


def bytes_dir(opts: dict, path: str, delim: str, skip: int,
              block_bytes: int) -> str:
    """key-covered: all — the digest is the whole bytes parse view."""
    from avenir_tpu.core.keys import key_site

    key_site("sidecar.bytes")
    return _dir_for(opts, path, _config_digest(
        "bytes", delim, block_bytes, str(int(skip))))


def _dir_for(opts: dict, path: str, digest: str) -> str:
    path = os.path.abspath(path)
    base = opts.get("dir") if opts else None
    if base:
        # an override base pools many corpora: disambiguate same-named
        # files from different directories by a path hash
        tag = hashlib.sha1(path.encode()).hexdigest()[:8]
        return os.path.join(base,
                            f"{os.path.basename(path)}.{tag}.{digest[:8]}")
    return os.path.join(os.path.dirname(path), SIDECAR_DIRNAME,
                        f"{os.path.basename(path)}.{digest[:8]}")


# --------------------------------------------------------------------------
# manifest IO + content re-proof
# --------------------------------------------------------------------------
def _load_manifest(dirpath: str) -> Optional[dict]:
    try:
        with open(os.path.join(dirpath, MANIFEST)) as fh:
            man = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(man, dict) or man.get("format") != FORMAT \
            or not isinstance(man.get("blocks"), list):
        return None
    if man.get("format_version", FORMAT) != FORMAT:
        # version-skewed manifest: refuse to serve, go cold (a MISSING
        # stamp is a pre-versioning sidecar and still serves — the
        # "format" gate above already pins its layout)
        return None
    return man


def _write_manifest(dirpath: str, man: dict) -> None:
    # the manifest rename IS the sidecar commit point: the fsync'd
    # payload lands via unique sibling tmp + replace, so a reader sees
    # the old manifest or the new one, never a torn table
    sched_point("sidecar.manifest")
    publish_bytes(json.dumps(man).encode("utf-8"),
                  os.path.join(dirpath, MANIFEST),
                  site="sidecar.manifest", fsync=True)


_verify_lock = threading.Lock()
_verify_memo: dict = {}


def _verified_blocks(dirpath: str, man: dict, path: str
                     ) -> Tuple[int, int]:
    """(n_blocks, covered_end): how many of the manifest's blocks are a
    verified CONTENT prefix of the current file — re-hashed through
    core.incremental.verified_prefix, memoized per (manifest, file)
    snapshot so repeat scans prove once, not once per scan. Never an
    mtime-only shortcut: the memo key only short-circuits the re-hash
    while both the manifest and the file bytes' stat identity hold."""
    try:
        st = os.stat(path)
        mst = os.stat(os.path.join(dirpath, MANIFEST))
    except OSError:
        return 0, 0
    key = (dirpath, mst.st_mtime_ns, mst.st_size, st.st_size, st.st_mtime_ns)
    with _verify_lock:
        if key in _verify_memo:
            return _verify_memo[key]
    fps = [{"offset": b["offset"], "length": b["length"], "hash": b["hash"]}
           for b in man["blocks"]]
    n_ok, covered = verified_prefix(path, fps)
    # the segment must still hold every verified block's extent (a torn
    # or concurrently-rewritten segment reads as absent, not as garbage)
    need = 0
    for b in man["blocks"][:n_ok]:
        need = max(need, int(b["seg_off"]) + int(b["seg_len"]))
    try:
        if os.path.getsize(os.path.join(dirpath, SEGMENT)) < need:
            n_ok, covered = 0, 0
    except OSError:
        if need > 0:
            n_ok, covered = 0, 0
    with _verify_lock:
        if len(_verify_memo) > 512:
            _verify_memo.clear()
        _verify_memo[key] = (n_ok, covered)
    return n_ok, covered


def verified_offsets(dirpath: str, path: str,
                     block_bytes: int) -> List[int]:
    """Sorted block START offsets of the verified manifest prefix — the
    newline-aligned cut candidates the shard planner snaps its block
    boundaries to so workers can replay their claimed ranges."""
    man = _load_manifest(dirpath)
    if man is None or int(man.get("block_bytes", -1)) != int(block_bytes):
        return []
    n_ok, _cov = _verified_blocks(dirpath, man, path)
    return [int(b["offset"]) for b in man["blocks"][:n_ok]]


def sidecar_nbytes(dirpath: str) -> int:
    """On-disk footprint of one sidecar directory (manifest + segment)."""
    total = 0
    for name in (MANIFEST, SEGMENT):
        try:
            total += os.path.getsize(os.path.join(dirpath, name))
        except OSError:
            pass
    return total


# --------------------------------------------------------------------------
# dataset kind: pack / unpack one parsed block
# --------------------------------------------------------------------------
def _pack_dataset_block(data: bytes, ds, schema, delim: str, fh) -> Optional[list]:
    """Write one parsed block's columns to the open segment; returns the
    per-column layout list, or None when the native column extraction is
    unavailable (the caller aborts the sidecar, never the scan).

    Classification mirrors Dataset._from_native_data: numerics as raw
    float32 pages, categoricals with a DECLARED fixed vocabulary as
    narrowest-dtype codes, everything else (strings, ids, discovered
    categoricals) as the native parser's compact newline-joined token
    buffer extracted from the RAW block — so replay re-runs the same
    discovery/encode the cold parse would, against the reader's own
    schema object."""
    from avenir_tpu.native.ingest import extract_column_raw

    cols = []
    for fld in schema.fields:
        o = fld.ordinal
        if fld.is_numeric:
            buf = np.ascontiguousarray(
                ds.column(o), dtype=np.float32).tobytes()
            kind, dt = "f", 0
        elif fld.is_categorical and fld.cardinality \
                and not fld.discovered_cardinality:
            dt = _dtype_code(max(len(fld.cardinality) - 1, 0))
            buf = np.ascontiguousarray(
                ds.column(o)).astype(_ENC_DTYPES[dt]).tobytes()
            kind = "c"
        else:
            raw = extract_column_raw(data, delim, o)
            if raw is None:
                return None
            buf, kind, dt = raw, "t", 0
        fh.write(buf)
        cols.append([o, kind, dt, len(buf)])
    return cols


def _unpack_dataset_block(buf: bytes, entry: dict, schema, delim: str):
    """Rebuild the Dataset chunk the native parser would have produced
    for this block — including the schema-discovery side effects
    (_discover_cardinality / _discover_numeric_range) and the lazy
    string-column thunks, so downstream folds are byte-identical."""
    from avenir_tpu.core.dataset import (Dataset, _discover_cardinality,
                                         _discover_numeric_range)

    n = int(entry["rows"])
    columns, lazy = {}, {}
    pos = 0
    for o, kind, dt, nb in entry["cols"]:
        part = buf[pos:pos + nb]
        pos += nb
        if kind == "f":
            columns[o] = np.frombuffer(part, np.float32).copy()
        elif kind == "c":
            columns[o] = np.frombuffer(
                part, _ENC_DTYPES[int(dt)]).astype(np.int32)
        else:
            fld = schema.field_by_ordinal(o)
            if fld.is_categorical:
                toks = part.decode().split("\n")[:-1]
                _discover_cardinality(fld, toks)
                index = fld.cardinality_index()
                columns[o] = np.array([index[t] for t in toks], np.int32)
            else:
                lazy[o] = (lambda r=part: np.array(
                    r.decode().split("\n")[:-1], dtype=object))
    for fld in schema.fields:
        if fld.is_numeric and fld.ordinal in columns:
            _discover_numeric_range(fld, columns[fld.ordinal])
    return Dataset(schema, columns, n, lazy=lazy)


# --------------------------------------------------------------------------
# bytes kind: pack / unpack one encoded block
# --------------------------------------------------------------------------
class SidecarBytesBlock:
    """One replayed bytes-kind block: per-row TAIL token counts, the tail
    codes against the manifest's sidecar vocabulary shifted by one
    (stored 0 = the empty token), the skipped meta columns as token
    lists, and the vocabulary watermark after this block (``vocab_end``,
    what makes first-seen-order vocabulary extension replayable). The
    CSR consumers (_MarkovPerClassFold.consume_encoded, the miners'
    SpillScanMixin._scan_encoded_block) dispatch on this type."""

    __slots__ = ("n", "counts", "codes", "meta", "vocab", "vocab_end",
                 "skip", "nbytes")

    def __init__(self, n, counts, codes, meta, vocab, vocab_end, skip,
                 nbytes):
        self.n = n
        self.counts = counts          # int64 [n] tail tokens per row
        self.codes = codes            # int32 [sum(counts)] code+1, 0=empty
        self.meta = meta              # list[skip] of token lists
        self.vocab = vocab            # the manifest's full vocab (shared)
        self.vocab_end = vocab_end    # vocab size after this block
        self.skip = skip
        self.nbytes = nbytes          # source-block byte length


class _SidecarAbort(Exception):
    """Internal: this file cannot be (further) packed — drop the writer,
    keep scanning cold."""


def _pack_bytes_block(data: bytes, enc, skip: int, delim: str,
                      fh) -> Tuple[dict, int]:
    """Encode one raw block with the sidecar-owned discovering encoder
    and write counts + shifted tail codes + raw meta columns; returns
    (entry extras, bytes written). Raises _SidecarAbort on rows shorter
    than the skip count or unresolvable tokens — shapes the compact
    format cannot represent losslessly."""
    from avenir_tpu.native.ingest import (csr_region_mask,
                                          extract_column_raw)

    out = enc.encode(data)
    if out is None:
        return {"rows": 0, "vocab_end": len(enc.vocab)}, 0
    codes, offsets, _region, n = out
    lens = np.diff(offsets)
    if (lens < skip).any():
        raise _SidecarAbort("row shorter than the meta skip count")
    v = len(enc.vocab)
    tail_mask = csr_region_mask(offsets, skip, codes.shape[0]) \
        if skip else np.ones(codes.shape[0], bool)
    tail = codes[tail_mask]
    if (tail < 0).any():
        raise _SidecarAbort("unresolvable token")
    stored = np.where(tail >= v, 0, tail + 1)
    counts = (lens - skip).astype(np.int64)
    cd = _dtype_code(int(counts.max(initial=0)))
    kd = _dtype_code(int(stored.max(initial=0)))
    wrote = 0
    buf = counts.astype(_ENC_DTYPES[cd]).tobytes()
    fh.write(buf)
    wrote += len(buf)
    buf = stored.astype(_ENC_DTYPES[kd]).tobytes()
    fh.write(buf)
    wrote += len(buf)
    meta_lens = []
    for j in range(skip):
        raw = extract_column_raw(data, delim, j)
        if raw is None:
            raise _SidecarAbort("native column extraction unavailable")
        fh.write(raw)
        wrote += len(raw)
        meta_lens.append(len(raw))
    return {"rows": int(n), "vocab_end": int(v), "counts_dtype": cd,
            "codes_dtype": kd, "n_codes": int(stored.shape[0]),
            "meta_lens": meta_lens}, wrote


def _unpack_bytes_block(buf: bytes, entry: dict, vocab: List[str],
                        skip: int) -> SidecarBytesBlock:
    n = int(entry["rows"])
    pos = 0
    cd, kd = int(entry["counts_dtype"]), int(entry["codes_dtype"])
    nb = n * _ENC_DTYPES[cd]().itemsize
    counts = np.frombuffer(buf[pos:pos + nb], _ENC_DTYPES[cd]).astype(
        np.int64)
    pos += nb
    nk = int(entry["n_codes"])
    nb = nk * _ENC_DTYPES[kd]().itemsize
    codes = np.frombuffer(buf[pos:pos + nb], _ENC_DTYPES[kd]).astype(
        np.int32)
    pos += nb
    meta = []
    for ml in entry.get("meta_lens", []):
        meta.append(buf[pos:pos + ml].decode().split("\n")[:-1])
        pos += ml
    return SidecarBytesBlock(n, counts, codes, meta, vocab,
                             int(entry["vocab_end"]), skip,
                             int(entry["length"]))


# --------------------------------------------------------------------------
# the feeds
# --------------------------------------------------------------------------
def dataset_blocks(opts: Optional[dict], path: str, schema, delim: str,
                   block_bytes: int,
                   byte_range: Optional[Tuple[int, int]] = None,
                   write: bool = True):
    """Sidecar-aware block feed over a schema-typed CSV. Yields
    (offset, length, hash, payload) tuples tiling the range gap-free:
    payload is a parsed Dataset (replayed from the sidecar or parsed
    cold — cold blocks also PACK into the sidecar when `write`), or
    None for a whitespace-only block. Returns None when the sidecar
    machinery cannot engage at all (disabled, python-only parse path,
    multi-byte delimiter) — callers keep their historical cold feed.
    With write=False the feed engages only when the WHOLE range replays
    from verified sidecar blocks (the ranged shard-worker contract)."""
    from avenir_tpu.native.ingest import native_available

    if opts is None or not native_available() \
            or len(delim.encode()) != 1:
        return None
    try:
        dirpath = dataset_dir(opts, path, schema, delim, block_bytes)
        return _feed(opts, "dataset", path, dirpath, block_bytes,
                     byte_range, write,
                     {"delim": delim, "schema": schema})
    except Exception:
        return None


def byte_blocks(opts: Optional[dict], path: str, delim: str, skip: int,
                block_bytes: int,
                byte_range: Optional[Tuple[int, int]] = None,
                write: bool = True):
    """Sidecar-aware raw-block feed for the CSR consumers. Same tuple
    contract as dataset_blocks, with payload a SidecarBytesBlock on
    replay and the RAW bytes on a cold block (consumers encode those
    themselves; the feed packs them into the sidecar when `write`)."""
    from avenir_tpu.native.ingest import native_seq_ready

    if opts is None or skip < 0 or not native_seq_ready(delim):
        return None
    try:
        dirpath = bytes_dir(opts, path, delim, skip, block_bytes)
        return _feed(opts, "bytes", path, dirpath, block_bytes,
                     byte_range, write, {"delim": delim, "skip": skip})
    except Exception:
        return None


def _base_manifest(kind: str, path: str, block_bytes: int,
                   kp: dict) -> dict:
    man = {"format": FORMAT, "format_version": FORMAT, "kind": kind,
           "block_bytes": int(block_bytes), "delim": kp["delim"],
           "source": os.path.abspath(path)}
    if kind == "dataset":
        man["schema_digest"] = schema_digest(kp["schema"])
    else:
        man["skip"] = int(kp["skip"])
        man["vocab"] = []
    return man


def _manifest_matches(man: dict, kind: str, block_bytes: int,
                      kp: dict) -> bool:
    if man.get("kind") != kind or man.get("delim") != kp["delim"]:
        return False
    # the block-size gate: a sidecar only serves scans requesting the
    # layout it tiled — distinct stream.block.size.mb configs stay
    # distinct corpora (the chunk-invariance auditor depends on it)
    if int(man.get("block_bytes", -1)) != int(block_bytes):
        return False
    if kind == "dataset":
        return man.get("schema_digest") == schema_digest(kp["schema"])
    return int(man.get("skip", -1)) == int(kp["skip"]) \
        and isinstance(man.get("vocab"), list)


def _feed(opts, kind, path, dirpath, block_bytes, byte_range, write, kp):
    size = os.path.getsize(path)
    start, end = byte_range if byte_range is not None else (0, size)
    end = min(end, size)
    man = _load_manifest(dirpath)
    if man is not None and not _manifest_matches(man, kind, block_bytes,
                                                 kp):
        man = None
    n_ok, covered = (0, 0)
    if man is not None:
        n_ok, covered = _verified_blocks(dirpath, man, path)
        if n_ok == 0:
            man = None
    # the entries replayable for [start, ...): a contiguous run of
    # verified blocks whose first entry starts EXACTLY at `start`
    replay: list = []
    if man is not None:
        ents = man["blocks"][:n_ok]
        i0 = next((i for i, b in enumerate(ents)
                   if int(b["offset"]) == start), None)
        if i0 is not None:
            for b in ents[i0:]:
                if int(b["offset"]) + int(b["length"]) > end:
                    break
                replay.append(b)
    rep_end = (int(replay[-1]["offset"]) + int(replay[-1]["length"])
               ) if replay else start
    if rep_end < end and replay:
        # a replay/parse splice point must sit on a line boundary, or
        # the first cold line would split in two
        if not ends_at_newline(path, rep_end):
            replay, rep_end = [], start
    if not write:
        if not replay or rep_end < end:
            return None            # ranged readers replay all or nothing
        return _replay_only(path, dirpath, man, replay, kind, kp,
                            block_bytes, end)
    # write mode: extension is legal only when the cold tail starts
    # exactly where verified coverage ends (manifest blocks must tile
    # gap-free from their first offset) and the range runs to EOF
    extend = None
    if rep_end >= end:
        pass                        # full replay, nothing to write
    elif man is None:
        if start == 0 and end == size:
            extend = "fresh"
    elif rep_end == covered and end == size:
        extend = "append"
    return _feed_gen(opts, kind, path, dirpath, man, replay, rep_end, end,
                     block_bytes, extend, kp)


def _replay_entries(path, dirpath, man, entries, kind, kp):
    """Yield the 4-tuples of a verified entry run, reading the segment
    sequentially. Blank (zero-row) entries yield payload None."""
    vocab = man.get("vocab") if kind == "bytes" else None
    seg = os.path.join(dirpath, SEGMENT)
    sched_point("sidecar.replay")
    fh = open(seg, "rb") if any(int(b["seg_len"]) for b in entries) \
        else None
    try:
        for b in entries:
            off, length = int(b["offset"]), int(b["length"])
            if int(b["rows"]) <= 0:
                yield off, length, b["hash"], None
                continue
            t0 = _obs.now()
            sched_point("sidecar.replay")
            fh.seek(int(b["seg_off"]))
            buf = fh.read(int(b["seg_len"]))
            if len(buf) != int(b["seg_len"]):
                raise RuntimeError(
                    f"sidecar segment truncated under replay: {seg}")
            if kind == "dataset":
                payload = _unpack_dataset_block(buf, b, kp["schema"],
                                                kp["delim"])
            else:
                payload = _unpack_bytes_block(buf, b, vocab, kp["skip"])
            _obs.record("stream.sidecar.replay", t0, path=path,
                        nbytes=length, rows=int(b["rows"]))
            _count("hit_blocks")
            _count("hit_bytes", length)
            yield off, length, b["hash"], payload
    finally:
        if fh is not None:
            fh.close()


def _replay_only(path, dirpath, man, entries, kind, kp, block_bytes,
                 end):
    """The write=False feed: a pure replay run — except that the warm
    store may EVICT the sidecar directory mid-replay (SidecarHandle
    eviction is whole-directory rmtree, racing any open scan). The
    replayed prefix stays valid — every yielded block was verified
    against the live corpus bytes — so the scan finishes COLD from the
    last yielded boundary instead of crashing the consumer."""
    cursor = int(entries[0]["offset"])
    try:
        for off, length, bhash, payload in _replay_entries(
                path, dirpath, man, entries, kind, kp):
            yield off, length, bhash, payload
            cursor = off + length
    except (OSError, RuntimeError):
        yield from _cold_tail(path, cursor, end, block_bytes, kind, kp,
                              None)


def _feed_gen(opts, kind, path, dirpath, man, replay, rep_end, end,
              block_bytes, extend, kp):
    """The full feed: verified replay prefix, then the cold tail —
    parsed (dataset) or raw (bytes) — packed into the sidecar when
    `extend` says the tiling stays gap-free. Writer failures abort the
    sidecar, never the scan; and a replay failure (the warm store
    evicting this sidecar under an open scan) degrades to a cold
    finish from the last yielded block boundary — entry boundaries
    come from the verified tiling, so the splice is newline-aligned by
    construction — never a consumer crash."""
    if replay:
        cursor = int(replay[0]["offset"])
        try:
            for off, length, bhash, payload in _replay_entries(
                    path, dirpath, man, replay, kind, kp):
                yield off, length, bhash, payload
                cursor = off + length
        except (OSError, RuntimeError):
            yield from _cold_tail(path, cursor, end, block_bytes, kind,
                                  kp, None)
            return
    if rep_end >= end:
        return
    writer = None
    if extend is not None:
        try:
            writer = _Writer(opts, kind, path, dirpath, man, block_bytes,
                             kp, fresh=extend == "fresh")
        except Exception:
            writer = None
    yield from _cold_tail(path, rep_end, end, block_bytes, kind, kp,
                          writer)


def _cold_tail(path, start, end, block_bytes, kind, kp, writer):
    """The cold half of a feed: every block in ``[start, end)`` parsed
    (dataset) or handed through raw (bytes), packed into `writer` when
    one is given. Writer failures abort the sidecar, never the scan."""
    from avenir_tpu.core.dataset import Dataset
    from avenir_tpu.core.stream import (is_blank_block, iter_byte_blocks,
                                        prefetched)

    if start >= end:
        return
    blocks = prefetched(iter_byte_blocks(path, block_bytes,
                                         byte_range=(start, end),
                                         with_offsets=True), depth=1)
    try:
        for off, data in blocks:
            fp = block_fingerprint(off, data)
            if is_blank_block(data):
                if writer is not None:
                    writer = writer.add_blank(fp)
                yield off, len(data), fp["hash"], None
                continue
            if kind == "dataset":
                t0 = _obs.now()
                payload = Dataset.from_csv(data, kp["schema"],
                                           delim=kp["delim"])
                _obs.record("stream.parse", t0, path=path,
                            nbytes=len(data), rows=len(payload))
                if writer is not None:
                    writer = writer.add_dataset(fp, data, payload)
            else:
                payload = data
                if writer is not None:
                    writer = writer.add_bytes(fp, data)
            _count("delta_blocks")
            _count("parse_bytes", len(data))
            yield off, len(data), fp["hash"], payload
    except BaseException:
        if writer is not None:
            writer.abort()
            writer = None
        raise
    finally:
        blocks.close()
        if writer is not None:
            writer.commit()


class _Writer:
    """One write (or append) pass over a sidecar directory.

    Crash/abort safety: a FRESH write stages the segment as a temp file
    and deletes any stale manifest up front, so a torn pass leaves no
    manifest at all (cold next time); the manifest lands LAST, tmp+
    rename, after the finished segment is renamed into place. An APPEND
    truncates the segment back to the verified coverage point, extends
    it in place, and rewrites the manifest last — a crash mid-append
    leaves the OLD manifest, whose blocks still verify against their
    intact extents. Exceeding the byte budget kills the pass (the
    sidecar is a bounded cache, not a second corpus)."""

    def __init__(self, opts, kind, path, dirpath, man, block_bytes, kp,
                 fresh):
        os.makedirs(dirpath, exist_ok=True)
        # startup GC: tmp files a hard-killed writer left behind (the
        # age gate keeps a concurrent writer's live tmp safe)
        sweep_stale_tmps(dirpath)
        self.dirpath = dirpath
        self.kind = kind
        self.kp = kp
        self.budget = int(opts.get("budget") or DEFAULT_BUDGET_BYTES)
        self.encoder = None
        self._tmp = None
        if fresh:
            try:
                os.remove(os.path.join(dirpath, MANIFEST))
            except OSError:
                pass
            self.man = _base_manifest(kind, path, block_bytes, kp)
            self.entries: list = []
            self._tmp = os.path.join(dirpath,
                                     f"{SEGMENT}.tmp.{os.getpid()}")
            self._fh = open(self._tmp, "wb")
            self.seg_pos = 0
        else:
            self.man = dict(man)
            keep = self.man["blocks"][:len(man["blocks"])]
            # append resumes after the last entry the feed replayed /
            # verified — recompute from the replayed coverage point
            self.entries = []
            self._fh = None
            self._keep_source = keep
            self.seg_pos = 0
        if kind == "bytes":
            from avenir_tpu.native.ingest import BlockScanEncoder

            vocab = list(self.man.get("vocab", []))
            self.man["vocab"] = vocab
            self.encoder = BlockScanEncoder(
                kp["delim"], kp["skip"], vocab,
                {t: i for i, t in enumerate(vocab)}, marker=None)

    def _open_append(self, first_offset: int) -> None:
        keep = [b for b in self._keep_source
                if int(b["offset"]) + int(b["length"]) <= first_offset]
        seg_end = 0
        for b in keep:
            seg_end = max(seg_end, int(b["seg_off"]) + int(b["seg_len"]))
        self.man["blocks"] = keep
        segp = os.path.join(self.dirpath, SEGMENT)
        self._fh = open(segp, "r+b" if os.path.exists(segp) else "w+b")
        self._fh.truncate(seg_end)
        self._fh.seek(seg_end)
        self.seg_pos = seg_end

    def _add(self, fp, extra, wrote) -> "_Writer":
        entry = dict(fp)
        entry["seg_off"] = self.seg_pos
        entry["seg_len"] = wrote
        entry.update(extra)
        self.seg_pos += wrote
        self.entries.append(entry)
        if self.seg_pos > self.budget:
            self.abort()
            return None
        return self

    def _ensure_open(self, fp) -> None:
        if self._fh is None:
            self._open_append(int(fp["offset"]))

    def add_blank(self, fp) -> Optional["_Writer"]:
        try:
            self._ensure_open(fp)
            extra = {"rows": 0}
            if self.kind == "bytes":
                extra["vocab_end"] = len(self.man["vocab"])
            return self._add(fp, extra, 0)
        except Exception:
            self.abort()
            return None

    def add_dataset(self, fp, data, ds) -> Optional["_Writer"]:
        try:
            self._ensure_open(fp)
            cols = _pack_dataset_block(data, ds, self.kp["schema"],
                                       self.kp["delim"], self._fh)
            if cols is None:
                self.abort()
                return None
            wrote = sum(c[3] for c in cols)
            return self._add(fp, {"rows": int(len(ds)), "cols": cols},
                             wrote)
        except Exception:
            self.abort()
            return None

    def add_bytes(self, fp, data) -> Optional["_Writer"]:
        try:
            self._ensure_open(fp)
            extra, wrote = _pack_bytes_block(data, self.encoder,
                                             self.kp["skip"],
                                             self.kp["delim"], self._fh)
            return self._add(fp, extra, wrote)
        except Exception:
            self.abort()
            return None

    def commit(self) -> bool:
        if self._fh is None:       # append pass that saw no blocks
            return False
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            self._fh = None
            if self._tmp is not None:
                os.replace(self._tmp, os.path.join(self.dirpath, SEGMENT))
                self._tmp = None
            man = dict(self.man)
            man["blocks"] = list(self.man.get("blocks", [])) + self.entries
            man["segment_bytes"] = max(
                [self.seg_pos] + [int(b["seg_off"]) + int(b["seg_len"])
                                  for b in man["blocks"]])
            _write_manifest(self.dirpath, man)
            return True
        except Exception:
            self.abort()
            return False

    def abort(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except Exception:
                pass
            self._fh = None
        if self._tmp is not None:
            try:
                os.remove(self._tmp)
            except OSError:
                pass
            self._tmp = None


# --------------------------------------------------------------------------
# warm-store handle (resident job server)
# --------------------------------------------------------------------------
class SidecarHandle:
    """A pinnable handle on one sidecar directory, speaking the same
    warm-source protocol as the miners' streaming sources so the job
    server's WarmStore can hold sidecars under its existing byte budget
    with the same exclusive-checkout / whole-entry-eviction semantics:
    ``cache_ready()`` re-proves the manifest against the current corpus
    bytes, ``cache_nbytes`` prices the pin, ``close()`` EVICTS — it
    deletes the sidecar directory (an in-flight scan holding the open
    segment fd finishes unharmed, POSIX-style; the next scan goes cold
    and repacks)."""

    #: the pinned state is a durable cross-run disk cache, not a
    #: process resource: the store may drop the PIN without close() at
    #: shutdown (or when re-pinning the same directory) — only a budget
    #: eviction or a staleness drop should delete the directory
    cache_durable = True

    def __init__(self, path: str, dirpath: str):
        self.path = os.path.abspath(path)
        self.dirpath = dirpath

    def cache_ready(self) -> bool:
        man = _load_manifest(self.dirpath)
        if man is None:
            return False
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return False
        n_ok, covered = _verified_blocks(self.dirpath, man, self.path)
        return n_ok == len(man["blocks"]) and covered == size

    @property
    def cache_nbytes(self) -> int:
        return sidecar_nbytes(self.dirpath)

    def cache_evict_to(self, byte_budget: int) -> int:
        """Sidecar segments are one unit — partial trims aren't
        representable, so anything under the full size evicts whole."""
        nb = self.cache_nbytes
        if nb <= byte_budget:
            return 0
        self.close()
        return nb

    def close(self) -> None:
        sched_point("warm.evict")
        shutil.rmtree(self.dirpath, ignore_errors=True)
