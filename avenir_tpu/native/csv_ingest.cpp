// Fast columnar CSV ingest for avenir_tpu.
//
// The reference's ingest is the Hadoop InputFormat + per-mapper
// line.split() (e.g. bayesian/BayesianDistribution.java:137); the TPU
// framework replaces HDFS splits with host CSV -> device arrays, and this
// library makes that host step native: one pass over the byte buffer
// producing float32 numeric columns and dictionary-encoded int32
// categorical columns directly (no Python string objects per field).
//
// Exposed via ctypes (no pybind11 in the image); see
// avenir_tpu/native/ingest.py for the Python contract.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// Trim ASCII whitespace in [b, e).
inline void trim(const char*& b, const char*& e) {
    while (b < e && (*b == ' ' || *b == '\t' || *b == '\r')) ++b;
    while (e > b && (e[-1] == ' ' || e[-1] == '\t' || e[-1] == '\r')) --e;
}

// Allocation-free categorical vocabulary: open-addressing over the value
// list, probed with (ptr, len) so the hot loop never constructs a
// std::string per token (the former unordered_map<string> lookup was the
// parse-rate bottleneck together with strtof).
struct Vocab {
    std::vector<std::string> values;
    std::vector<int32_t> slots;   // open addressing, -1 empty
    size_t mask = 0;

    static uint64_t hash(const char* b, size_t n) {
        uint64_t h = 1469598103934665603ull;          // FNV-1a
        for (size_t i = 0; i < n; ++i) {
            h ^= static_cast<unsigned char>(b[i]);
            h *= 1099511628211ull;
        }
        return h;
    }

    void build() {
        size_t cap = 8;
        while (cap < values.size() * 2) cap <<= 1;
        slots.assign(cap, -1);
        mask = cap - 1;
        for (size_t v = 0; v < values.size(); ++v) {
            size_t h = hash(values[v].data(), values[v].size()) & mask;
            while (slots[h] >= 0) h = (h + 1) & mask;
            slots[h] = static_cast<int32_t>(v);
        }
    }

    int32_t find(const char* b, size_t n) const {
        size_t h = hash(b, n) & mask;
        while (slots[h] >= 0) {
            const std::string& s = values[slots[h]];
            if (s.size() == n && memcmp(s.data(), b, n) == 0) return slots[h];
            h = (h + 1) & mask;
        }
        return -1;
    }
};

const double kPow10[10] = {1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9};

// Fast path for plain [+-]digits[.digits] tokens (the overwhelming CSV
// case); returns false for exponents/specials so the caller can fall back
// to strtof.
inline bool parse_float_fast(const char* b, const char* e, float* out) {
    bool neg = false;
    const char* p = b;
    if (p < e && (*p == '-' || *p == '+')) { neg = *p == '-'; ++p; }
    int64_t ip = 0;
    int nd = 0;
    while (p < e && *p >= '0' && *p <= '9') {
        if (nd == 18) return false;   // before the multiply: no signed overflow
        ip = ip * 10 + (*p - '0');
        ++p;
        ++nd;
    }
    if (nd == 0) return false;
    double v;
    if (p == e) {
        v = static_cast<double>(ip);
    } else {
        if (*p != '.') return false;
        ++p;
        int64_t fp = 0;
        int fd = 0;
        while (p < e && *p >= '0' && *p <= '9') {
            fp = fp * 10 + (*p - '0');
            ++p;
            if (++fd > 9) return false;
        }
        if (p != e) return false;
        v = static_cast<double>(ip) + static_cast<double>(fp) / kPow10[fd];
    }
    *out = static_cast<float>(neg ? -v : v);
    return true;
}

// Shared per-parse lookup tables (built once, read-only across threads).
struct ParseTables {
    std::vector<int8_t> kind;     // ordinal -> 0 none, 1 numeric, 2 cat
    std::vector<int32_t> slot;
    std::vector<Vocab> vocabs;
    int32_t max_ord;
};

// Parse rows in [p, end) writing global rows [row_base, row_base+max_rows).
// Returns rows parsed, or -1 (unknown categorical) / -2 (bad numeric) with
// err_row (global) / err_ord set.
int64_t parse_range(const char* p, const char* end, char delim,
                    const ParseTables& t, float* num_out, int32_t* cat_out,
                    int64_t n_rows, int64_t row_base, int64_t max_rows,
                    int64_t* err_row, int32_t* err_ord) {
    int64_t row = 0;
    while (p < end && row < max_rows) {
        const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
        const char* line_end = nl ? nl : end;
        {
            const char* b = p;
            const char* e = line_end;
            trim(b, e);
            if (e <= b) {  // blank line
                p = nl ? nl + 1 : end;
                continue;
            }
        }
        int32_t ord = 0;
        const char* fb = p;
        for (const char* q = p; q <= line_end; ++q) {
            if (q == line_end || *q == delim) {
                if (ord <= t.max_ord && t.kind[ord]) {
                    const char* b = fb;
                    const char* e = q;
                    trim(b, e);
                    if (t.kind[ord] == 1) {
                        float v;
                        if (e == b) {
                            v = __builtin_nanf("");
                        } else if (!parse_float_fast(b, e, &v)) {
                            // exponents/specials: fall back to strtof
                            char* endp = nullptr;
                            std::string tok(b, e - b);
                            v = strtof(tok.c_str(), &endp);
                            if (endp == tok.c_str() || *endp != '\0') {
                                *err_row = row_base + row;
                                *err_ord = ord;
                                return -2;
                            }
                        }
                        num_out[static_cast<int64_t>(t.slot[ord]) * n_rows
                                + row_base + row] = v;
                    } else {
                        int32_t code = t.vocabs[t.slot[ord]].find(b, e - b);
                        if (code < 0) {
                            *err_row = row_base + row;
                            *err_ord = ord;
                            return -1;
                        }
                        cat_out[static_cast<int64_t>(t.slot[ord]) * n_rows
                                + row_base + row] = code;
                    }
                }
                ++ord;
                fb = q + 1;
            }
        }
        ++row;
        p = nl ? nl + 1 : end;
    }
    return row;
}

ParseTables build_tables(int32_t max_ord, const int32_t* num_ords,
                         int32_t n_num, const int32_t* cat_ords,
                         int32_t n_cat, const char* vocab_blob,
                         const int32_t* vocab_counts) {
    ParseTables t;
    t.max_ord = max_ord;
    t.kind.assign(max_ord + 1, 0);
    t.slot.assign(max_ord + 1, -1);
    for (int32_t i = 0; i < n_num; ++i) {
        t.kind[num_ords[i]] = 1;
        t.slot[num_ords[i]] = i;
    }
    t.vocabs.resize(n_cat);
    const char* vp = vocab_blob;
    for (int32_t c = 0; c < n_cat; ++c) {
        t.kind[cat_ords[c]] = 2;
        t.slot[cat_ords[c]] = c;
        for (int32_t v = 0; v < vocab_counts[c]; ++v) {
            t.vocabs[c].values.emplace_back(vp);
            vp += strlen(vp) + 1;
        }
        t.vocabs[c].build();
    }
    return t;
}

// Count non-empty rows in [p, end).
int64_t count_range(const char* p, const char* end) {
    int64_t rows = 0;
    while (p < end) {
        const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
        const char* line_end = nl ? nl : end;
        const char* b = p;
        const char* e = line_end;
        trim(b, e);
        if (e > b) ++rows;
        p = nl ? nl + 1 : end;
    }
    return rows;
}

// Stripe [buf, buf+len) into n newline-aligned ranges; bounds[i..i+1]
// delimits stripe i.
std::vector<const char*> stripe_bounds(const char* buf, int64_t len,
                                       int32_t n) {
    std::vector<const char*> bounds(n + 1);
    bounds[0] = buf;
    bounds[n] = buf + len;
    for (int32_t i = 1; i < n; ++i) {
        const char* p = buf + len * i / n;
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', buf + len - p));
        bounds[i] = nl ? nl + 1 : buf + len;
    }
    return bounds;
}

// Run fn(i) on n threads; false if spawning failed (work may be partially
// done — callers must treat false as "redo sequentially").
template <typename Fn>
bool run_threads(int32_t n, Fn fn) {
    std::vector<std::thread> ts;
    ts.reserve(n);
    try {
        for (int32_t i = 0; i < n; ++i) ts.emplace_back([fn, i] { fn(i); });
    } catch (...) {
        // std::system_error from thread creation (pid/memory limits):
        // join what started, report failure — throwing across the
        // extern "C" boundary would std::terminate the host process
        for (auto& th : ts) th.join();
        return false;
    }
    for (auto& th : ts) th.join();
    return true;
}

}  // namespace

extern "C" {

// Count non-empty lines.
int64_t csv_count_rows(const char* buf, int64_t len) {
    return count_range(buf, buf + len);
}

// Parse the buffer in one pass.
//
// num_ords / n_num: field ordinals to parse as float32 into num_out
//   (column-major: num_out[c * n_rows + r]); empty tokens -> NaN, invalid
//   non-empty tokens abort with -2 (see return doc).
// cat_ords / n_cat: field ordinals to dictionary-encode into cat_out
//   (column-major int32). The vocabulary for categorical column c is
//   vocab_blob[vocab_off[vc] .. ] holding vocab_counts[c] zero-terminated
//   strings back to back (vc = running string index). Unknown values
//   write -1 and the row/ordinal of the first failure into err_row/err_ord.
// String/id columns are extracted separately via csv_extract_column.
//
// Returns the number of parsed rows, -1 on unknown categorical value, or
// -2 on an invalid non-empty numeric token (err_row/err_ord locate it).
int64_t csv_parse(const char* buf, int64_t len, char delim, int32_t max_ord,
                  const int32_t* num_ords, int32_t n_num, float* num_out,
                  const int32_t* cat_ords, int32_t n_cat,
                  const char* vocab_blob, const int32_t* vocab_counts,
                  int32_t* cat_out, int64_t n_rows,
                  int64_t* err_row, int32_t* err_ord) {
    ParseTables t = build_tables(max_ord, num_ords, n_num, cat_ords, n_cat,
                                 vocab_blob, vocab_counts);
    return parse_range(buf, buf + len, delim, t, num_out, cat_out, n_rows,
                       0, n_rows, err_row, err_ord);
}

// Multi-threaded csv_parse: the buffer splits into `n_threads` stripes at
// newline boundaries; each stripe is row-counted, prefix-summed into a
// global row base, then parsed in parallel into the shared column-major
// outputs (disjoint row ranges, no synchronization needed). Semantics are
// identical to csv_parse; on error the failure with the LOWEST global row
// wins (matching the sequential first-failure contract). A v5e host has
// ~100 usable cores; the single-threaded parse rate (~2M rows/sec) is the
// streaming CSV path's bound, so this is where host ingest scales.
int64_t csv_parse_mt(const char* buf, int64_t len, char delim,
                     int32_t max_ord, const int32_t* num_ords, int32_t n_num,
                     float* num_out, const int32_t* cat_ords, int32_t n_cat,
                     const char* vocab_blob, const int32_t* vocab_counts,
                     int32_t* cat_out, int64_t n_rows,
                     int64_t* err_row, int32_t* err_ord, int32_t n_threads) {
    if (n_threads <= 0) {
        n_threads = static_cast<int32_t>(std::thread::hardware_concurrency());
        if (n_threads <= 0) n_threads = 1;
    }
    // below ~4MB the spawn+count overhead beats the parallel win
    int64_t max_stripes = len / (4 << 20);
    if (n_threads > max_stripes) n_threads = static_cast<int32_t>(max_stripes);
    if (n_threads <= 1)
        return csv_parse(buf, len, delim, max_ord, num_ords, n_num, num_out,
                         cat_ords, n_cat, vocab_blob, vocab_counts, cat_out,
                         n_rows, err_row, err_ord);

    ParseTables t = build_tables(max_ord, num_ords, n_num, cat_ords, n_cat,
                                 vocab_blob, vocab_counts);
    std::vector<const char*> bounds = stripe_bounds(buf, len, n_threads);
    // pass A: parallel row count per stripe
    std::vector<int64_t> stripe_rows(n_threads, 0);
    bool ok = run_threads(n_threads, [&](int32_t i) {
        stripe_rows[i] = count_range(bounds[i], bounds[i + 1]);
    });
    std::vector<int64_t> base(n_threads + 1, 0);
    for (int32_t i = 0; i < n_threads; ++i)
        base[i + 1] = base[i] + stripe_rows[i];
    // thread-spawn failure or under-allocated output (the sequential
    // contract is "parse at most n_rows"): fall back to the sequential
    // path, which implements both cases exactly
    if (!ok || base[n_threads] > n_rows)
        return parse_range(buf, buf + len, delim, t, num_out, cat_out,
                           n_rows, 0, n_rows, err_row, err_ord);

    // pass B: parallel parse into disjoint global row ranges
    std::vector<int64_t> st(n_threads, 0), erow(n_threads, -1);
    std::vector<int32_t> eord(n_threads, -1);
    ok = run_threads(n_threads, [&](int32_t i) {
        st[i] = parse_range(bounds[i], bounds[i + 1], delim, t,
                            num_out, cat_out, n_rows, base[i],
                            stripe_rows[i], &erow[i], &eord[i]);
    });
    if (!ok)
        return parse_range(buf, buf + len, delim, t, num_out, cat_out,
                           n_rows, 0, n_rows, err_row, err_ord);
    for (int32_t i = 0; i < n_threads; ++i) {
        if (st[i] < 0) {                      // lowest-row failure wins
            *err_row = erow[i];
            *err_ord = eord[i];
            return st[i];
        }
    }
    return base[n_threads];
}

// Striped row count: the sequential pre-count is otherwise the Amdahl
// bottleneck of the parallel ingest (two full-buffer scans, one serial).
int64_t csv_count_rows_mt(const char* buf, int64_t len, int32_t n_threads) {
    if (n_threads <= 0) {
        n_threads = static_cast<int32_t>(std::thread::hardware_concurrency());
        if (n_threads <= 0) n_threads = 1;
    }
    int64_t max_stripes = len / (4 << 20);
    if (n_threads > max_stripes) n_threads = static_cast<int32_t>(max_stripes);
    if (n_threads <= 1) return count_range(buf, buf + len);
    std::vector<const char*> bounds = stripe_bounds(buf, len, n_threads);
    std::vector<int64_t> rows(n_threads, 0);
    if (!run_threads(n_threads, [&](int32_t i) {
            rows[i] = count_range(bounds[i], bounds[i + 1]);
        }))
        return count_range(buf, buf + len);
    int64_t total = 0;
    for (int64_t r : rows) total += r;
    return total;
}

// Total bytes needed by csv_extract_column's output (tokens + '\n' each).
int64_t csv_column_bytes(const char* buf, int64_t len, char delim,
                         int32_t ordinal) {
    int64_t total = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
        const char* line_end = nl ? nl : end;
        const char* b = p;
        const char* e = line_end;
        trim(b, e);
        if (e > b) {
            int32_t ord = 0;
            const char* fb = p;
            bool found = false;
            for (const char* q = p; q <= line_end; ++q) {
                if (q == line_end || *q == delim) {
                    if (ord == ordinal) {
                        const char* tb = fb;
                        const char* te = q;
                        trim(tb, te);
                        total += (te - tb) + 1;
                        found = true;
                        break;
                    }
                    ++ord;
                    fb = q + 1;
                }
            }
            if (!found) total += 1;  // short row: empty token keeps alignment
        }
        p = nl ? nl + 1 : end;
    }
    return total;
}

// Extract one column's tokens, '\n'-separated, into out (cap bytes).
// Returns bytes written, or -1 if cap is too small.
int64_t csv_extract_column(const char* buf, int64_t len, char delim,
                           int32_t ordinal, char* out, int64_t cap) {
    int64_t w = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
        const char* line_end = nl ? nl : end;
        const char* b = p;
        const char* e = line_end;
        trim(b, e);
        if (e > b) {
            int32_t ord = 0;
            const char* fb = p;
            bool found = false;
            for (const char* q = p; q <= line_end; ++q) {
                if (q == line_end || *q == delim) {
                    if (ord == ordinal) {
                        const char* tb = fb;
                        const char* te = q;
                        trim(tb, te);
                        int64_t n = te - tb;
                        if (w + n + 1 > cap) return -1;
                        memcpy(out + w, tb, n);
                        w += n;
                        out[w++] = '\n';
                        found = true;
                        break;
                    }
                    ++ord;
                    fb = q + 1;
                }
            }
            if (!found) {  // short row: empty token keeps row alignment
                if (w + 1 > cap) return -1;
                out[w++] = '\n';
            }
        }
        p = nl ? nl + 1 : end;
    }
    return w;
}

// Ragged tokenize + dictionary-encode (the sequence-job ingest: markov /
// HMM lines are "id,class,s1,s2,..." with per-row token counts). One scan
// splits every non-empty line by `delim`, ASCII-trims each token, and
// encodes it against ONE vocabulary (n_vocab zero-terminated strings back
// to back in vocab_blob); unknown tokens (ids, free meta fields) encode
// as -1 and the CALLER decides which positions must be known. Outputs
// CSR: codes[total_tokens] + offsets[n_rows+1] (offsets[0] = 0).
// seq_token_count sizes the arrays; seq_encode returns rows written or
// -3 when the buffers are too small.
int64_t seq_token_count(const char* buf, int64_t len, char delim,
                        int64_t* out_tokens) {
    int64_t rows = 0, tokens = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* e = nl ? nl : end;
        // row-ness must match seq_encode EXACTLY: whitespace-only lines
        // are skipped even when the delimiter itself is a whitespace char
        bool all_ws = true;
        int64_t t = 1;
        for (const char* q = p; q < e; ++q) {
            if (*q == delim) ++t;
            if (*q != ' ' && *q != '\t' && *q != '\r') all_ws = false;
        }
        if (!all_ws) { ++rows; tokens += t; }
        p = nl ? nl + 1 : end;
    }
    *out_tokens = tokens;
    return rows;
}

int64_t seq_encode(const char* buf, int64_t len, char delim,
                   const char* vocab_blob, int32_t n_vocab,
                   int32_t* codes, int64_t max_tokens,
                   int64_t* offsets, int64_t max_rows) {
    Vocab vocab;
    const char* v = vocab_blob;
    for (int32_t i = 0; i < n_vocab; ++i) {
        size_t n = strlen(v);
        vocab.values.emplace_back(v, n);
        v += n + 1;
    }
    vocab.build();

    int64_t rows = 0, tok = 0;
    offsets[0] = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* e = nl ? nl : end;
        // whitespace-only lines don't produce rows (the Python line
        // reader's `if ln.strip()` filter); a delim-only line DOES (it
        // parses into empty tokens, exactly like the Python split path)
        bool all_ws = true;
        for (const char* s = p; s < e; ++s)
            if (*s != ' ' && *s != '\t' && *s != '\r') { all_ws = false; break; }
        if (all_ws) {
            p = nl ? nl + 1 : end;
            continue;
        }
        if (rows + 1 >= max_rows) return -3;   // offsets[++rows] must fit
        const char* ts = p;
        for (const char* s = p;; ++s) {
            if (s == e || *s == delim) {
                const char* a = ts;
                const char* b = s;
                while (a < b && (*a == ' ' || *a == '\t' || *a == '\r')) ++a;
                while (b > a && (b[-1] == ' ' || b[-1] == '\t'
                                 || b[-1] == '\r')) --b;
                if (tok >= max_tokens) return -3;
                codes[tok++] = vocab.find(a, static_cast<size_t>(b - a));
                ts = s + 1;
                if (s == e) break;
            }
        }
        offsets[++rows] = tok;
        p = nl ? nl + 1 : end;
    }
    return rows;
}

}  // extern "C"
