"""ctypes binding for the native CSV ingest (csv_ingest.cpp).

The shared library builds lazily with g++ on first use (no pybind11 in the
image; plain `extern "C"` + ctypes per the environment constraints) and is
cached next to the source. Everything degrades to the Python parser when a
compiler is unavailable — `native_available()` gates the fast path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from avenir_tpu import obs as _obs

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "csv_ingest.cpp")
_LIB = os.path.join(_DIR, "libcsv_ingest.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    if not os.path.exists(_LIB) or (
        os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
    ):
        try:
            subprocess.run(
                ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                 "-pthread", "-o", _LIB, _SRC],
                check=True, capture_output=True, timeout=120,
            )
        except (OSError, subprocess.SubprocessError):
            _build_failed = True
            return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        # corrupt / wrong-arch cached .so: degrade to the Python parser
        _build_failed = True
        return None
    c_char_p = ctypes.c_char_p
    i64, i32 = ctypes.c_int64, ctypes.c_int32
    p_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    p_f32 = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    p_i64 = ctypes.POINTER(i64)

    lib.csv_count_rows.restype = i64
    lib.csv_count_rows.argtypes = [c_char_p, i64]
    lib.csv_count_rows_mt.restype = i64
    lib.csv_count_rows_mt.argtypes = [c_char_p, i64, i32]
    lib.csv_parse.restype = i64
    lib.csv_parse.argtypes = [
        c_char_p, i64, ctypes.c_char, i32,
        p_i32, i32, p_f32,
        p_i32, i32, c_char_p, p_i32, p_i32, i64,
        p_i64, ctypes.POINTER(i32),
    ]
    lib.csv_parse_mt.restype = i64
    lib.csv_parse_mt.argtypes = lib.csv_parse.argtypes + [i32]
    lib.csv_column_bytes.restype = i64
    lib.csv_column_bytes.argtypes = [c_char_p, i64, ctypes.c_char, i32]
    lib.csv_extract_column.restype = i64
    lib.csv_extract_column.argtypes = [c_char_p, i64, ctypes.c_char, i32,
                                       ctypes.c_char_p, i64]
    p_i64_arr = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.seq_token_count.restype = i64
    lib.seq_token_count.argtypes = [c_char_p, i64, ctypes.c_char, p_i64]
    lib.seq_encode.restype = i64
    lib.seq_encode.argtypes = [c_char_p, i64, ctypes.c_char,
                               c_char_p, i32, p_i32, i64, p_i64_arr, i64]
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None and not _build_failed:
        with _lock:
            if _lib is None and not _build_failed:
                _lib = _build()
    return _lib


def native_available() -> bool:
    return _get_lib() is not None


def parse_csv_native(
    data: bytes,
    delim: str,
    numeric_ordinals: List[int],
    categorical: List[Tuple[int, List[str]]],   # (ordinal, cardinality)
    string_ordinals: List[int],
    lazy_strings: bool = False,
    threads: int = 0,
) -> Tuple[int, Dict[int, np.ndarray], Dict[int, object]]:
    """One native pass: (n_rows, {ordinal: column array}, {ordinal: thunk}).

    Numeric columns come back float32 (missing -> NaN), categorical int32
    codes against the given cardinalities (unknown value raises ValueError,
    matching the Python parser's contract), string/id columns as numpy
    object arrays — or, with lazy_strings=True, as zero-arg thunks in the
    third return value (materializing millions of python strings costs
    more than the whole numeric/categorical parse; algorithms that never
    read ids skip it entirely)."""
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native CSV ingest unavailable (no g++?)")
    d = delim.encode()[0:1]
    n = int(lib.csv_count_rows_mt(data, len(data), np.int32(threads)))
    columns: Dict[int, np.ndarray] = {}

    num_ords = np.asarray(numeric_ordinals, np.int32)
    cat_ords = np.asarray([o for o, _ in categorical], np.int32)
    vocab_blob = b"".join(
        v.encode() + b"\0" for _, card in categorical for v in card
    )
    vocab_counts = np.asarray([len(card) for _, card in categorical], np.int32)
    all_ords = list(numeric_ordinals) + [o for o, _ in categorical] + list(
        string_ordinals)
    max_ord = max(all_ords) if all_ords else 0

    # prefill sentinels: rows shorter than the schema leave numeric NaN
    # (matching the Python parser) and categorical -1 (checked below)
    num_out = np.full((len(num_ords), n), np.nan, np.float32)
    cat_out = np.full((len(cat_ords), n), -1, np.int32)
    err_row = ctypes.c_int64(-1)
    err_ord = ctypes.c_int32(-1)
    # threads=0 lets the library pick hardware_concurrency; stripes are
    # capped so small buffers stay on the sequential path (identical
    # semantics either way — the MT entry splits at newline boundaries
    # into disjoint global row ranges)
    got = int(lib.csv_parse_mt(
        data, len(data), d, np.int32(max_ord),
        num_ords, len(num_ords), num_out,
        cat_ords, len(cat_ords), vocab_blob, vocab_counts, cat_out,
        np.int64(n), ctypes.byref(err_row), ctypes.byref(err_ord),
        np.int32(threads),
    ))
    if got < 0:
        # recover the offending token for the standard error message
        bad = _extract_column(lib, data, d, int(err_ord.value))
        tok = bad[err_row.value] if err_row.value < len(bad) else "?"
        if got == -2:
            raise ValueError(
                f"could not convert string to float: {tok!r} at ordinal "
                f"{err_ord.value}")
        raise ValueError(
            f"value {tok!r} not in declared cardinality of ordinal "
            f"{err_ord.value}")
    for i, o in enumerate(numeric_ordinals):
        columns[o] = num_out[i]
    for i, (o, _) in enumerate(categorical):
        if (cat_out[i] < 0).any():
            row = int(np.argmax(cat_out[i] < 0))
            raise ValueError(
                f"value '' not in declared cardinality of ordinal {o} "
                f"(row {row} is short)")
        columns[o] = cat_out[i]
    lazy: Dict[int, object] = {}
    for o in string_ordinals:
        if lazy_strings:
            # the native extraction runs now into a COMPACT per-column
            # buffer (so the thunk does not pin the whole CSV block); only
            # the python-string materialization — the expensive part — is
            # deferred
            raw = _extract_column_bytes(lib, data, d, o)
            lazy[o] = (lambda r=raw: np.array(
                r.decode().split("\n")[:-1], dtype=object))
        else:
            columns[o] = np.array(_extract_column(lib, data, d, o),
                                  dtype=object)
    return got, columns, lazy


def _extract_column_bytes(lib, data: bytes, d: bytes, ordinal: int) -> bytes:
    cap = int(lib.csv_column_bytes(data, len(data), d, np.int32(ordinal)))
    buf = ctypes.create_string_buffer(max(cap, 1))
    w = int(lib.csv_extract_column(data, len(data), d, np.int32(ordinal),
                                   buf, np.int64(cap)))
    return buf.raw[:w] if w > 0 else b""


def _extract_column(lib, data: bytes, d: bytes, ordinal: int) -> List[str]:
    raw = _extract_column_bytes(lib, data, d, ordinal)
    if not raw:
        return []
    return raw.decode().split("\n")[:-1]


def extract_column_raw(data: bytes, delim: str, ordinal: int
                       ) -> Optional[bytes]:
    """One column's trimmed tokens as the native parser's compact
    newline-joined buffer (trailing newline included) — the exact bytes
    the lazy-string thunks of parse_csv_native defer over, which is
    also what the columnar sidecar stores for open-vocabulary columns.
    None when the native library or a single-byte delimiter is not
    available."""
    lib = _get_lib()
    if lib is None:
        return None
    d = delim.encode()
    if len(d) != 1:
        return None
    return _extract_column_bytes(lib, data, d, ordinal)


def seq_encode_native(data: bytes, delim: str, vocab: List[str]
                      ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Ragged tokenize + dictionary-encode a text block against one
    vocabulary (the sequence-job ingest). Returns (codes int32
    [total_tokens], offsets int64 [rows+1]) in CSR form — token t of row
    r is codes[offsets[r] + t]; unknown tokens are -1. None when the
    native library is unavailable (callers fall back to Python split)."""
    lib = _get_lib()
    if lib is None:
        return None
    d = delim.encode()
    if len(d) != 1:
        return None
    n_tokens = ctypes.c_int64(0)
    n_rows = int(lib.seq_token_count(data, len(data), d,
                                     ctypes.byref(n_tokens)))
    codes = np.empty(max(n_tokens.value, 1), np.int32)
    offsets = np.empty(n_rows + 1, np.int64)
    blob = b"".join(v.encode() + b"\0" for v in vocab)
    got = int(lib.seq_encode(data, len(data), d, blob, len(vocab),
                             codes, codes.shape[0], offsets, n_rows + 1))
    if got != n_rows:
        raise RuntimeError(f"seq_encode row mismatch: {got} != {n_rows}")
    return codes[: int(offsets[n_rows])], offsets


def native_seq_ready(delim: str) -> bool:
    """True when the native sequence encoder handles this delimiter
    (single byte) and the library is built — the gate every CSR
    consumer checks before taking the byte-block path."""
    return len(delim.encode()) == 1 and native_available()


def csr_rows(offsets: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(row_of [total_tokens], starts [n_rows]) for a CSR offsets array —
    the shared row-decode of every seq_encode consumer (markov fit_csr,
    HMM add_csr, apriori counting chunks). row_of is int32: a block
    never holds 2^31 rows (blocks are tens of MB), and the token-
    proportional arrays dominate a streaming pass's transient RSS, so
    halving them matters at scale."""
    return (np.repeat(np.arange(offsets.shape[0] - 1, dtype=np.int32),
                      np.diff(offsets)),
            offsets[:-1])


def csr_region_mask(offsets: np.ndarray, skip: int, n_tokens: int
                    ) -> np.ndarray:
    """bool [n_tokens]: True where a token sits at within-row position
    >= skip (the item/sequence region past the meta fields). Built by
    unmarking the first `skip` positions of each row — O(rows * skip)
    small arrays instead of the arange(n_tokens) + starts[row_of]
    int64 temporaries the naive position compare materializes (those
    were the largest transients of the miners' streaming passes)."""
    region = np.ones(n_tokens, bool)
    starts, ends = offsets[:-1], offsets[1:]
    for j in range(skip):
        pos = starts + j
        region[pos[pos < ends]] = False
    return region


class BlockScanEncoder:
    """Per-block body of the vocabulary-DISCOVERING native scan — the
    shared pass-1 engine of the streaming miners (association
    scan_items, sequence scan), factored so an external SharedScan can
    drive it one byte block at a time (core.stream.SharedScan fans one
    disk read out to N sinks; this is the miner-side sink body).

    Each block encodes against the CURRENT vocab plus two drop
    sentinels (the infrequent-item marker and the empty token, which
    would otherwise read as unknown and force the slow path on every
    block of a trailing-delimiter CSV). A block with genuinely unknown
    tokens takes one Python pass to extend `vocab`/`index` in place,
    then re-encodes — but only if that pass actually added something;
    steady-state blocks of a vocabulary-stable stream never touch
    per-row Python. `region` is True exactly at item positions holding
    a REAL vocab code (sentinels, ids and short rows excluded), so
    callers can fold counts straight off (codes[region], row_of[region]).
    Vocab codes are append-only, so codes encoded against an EARLIER
    vocab prefix stay valid against the final vocabulary — the property
    the encoded-block spill cache (EncodedBlockCache) is built on."""

    def __init__(self, delim: str, skip: int, vocab: List[str],
                 index: Dict[str, int], marker: Optional[str] = None):
        self.delim = delim
        self.skip = skip
        self.vocab = vocab
        self.index = index
        self.marker = marker
        self._sentinels = ([marker] if marker is not None else []) + [""]

    def encode(self, data: bytes):
        """(codes, offsets, region, n_rows) for one raw byte block, or
        None for a block with no rows."""
        codes, offsets = seq_encode_native(data, self.delim,
                                           self.vocab + self._sentinels)
        n = offsets.shape[0] - 1
        if n <= 0:
            return None
        region = csr_region_mask(offsets, self.skip, codes.shape[0])
        if (codes[region] < 0).any():
            added = False
            for ln in data.decode("utf-8", "replace").split("\n"):
                if not ln.strip():
                    continue
                for tok in [t.strip(" \t\r")
                            for t in ln.split(self.delim)][self.skip:]:
                    if tok and tok != self.marker and tok not in self.index:
                        self.index[tok] = len(self.vocab)
                        self.vocab.append(tok)
                        added = True
            if added:
                codes, offsets = seq_encode_native(
                    data, self.delim, self.vocab + self._sentinels)
        v = len(self.vocab)
        np.logical_and(region, codes >= 0, out=region)
        np.logical_and(region, codes < v, out=region)     # sentinels drop
        return codes, offsets, region, n


def scan_encode_blocks(paths, delim: str, skip: int, vocab: List[str],
                       index: Dict[str, int], block_bytes: int,
                       marker: Optional[str] = None):
    """Vocabulary-DISCOVERING native scan: yield (codes, offsets, region,
    n_rows) per byte block (see BlockScanEncoder for the per-block
    contract; this generator owns the prefetched disk read)."""
    from avenir_tpu.core.stream import iter_byte_blocks, prefetched

    enc = BlockScanEncoder(delim, skip, vocab, index, marker)
    for path in paths:
        for data in prefetched(iter_byte_blocks(path, block_bytes),
                               depth=1):
            out = enc.encode(data)
            if out is not None:
                yield out


# --------------------------------------------------------------------------
# Encoded-block spill cache
# --------------------------------------------------------------------------
_ENC_MAGIC = b"AVNRENC1"
_ENC_DTYPES = {0: np.uint8, 1: np.uint16, 2: np.uint32}

#: the cache's default on-disk byte budget — generous (the 100M-row
#: anchors spill ~1.5GB of CSV into ~600MB of codes), but FINITE: an
#: unbudgeted spill is exactly the `mem-cache-spill-unbudgeted` hazard
#: graftlint --mem flags, and the resident job server needs every spill
#: evictable
DEFAULT_CACHE_BUDGET_BYTES = 1 << 30


def _enc_dtype_code(max_value: int) -> int:
    if max_value < (1 << 8):
        return 0
    if max_value < (1 << 16):
        return 1
    return 2


class EncodedBlockCache:
    """Compact on-disk spill cache of region-compacted encoded blocks.

    The multi-pass miners (Apriori / GSP) re-scan their CSV once per
    itemset length k; after PR 1 the scan cost — disk read + native
    tokenize/encode — dominates each pass, not the device fold. The
    discovery scan (pass 1) already produces every later pass's inputs:
    the region-masked vocab codes of each block, in row order. This
    cache spills exactly that, per block:

        header  <q n_rows> <q n_tokens> <B counts_dtype> <B codes_dtype>
        counts  n_rows  elements — region token count per row
        codes   n_tokens elements — vocab codes of region tokens, row-major

    with the narrowest dtype that fits (1-byte codes for vocabularies
    under 256 items), so the cache is a fraction of the raw CSV bytes —
    replay passes read it instead of re-parsing CSV, and the raw-block /
    full-codes transients of the scan never materialize again (this is
    also what buys back Apriori's thin RSS headroom at 100M rows).

    Byte budget: the spill is bounded by `byte_budget` (default
    :data:`DEFAULT_CACHE_BUDGET_BYTES`; a config surface sits at the
    jobs' ``stream.encoded.cache.budget.mb`` key). Blocks land in one
    SEGMENT per source (``set_source``; writers that cannot attribute
    blocks — the shared-scan external feed — use one combined segment).
    Exceeding the budget evicts whole least-recently-replayed source
    segments atomically (never-replayed segments first, in write
    order), accumulating ``evicted_bytes``; consumers re-parse evicted
    sources and keep replaying the survivors (``source_valid(i)`` /
    ``blocks(i)``), so a tight budget degrades throughput, never
    correctness.

    Invalidation contract: validity is PER BLOCK, not per file. The
    own-read scan records a content fingerprint (offset + length +
    blake2b hash, ``note_block``) for every raw block it encodes; at
    replay time a source whose quick (path, size, mtime_ns) snapshot
    moved is re-proven by re-hashing the recorded ranges (memoized per
    file snapshot). An APPENDED source therefore stays replayable —
    its committed blocks still content-match the file's prefix
    (``source_delta`` hands consumers the byte offset where coverage
    ends, and only the tail re-parses) — while an in-place edit, or a
    writer that never saw raw blocks (the shared-scan external feed
    records no fingerprints), falls back to the whole-file snapshot
    gate and the full re-parse path. commit() still refuses a source
    that changed at all while the scan ran: a torn cache never commits.
    The cache directory is owned by this object (a tempdir unless
    `cache_dir` is given) and is removed on close()/GC; it is a
    within-job spill, not a cross-run artifact store."""

    #: segment key of the combined (source-unattributed) write stream
    _COMBINED = None

    #: sentinel: no segment can serve the requested source
    _NO_SEGMENT = object()

    def __init__(self, sources: Sequence[str],
                 cache_dir: Optional[str] = None,
                 byte_budget: Optional[int] = None):
        import tempfile

        self.sources = list(sources)
        self.byte_budget = (DEFAULT_CACHE_BUDGET_BYTES
                            if byte_budget is None else int(byte_budget))
        self._own_dir = cache_dir is None
        self._dir = cache_dir or tempfile.mkdtemp(prefix="avenir_encblk_")
        os.makedirs(self._dir, exist_ok=True)
        self._fh = None
        self._cur = self._COMBINED        # segment being written
        self._seg_order: list = []        # segment keys in write order
        self._seg_bytes: dict = {}        # segment key -> bytes written
        self._evicted: set = set()
        self._last_replay: dict = {}      # segment key -> replay clock
        self._replay_clock = 0
        self._fingerprint = None
        self._block_fps: dict = {}        # segment key -> [(off, len, hash)]
        self._delta_memo: dict = {}       # (src, size, mtime) -> end | None
        self._committed = False
        self.n_blocks = 0
        self.evicted_bytes = 0
        self.replays = 0          # completed replay passes (bench tripwire)

    def _seg_path(self, key) -> str:
        name = ("encoded_blocks.bin" if key is self._COMBINED
                else f"encoded_blocks_s{key}.bin")
        return os.path.join(self._dir, name)

    # ------------------------------------------------------------- write
    def _current_fingerprint(self):
        """Cheap stat identity of the source set — the begin/commit
        torn-write GATE only (a scan that mutated its own sources can
        never commit); REPLAY validity is the per-block content
        re-proof (``_content_coverage``), never this stat tuple.

        key-covered: all — replay identity is the content fingerprints.
        """
        from avenir_tpu.core.keys import key_site

        key_site("cache.fingerprint")
        out = []
        for p in self.sources:
            try:
                st = os.stat(p)
                out.append((p, st.st_size, st.st_mtime_ns))
            except OSError:
                out.append((p, -1, -1))
        return tuple(out)

    def begin(self) -> None:
        """Start (or restart) a write pass; any prior content is gone."""
        self.abort()
        for key in self._seg_order:
            try:
                os.remove(self._seg_path(key))
            except OSError:
                pass
        self._fingerprint = self._current_fingerprint()
        self._seg_order = []
        self._seg_bytes = {}
        self._evicted = set()
        self._last_replay = {}
        self._block_fps = {}
        self._delta_memo = {}
        self._cur = self._COMBINED
        self.n_blocks = 0
        self.evicted_bytes = 0

    def note_block(self, offset: int, data: bytes) -> None:
        """Record the CONTENT fingerprint (offset + length + hash) of one
        raw byte block of the currently-attributed source, whether or
        not the block spills any payload (blank blocks cover bytes but
        add no rows). Per-block fingerprints are what turn an appended
        source from a total invalidation into a delta: the committed
        blocks still content-match the file's prefix, so replay serves
        them and only the appended tail re-parses (source_delta).
        Writers that cannot see raw blocks — the shared-scan external
        feed — simply never call this and keep the whole-file gate."""
        from avenir_tpu.core.incremental import block_hash

        self.note_fingerprint(offset, len(data), block_hash(data))

    def note_fingerprint(self, offset: int, length: int,
                         hash_: str) -> None:
        """note_block for a writer that already holds the block's content
        hash (the sidecar-aware scan computes one fingerprint per block
        for its own manifest) — same contract, no second hash pass."""
        if self._fingerprint is None:
            raise RuntimeError("note_block() before begin()")
        if self._committed:
            raise RuntimeError("note_block() after commit()")
        self._block_fps.setdefault(self._cur, []).append(
            (int(offset), int(length), hash_))

    def set_source(self, index: int) -> None:
        """Attribute subsequent add_block() calls to source `index` —
        per-source segments are what make partial eviction (and partial
        replay) possible. Writers that cannot attribute blocks simply
        never call this and get one combined segment."""
        if self._cur == index:
            return
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._cur = index

    def _open_segment(self) -> None:
        path = self._seg_path(self._cur)
        if self._cur in self._seg_order and os.path.exists(path):
            # a writer returning to an earlier source (interleaved
            # set_source calls) must EXTEND its segment — "wb" here
            # would silently truncate committed blocks and replay a
            # partial segment as if it were whole
            self._fh = open(path, "ab")
            return
        self._fh = open(path, "wb")
        self._fh.write(_ENC_MAGIC)
        self._seg_bytes[self._cur] = len(_ENC_MAGIC)
        if self._cur not in self._seg_order:
            self._seg_order.append(self._cur)

    def _spilled_bytes(self) -> int:
        """Live spill size from the per-segment byte counters — O(live
        segments) arithmetic, no flush/stat per call (add_block calls
        this once per block)."""
        return sum(n for k, n in self._seg_bytes.items()
                   if k not in self._evicted)

    def _evict_segment(self, key) -> None:
        if key == self._cur and self._fh is not None:
            self._fh.close()
            self._fh = None
        try:
            os.remove(self._seg_path(key))
        except OSError:
            pass
        self.evicted_bytes += self._seg_bytes.get(key, 0)
        self._evicted.add(key)

    def evict_to(self, byte_budget: int) -> int:
        """Evict whole segments, least-recently-replayed first (never-
        replayed segments in write order before any replayed one), until
        the spill fits `byte_budget`. The currently-written segment goes
        last — but it too is evicted when it alone exceeds the budget
        (the cache then quietly disables itself for that source and the
        consumer re-parses). Returns the bytes evicted by this call."""
        before = self.evicted_bytes
        order = {k: i for i, k in enumerate(self._seg_order)}
        live = [k for k in self._seg_order if k not in self._evicted]
        live.sort(key=lambda k: (k == self._cur,
                                 self._last_replay.get(k, -1), order[k]))
        spilled = self._spilled_bytes()
        for key in live:
            if spilled <= byte_budget:
                break
            spilled -= self._seg_bytes.get(key, 0)
            self._evict_segment(key)
        return self.evicted_bytes - before

    def add_block(self, counts: np.ndarray, codes: np.ndarray) -> None:
        """Append one block: per-row region token counts + the region
        token codes (row-major). Narrowest-dtype encoding per block; a
        write that pushes the spill past the byte budget triggers
        whole-segment eviction. Blocks for an already-evicted segment
        are dropped (and counted) — the budget is a hard bound."""
        import struct

        if self._fingerprint is None:
            raise RuntimeError("add_block() before begin()")
        if self._committed:
            raise RuntimeError(
                "add_block() after commit(): a sealed cache never grows "
                "— call begin() to rewrite it")
        counts = np.ascontiguousarray(counts)
        codes = np.ascontiguousarray(codes)
        cd = _enc_dtype_code(int(counts.max(initial=0)))
        kd = _enc_dtype_code(int(codes.max(initial=0)))
        size = (18 + counts.shape[0] * _ENC_DTYPES[cd]().itemsize
                + codes.shape[0] * _ENC_DTYPES[kd]().itemsize)
        if self._cur in self._evicted:
            self.evicted_bytes += size
            return
        if self._fh is None:
            self._open_segment()
        self._fh.write(struct.pack("<qqBB", counts.shape[0],
                                   codes.shape[0], cd, kd))
        counts.astype(_ENC_DTYPES[cd]).tofile(self._fh)
        codes.astype(_ENC_DTYPES[kd]).tofile(self._fh)
        self.n_blocks += 1
        self._seg_bytes[self._cur] = self._seg_bytes.get(self._cur, 0) + size
        if self._spilled_bytes() > self.byte_budget:
            self.evict_to(self.byte_budget)

    def commit(self) -> bool:
        """Seal the write pass. Returns False (and stays invalid) when a
        source changed while the scan ran — a torn cache must never be
        replayed. Segments evicted by the budget stay evicted; the
        surviving ones replay."""
        if self._fingerprint is None:
            return False
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._committed = self._fingerprint == self._current_fingerprint()
        return self._committed

    def abort(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._fingerprint = None     # a new begin() must precede writes
        self._committed = False

    # ------------------------------------------------------------ replay
    def _segment_key(self, index: int):
        """Segment key serving source `index` (its own segment, or the
        combined one when it is the only source), else _NO_SEGMENT."""
        if index in self._seg_order:
            return index
        if self._COMBINED in self._seg_order and len(self.sources) == 1 \
                and index == 0:
            return self._COMBINED
        return self._NO_SEGMENT

    def _content_coverage(self, index: int) -> Optional[int]:
        """Byte offset up to which source `index`'s recorded per-block
        fingerprints still content-match the file, re-proven by hashing
        the recorded ranges (memoized per (size, mtime_ns) snapshot so
        per-k replay passes verify once, not once per pass). None when
        no fingerprints were recorded, the serving segment is evicted
        or absent, or ANY recorded block mismatches — coverage is
        all-or-nothing: the cache replays every committed block of a
        source or none of them."""
        if not self._committed:
            return None
        key = self._segment_key(index)
        if key is self._NO_SEGMENT or key in self._evicted \
                or not os.path.exists(self._seg_path(key)):
            return None
        fps = self._block_fps.get(key)
        if not fps:
            return None
        path = self.sources[index]
        try:
            st = os.stat(path)
        except OSError:
            return None
        memo = (index, st.st_size, st.st_mtime_ns)
        if memo not in self._delta_memo:
            from avenir_tpu.core.incremental import verified_prefix

            n, covered = verified_prefix(
                path, [{"offset": o, "length": ln, "hash": h}
                       for o, ln, h in fps])
            self._delta_memo[memo] = covered if n == len(fps) else None
        return self._delta_memo[memo]

    def source_delta(self, index: int) -> Optional[int]:
        """Byte offset at which source `index`'s cached coverage ends,
        when its committed blocks are still a verified content PREFIX of
        the current file — the appended-source replay gate: consumers
        replay ``blocks(index, prefix=True)`` and re-parse only
        ``[delta, size)``. None when the prefix itself no longer matches
        (an in-place edit), the segment was evicted, the writer recorded
        no fingerprints (external shared-scan feeds), or the coverage
        ends MID-LINE on a grown file (the scanned corpus' last line had
        no terminator, so the appended bytes extend an already-encoded
        row — splicing a tail re-parse there would split one line into
        two)."""
        cov = self._content_coverage(index)
        if cov is None:
            return None
        path = self.sources[index]
        try:
            size = os.path.getsize(path)
        except OSError:
            return None
        if cov < size:
            from avenir_tpu.core.incremental import ends_at_newline

            if not ends_at_newline(path, cov):
                return None
        return cov

    def _source_unchanged(self, index: int) -> bool:
        rec = self._fingerprint[index]
        path = self.sources[index]
        try:
            st = os.stat(path)
            cur = (path, st.st_size, st.st_mtime_ns)
        except OSError:
            cur = (path, -1, -1)
        if cur == rec:
            return True
        # mtime-only churn (touch, copy-back) must not torch the cache:
        # the per-block content fingerprints re-prove the bytes; full
        # validity needs them to cover the file END TO END
        cov = self._content_coverage(index)
        return cov is not None and cov == cur[1]

    def _fingerprint_ok(self) -> bool:
        if not self._committed or self._fingerprint is None:
            return False
        if self._fingerprint == self._current_fingerprint():
            return True
        return all(self._source_unchanged(i)
                   for i in range(len(self.sources)))

    @property
    def valid(self) -> bool:
        """True when a committed cache exists, the sources are
        byte-for-byte the ones it encoded (size+mtime fingerprint), AND
        no segment was evicted — the all-or-nothing replay gate. With
        evictions, consumers use the per-source gate below."""
        return (self._fingerprint_ok() and not self._evicted
                and all(os.path.exists(self._seg_path(k))
                        for k in self._seg_order))

    def source_valid(self, index: int) -> bool:
        """True when source `index`'s blocks can replay IN FULL (the
        file is covered end to end): its own segment survives, or the
        cache wrote one combined segment for a single source. A multi-
        source combined segment cannot split, so it replays only through
        the all-or-nothing `valid` gate. An appended source fails this
        gate but keeps the prefix gate: see source_delta()."""
        if not self._fingerprint_ok():
            return False
        key = self._segment_key(index)
        if key is self._NO_SEGMENT:
            return False
        return (key not in self._evicted
                and os.path.exists(self._seg_path(key)))

    def _read_segment(self, key):
        import struct

        path = self._seg_path(key)
        with open(path, "rb") as fh:
            if fh.read(len(_ENC_MAGIC)) != _ENC_MAGIC:
                raise RuntimeError("encoded-block cache is corrupt")
            while True:
                head = fh.read(18)
                if not head:
                    break
                n_rows, n_tok, cd, kd = struct.unpack("<qqBB", head)
                counts = np.fromfile(fh, _ENC_DTYPES[cd], n_rows)
                codes = np.fromfile(fh, _ENC_DTYPES[kd], n_tok)
                if counts.shape[0] != n_rows or codes.shape[0] != n_tok:
                    raise RuntimeError("encoded-block cache is truncated")
                # int32 both ways: per-row region counts are bounded by
                # tokens-per-row and codes by the vocab — widening the
                # block-proportional arrays to int64 here was exactly the
                # mem-dtype-expansion-at-parse shape this tier flags
                yield counts.astype(np.int32), codes.astype(np.int32)
        self._replay_clock += 1
        self._last_replay[key] = self._replay_clock

    def blocks(self, source: Optional[int] = None, prefix: bool = False):
        """Yield (counts int32 [n_rows], codes int32 [n_tokens]) per
        cached block — all segments in write order by default, one
        source's segment with `source=i`. With ``prefix=True`` the
        per-source gate relaxes from full coverage to the verified-
        content-prefix gate (source_delta): the appended-source replay,
        where the caller re-parses the tail itself. Raises RuntimeError
        when the requested scope is not replayable — callers check
        `valid` / `source_valid(i)` / `source_delta(i)` and fall back
        to the re-parse path."""
        if source is not None:
            ok = self.source_valid(source) or (
                prefix and self.source_delta(source) is not None)
            if not ok:
                raise RuntimeError(
                    f"encoded-block segment for source {source} is "
                    f"stale, evicted or absent")
            key = source if source in self._seg_order else self._COMBINED
            yield from self._read_segment(key)
            live = [k for k in self._seg_order if k not in self._evicted]
            if live and key == live[-1]:
                self.replays += 1
            return
        if not self.valid:
            raise RuntimeError("encoded-block cache is stale or absent")
        for key in self._seg_order:
            yield from self._read_segment(key)
        self.replays += 1

    def nbytes(self) -> int:
        try:
            return self._spilled_bytes()
        except OSError:
            return 0

    # ----------------------------------------------------------- cleanup
    def close(self) -> None:
        import shutil

        self.abort()
        if self._own_dir:
            shutil.rmtree(self._dir, ignore_errors=True)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def distinct_row_code_counts(row_of: np.ndarray, codes: np.ndarray,
                             region: np.ndarray, v: int) -> np.ndarray:
    """counts[c] = #rows whose region tokens include code c, each row
    counted once (the multi-hot k=1 support algebra): in-place sort +
    consecutive-diff dedup, so the int64 key array is the only
    token-sized temporary — no np.unique copy."""
    keys = row_of[region].astype(np.int64) * v + codes[region]
    keys.sort()
    if not keys.shape[0]:
        return np.zeros(v, np.int64)
    uniq = np.empty(keys.shape[0], bool)
    uniq[0] = True
    np.not_equal(keys[1:], keys[:-1], out=uniq[1:])
    return np.bincount((keys[uniq] % v).astype(np.intp), minlength=v)


class SpillScanMixin:
    """Shared pass-1 machinery of the streaming miner sources
    (association.StreamingTransactionSource, sequence.
    StreamingSequenceSource): the scan lifecycle (begin -> per-block
    -> finish/commit), the SharedScan sink adapter, and the encoded-
    block cache's ownership. ONE copy, so a cache-lifecycle fix can
    never land in one miner and silently miss the other.

    Subclass contract — attributes: ``paths``, ``delim``, ``skip``,
    ``block_bytes``, ``spill_cache``, ``vocab``, ``index``, ``_cache``,
    ``_item_counts``, ``_scan_counts``, ``_scan_encoder``; methods:
    ``_scan_block(data)`` (fold one raw byte block, updating
    ``_scan_counts`` via ``_grow_counts`` and spilling to ``_cache``),
    ``_reset_scan_state()`` (zero the per-scan row counters) and
    ``_scan_result()`` (the (vocab, counts, n) tuple scan()/scan_items()
    return). ``_scan_marker`` is the infrequent-item sentinel forwarded
    to the encoder (None when the format has none); an optional
    ``cache_budget_bytes`` attribute bounds the encoded-block spill
    (None -> the cache's generous default)."""

    _scan_marker: Optional[str] = None

    def _scan_begin(self) -> None:
        self._reset_scan_state()
        self._scan_counts = np.zeros(0, np.int64)
        self._sidecar_vocab_src = None
        self._sidecar_vocab_done = 0
        self._scan_encoder = (
            BlockScanEncoder(self.delim, self.skip, self.vocab, self.index,
                             marker=self._scan_marker)
            if native_seq_ready(self.delim) else None)
        if self.spill_cache:
            if self._cache is not None:
                self._cache.close()
            self._cache = EncodedBlockCache(
                self.paths,
                byte_budget=getattr(self, "cache_budget_bytes", None))
            self._cache.begin()

    def _grow_counts(self) -> None:
        v = len(self.vocab)
        if self._scan_counts.shape[0] < v:
            self._scan_counts = np.concatenate(
                [self._scan_counts,
                 np.zeros(v - self._scan_counts.shape[0], np.int64)])

    def _scan_all(self):
        """Own-read scan driver: prefetched byte blocks of every path
        through _scan_block, then seal. Blocks attribute to per-source
        cache segments so a budget eviction drops whole sources, not the
        whole cache (the SharedScan feed below cannot attribute and
        writes one combined segment), and every block's content
        fingerprint is recorded (note_block) so an appended source later
        replays its committed prefix and re-parses only the tail.

        A runner that attached ``sidecar_opts`` (runner._build_miner_
        source) routes each path through the cross-run columnar sidecar
        first: verified blocks replay as SidecarBytesBlock (no tokenize,
        no parse — _scan_encoded_block), cold blocks arrive raw and both
        fold AND pack, so the NEXT run's pass 1 is parse-free too. The
        per-k spill cache sits on top either way — replayed blocks feed
        it their re-mapped codes, cold blocks their scanned ones."""
        from avenir_tpu.core.stream import iter_byte_blocks, prefetched

        self._scan_begin()
        label = type(self).__name__
        opts = getattr(self, "sidecar_opts", None)
        for si, path in enumerate(self.paths):
            feed = None
            if opts is not None:
                from avenir_tpu.native import sidecar as _sidecar

                feed = _sidecar.byte_blocks(opts, path, self.delim,
                                            self.skip, self.block_bytes)
            if feed is not None:
                if self._cache is not None:
                    self._cache.set_source(si)
                for off, length, hsh, payload in feed:
                    if self._cache is not None:
                        self._cache.note_fingerprint(off, length, hsh)
                    if payload is None:
                        continue
                    if isinstance(payload, (bytes, bytearray)):
                        t0 = _obs.now()
                        self._scan_block(payload)
                        _obs.record("stream.parse", t0, sink=label,
                                    nbytes=length)
                    else:
                        self._scan_encoded_block(payload)
            elif self._cache is not None:
                self._cache.set_source(si)
                for off, data in prefetched(
                        iter_byte_blocks(path, self.block_bytes,
                                         with_offsets=True), depth=1):
                    self._cache.note_block(off, data)
                    t0 = _obs.now()
                    self._scan_block(data)
                    _obs.record("stream.parse", t0, sink=label,
                                nbytes=len(data))
            else:
                for data in prefetched(
                        iter_byte_blocks(path, self.block_bytes), depth=1):
                    t0 = _obs.now()
                    self._scan_block(data)
                    _obs.record("stream.parse", t0, sink=label,
                                nbytes=len(data))
        return self._scan_finish()

    def _scan_encoded_block(self, blk) -> None:
        """Fold one replayed sidecar block (native.sidecar.
        SidecarBytesBlock) — the parse-free twin of _scan_block. The
        sidecar's vocabulary extends this source's in FIRST-SEEN order
        (minus the infrequent-item marker, which the sidecar keeps but
        miners drop), which is exactly the order the cold discovery scan
        would have assigned — so codes, counts and the per-k spill cache
        come out identical to a cold pass over the same bytes."""
        if blk.skip != self.skip:
            raise ValueError(
                f"sidecar block packed at skip={blk.skip} fed to a "
                f"skip={self.skip} scan")
        # the merge watermark is PER SIDECAR: each source's manifest has
        # its own vocabulary (one shared list per feed), so key the
        # watermark on that list's identity — a scan crossing inputs
        # (own-read multi-path or a shared feed) restarts at 0 for the
        # next source instead of skipping its unseen tokens
        if getattr(self, "_sidecar_vocab_src", None) is not blk.vocab:
            self._sidecar_vocab_src = blk.vocab
            self._sidecar_vocab_done = 0
        done = self._sidecar_vocab_done
        for tok in blk.vocab[done:blk.vocab_end]:
            if tok != self._scan_marker and tok not in self.index:
                self.index[tok] = len(self.vocab)
                self.vocab.append(tok)
        self._sidecar_vocab_done = max(done, blk.vocab_end)
        self._grow_counts()
        # stored sidecar codes are vocab code + 1 with 0 = the empty
        # token; map through a LUT onto THIS source's codes, -1 dropping
        # empties and the marker exactly as the cold region mask does
        lut = np.full(blk.vocab_end + 1, -1, np.int32)
        for k in range(blk.vocab_end):
            tok = blk.vocab[k]
            if tok != self._scan_marker:
                lut[k + 1] = self.index[tok]
        mapped = lut[blk.codes]
        region = mapped >= 0
        row_of = np.repeat(np.arange(blk.n, dtype=np.int32), blk.counts)
        self._scan_counts += distinct_row_code_counts(
            row_of, mapped, region, len(self.vocab))
        per_row = np.bincount(row_of[region].astype(np.intp),
                              minlength=blk.n)
        if self._cache is not None:
            self._cache.add_block(per_row, mapped[region])
        self._note_encoded_rows(per_row, blk.n)

    def _note_encoded_rows(self, per_row: np.ndarray, n: int) -> None:
        """Subclass hook: update the per-scan row counters for one
        replayed block (association: transaction count; sequence: row
        count and max length) — the only part of the block fold the
        mixin cannot name for both miners."""
        raise NotImplementedError

    def scan_consumer(self):
        """Shared-scan sink: pass 1 driven by EXTERNAL raw byte blocks
        (core.stream.SharedScan fans one disk read to N such sinks).
        consume() per block; finish() seals the scan and returns what
        the source's own scan entry point would."""
        self._scan_begin()
        src = self
        label = type(self).__name__

        class _ScanSink:
            def consume(self, data) -> None:
                if not isinstance(data, (bytes, bytearray)):
                    # a sidecar-replayed block from a sidecar-aware
                    # shared feed: parse-free fold, no stream.parse span
                    src._scan_encoded_block(data)
                    return
                # pass-1 parse/encode of an externally-read block: the
                # same stream.parse span the own-read scan records
                t0 = _obs.now()
                src._scan_block(data)
                _obs.record("stream.parse", t0, sink=label,
                            nbytes=len(data))

            def finish(self):
                return src._scan_finish()

        return _ScanSink()

    def _scan_finish(self):
        self._item_counts = self._scan_counts
        self._scan_encoder = None
        if self._cache is not None and not self._cache.commit():
            # a source changed under the scan: never replay a torn cache
            self._cache.close()
            self._cache = None
        return self._scan_result()

    def restore_scan_state(self, vocab, counts) -> None:
        """Restore a mid-scan checkpoint into a freshly-BEGUN scan (the
        fold-state resume contract, graftlint --merge): reinstall the
        checkpointed discovery vocabulary and partial per-item counts in
        place (the encoder holds references to `vocab`/`index`, so they
        mutate, never rebind), rebuild the native encoder over them, and
        DROP the spill cache — a cache begun after the restore would
        hold only post-restore blocks yet commit as complete, and a
        later per-k pass would replay a truncated corpus. Restored scans
        therefore re-parse their sources per-k: correctness over
        throughput, documented in docs/DESIGN.md. Callers restore their
        own row counters (n_trans / n_rows / t_max) — the mixin does not
        know their names."""
        self.vocab[:] = list(vocab)
        self.index.clear()
        self.index.update({t: i for i, t in enumerate(self.vocab)})
        self._scan_counts = np.asarray(counts, np.int64).copy()
        if self._scan_encoder is not None:
            self._scan_encoder = BlockScanEncoder(
                self.delim, self.skip, self.vocab, self.index,
                marker=self._scan_marker)
        if self._cache is not None:
            self._cache.close()
            self._cache = None
        self.spill_cache = False

    @property
    def cache_replays(self) -> int:
        """Completed encoded-block replay passes (bench tripwire hook)."""
        return self._cache.replays if self._cache is not None else 0

    def cache_ready(self) -> bool:
        """True when the pass-1 spill cache is committed and EVERY
        source's segment can still replay in full (the cache's own
        content gates) — the warm-replay precondition the resident job
        server checks before serving a repeat mining request from this
        source with zero CSV parses. Any corpus change fails the gate:
        a warm hit can never serve stale discovery counts."""
        c = self._cache
        if c is None or self._item_counts is None:
            return False
        return all(c.source_valid(i) for i in range(len(self.paths)))

    def cache_evict_to(self, byte_budget: int) -> int:
        """Trim the spill toward `byte_budget` through the cache's own
        segment eviction (``EncodedBlockCache.evict_to``); returns the
        bytes evicted, 0 when the cache is off — the handle the job
        server's warm-state budget enforcement consumes."""
        return (self._cache.evict_to(byte_budget)
                if self._cache is not None else 0)

    @property
    def cache_nbytes(self) -> int:
        """On-disk size of the encoded-block spill cache (0 when off)."""
        return self._cache.nbytes() if self._cache is not None else 0

    @property
    def cache_evicted_bytes(self) -> int:
        """Bytes the spill cache evicted (or dropped) to hold its byte
        budget — surfaced as the Cache:EvictedBytes job counter."""
        return (self._cache.evicted_bytes
                if self._cache is not None else 0)

    def close(self) -> None:
        if self._cache is not None:
            self._cache.close()
            self._cache = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def extract_column_native(data: bytes, delim: str, ordinal: int
                          ) -> Optional[np.ndarray]:
    """One column's trimmed tokens for every non-blank line of a raw text
    block (short rows yield ''), as a numpy unicode array — the open-
    vocabulary companion to seq_encode_native (entity ids cannot
    dictionary-encode). None when the native library is unavailable."""
    lib = _get_lib()
    if lib is None:
        return None
    d = delim.encode()
    if len(d) != 1:
        return None
    raw = _extract_column_bytes(lib, data, d, ordinal)
    return np.array(raw.decode("utf-8", "replace").split("\n")[:-1])
