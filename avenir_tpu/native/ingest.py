"""ctypes binding for the native CSV ingest (csv_ingest.cpp).

The shared library builds lazily with g++ on first use (no pybind11 in the
image; plain `extern "C"` + ctypes per the environment constraints) and is
cached next to the source. Everything degrades to the Python parser when a
compiler is unavailable — `native_available()` gates the fast path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "csv_ingest.cpp")
_LIB = os.path.join(_DIR, "libcsv_ingest.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    if not os.path.exists(_LIB) or (
        os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
    ):
        try:
            subprocess.run(
                ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                 "-pthread", "-o", _LIB, _SRC],
                check=True, capture_output=True, timeout=120,
            )
        except (OSError, subprocess.SubprocessError):
            _build_failed = True
            return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        # corrupt / wrong-arch cached .so: degrade to the Python parser
        _build_failed = True
        return None
    c_char_p = ctypes.c_char_p
    i64, i32 = ctypes.c_int64, ctypes.c_int32
    p_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    p_f32 = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    p_i64 = ctypes.POINTER(i64)

    lib.csv_count_rows.restype = i64
    lib.csv_count_rows.argtypes = [c_char_p, i64]
    lib.csv_count_rows_mt.restype = i64
    lib.csv_count_rows_mt.argtypes = [c_char_p, i64, i32]
    lib.csv_parse.restype = i64
    lib.csv_parse.argtypes = [
        c_char_p, i64, ctypes.c_char, i32,
        p_i32, i32, p_f32,
        p_i32, i32, c_char_p, p_i32, p_i32, i64,
        p_i64, ctypes.POINTER(i32),
    ]
    lib.csv_parse_mt.restype = i64
    lib.csv_parse_mt.argtypes = lib.csv_parse.argtypes + [i32]
    lib.csv_column_bytes.restype = i64
    lib.csv_column_bytes.argtypes = [c_char_p, i64, ctypes.c_char, i32]
    lib.csv_extract_column.restype = i64
    lib.csv_extract_column.argtypes = [c_char_p, i64, ctypes.c_char, i32,
                                       ctypes.c_char_p, i64]
    p_i64_arr = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.seq_token_count.restype = i64
    lib.seq_token_count.argtypes = [c_char_p, i64, ctypes.c_char, p_i64]
    lib.seq_encode.restype = i64
    lib.seq_encode.argtypes = [c_char_p, i64, ctypes.c_char,
                               c_char_p, i32, p_i32, i64, p_i64_arr, i64]
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None and not _build_failed:
        with _lock:
            if _lib is None and not _build_failed:
                _lib = _build()
    return _lib


def native_available() -> bool:
    return _get_lib() is not None


def parse_csv_native(
    data: bytes,
    delim: str,
    numeric_ordinals: List[int],
    categorical: List[Tuple[int, List[str]]],   # (ordinal, cardinality)
    string_ordinals: List[int],
    lazy_strings: bool = False,
    threads: int = 0,
) -> Tuple[int, Dict[int, np.ndarray], Dict[int, object]]:
    """One native pass: (n_rows, {ordinal: column array}, {ordinal: thunk}).

    Numeric columns come back float32 (missing -> NaN), categorical int32
    codes against the given cardinalities (unknown value raises ValueError,
    matching the Python parser's contract), string/id columns as numpy
    object arrays — or, with lazy_strings=True, as zero-arg thunks in the
    third return value (materializing millions of python strings costs
    more than the whole numeric/categorical parse; algorithms that never
    read ids skip it entirely)."""
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native CSV ingest unavailable (no g++?)")
    d = delim.encode()[0:1]
    n = int(lib.csv_count_rows_mt(data, len(data), np.int32(threads)))
    columns: Dict[int, np.ndarray] = {}

    num_ords = np.asarray(numeric_ordinals, np.int32)
    cat_ords = np.asarray([o for o, _ in categorical], np.int32)
    vocab_blob = b"".join(
        v.encode() + b"\0" for _, card in categorical for v in card
    )
    vocab_counts = np.asarray([len(card) for _, card in categorical], np.int32)
    all_ords = list(numeric_ordinals) + [o for o, _ in categorical] + list(
        string_ordinals)
    max_ord = max(all_ords) if all_ords else 0

    # prefill sentinels: rows shorter than the schema leave numeric NaN
    # (matching the Python parser) and categorical -1 (checked below)
    num_out = np.full((len(num_ords), n), np.nan, np.float32)
    cat_out = np.full((len(cat_ords), n), -1, np.int32)
    err_row = ctypes.c_int64(-1)
    err_ord = ctypes.c_int32(-1)
    # threads=0 lets the library pick hardware_concurrency; stripes are
    # capped so small buffers stay on the sequential path (identical
    # semantics either way — the MT entry splits at newline boundaries
    # into disjoint global row ranges)
    got = int(lib.csv_parse_mt(
        data, len(data), d, np.int32(max_ord),
        num_ords, len(num_ords), num_out,
        cat_ords, len(cat_ords), vocab_blob, vocab_counts, cat_out,
        np.int64(n), ctypes.byref(err_row), ctypes.byref(err_ord),
        np.int32(threads),
    ))
    if got < 0:
        # recover the offending token for the standard error message
        bad = _extract_column(lib, data, d, int(err_ord.value))
        tok = bad[err_row.value] if err_row.value < len(bad) else "?"
        if got == -2:
            raise ValueError(
                f"could not convert string to float: {tok!r} at ordinal "
                f"{err_ord.value}")
        raise ValueError(
            f"value {tok!r} not in declared cardinality of ordinal "
            f"{err_ord.value}")
    for i, o in enumerate(numeric_ordinals):
        columns[o] = num_out[i]
    for i, (o, _) in enumerate(categorical):
        if (cat_out[i] < 0).any():
            row = int(np.argmax(cat_out[i] < 0))
            raise ValueError(
                f"value '' not in declared cardinality of ordinal {o} "
                f"(row {row} is short)")
        columns[o] = cat_out[i]
    lazy: Dict[int, object] = {}
    for o in string_ordinals:
        if lazy_strings:
            # the native extraction runs now into a COMPACT per-column
            # buffer (so the thunk does not pin the whole CSV block); only
            # the python-string materialization — the expensive part — is
            # deferred
            raw = _extract_column_bytes(lib, data, d, o)
            lazy[o] = (lambda r=raw: np.array(
                r.decode().split("\n")[:-1], dtype=object))
        else:
            columns[o] = np.array(_extract_column(lib, data, d, o),
                                  dtype=object)
    return got, columns, lazy


def _extract_column_bytes(lib, data: bytes, d: bytes, ordinal: int) -> bytes:
    cap = int(lib.csv_column_bytes(data, len(data), d, np.int32(ordinal)))
    buf = ctypes.create_string_buffer(max(cap, 1))
    w = int(lib.csv_extract_column(data, len(data), d, np.int32(ordinal),
                                   buf, np.int64(cap)))
    return buf.raw[:w] if w > 0 else b""


def _extract_column(lib, data: bytes, d: bytes, ordinal: int) -> List[str]:
    raw = _extract_column_bytes(lib, data, d, ordinal)
    if not raw:
        return []
    return raw.decode().split("\n")[:-1]


def seq_encode_native(data: bytes, delim: str, vocab: List[str]
                      ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Ragged tokenize + dictionary-encode a text block against one
    vocabulary (the sequence-job ingest). Returns (codes int32
    [total_tokens], offsets int64 [rows+1]) in CSR form — token t of row
    r is codes[offsets[r] + t]; unknown tokens are -1. None when the
    native library is unavailable (callers fall back to Python split)."""
    lib = _get_lib()
    if lib is None:
        return None
    d = delim.encode()
    if len(d) != 1:
        return None
    n_tokens = ctypes.c_int64(0)
    n_rows = int(lib.seq_token_count(data, len(data), d,
                                     ctypes.byref(n_tokens)))
    codes = np.empty(max(n_tokens.value, 1), np.int32)
    offsets = np.empty(n_rows + 1, np.int64)
    blob = b"".join(v.encode() + b"\0" for v in vocab)
    got = int(lib.seq_encode(data, len(data), d, blob, len(vocab),
                             codes, codes.shape[0], offsets, n_rows + 1))
    if got != n_rows:
        raise RuntimeError(f"seq_encode row mismatch: {got} != {n_rows}")
    return codes[: int(offsets[n_rows])], offsets


def native_seq_ready(delim: str) -> bool:
    """True when the native sequence encoder handles this delimiter
    (single byte) and the library is built — the gate every CSR
    consumer checks before taking the byte-block path."""
    return len(delim.encode()) == 1 and native_available()


def csr_rows(offsets: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(row_of [total_tokens], starts [n_rows]) for a CSR offsets array —
    the shared row-decode of every seq_encode consumer (markov fit_csr,
    HMM add_csr, apriori counting chunks). row_of is int32: a block
    never holds 2^31 rows (blocks are tens of MB), and the token-
    proportional arrays dominate a streaming pass's transient RSS, so
    halving them matters at scale."""
    return (np.repeat(np.arange(offsets.shape[0] - 1, dtype=np.int32),
                      np.diff(offsets)),
            offsets[:-1])


def csr_region_mask(offsets: np.ndarray, skip: int, n_tokens: int
                    ) -> np.ndarray:
    """bool [n_tokens]: True where a token sits at within-row position
    >= skip (the item/sequence region past the meta fields). Built by
    unmarking the first `skip` positions of each row — O(rows * skip)
    small arrays instead of the arange(n_tokens) + starts[row_of]
    int64 temporaries the naive position compare materializes (those
    were the largest transients of the miners' streaming passes)."""
    region = np.ones(n_tokens, bool)
    starts, ends = offsets[:-1], offsets[1:]
    for j in range(skip):
        pos = starts + j
        region[pos[pos < ends]] = False
    return region


def scan_encode_blocks(paths, delim: str, skip: int, vocab: List[str],
                       index: Dict[str, int], block_bytes: int,
                       marker: Optional[str] = None):
    """Vocabulary-DISCOVERING native scan: yield (codes, offsets, region,
    n_rows) per byte block — the shared pass-1 engine of the streaming
    miners (association scan_items, sequence scan).

    Each block encodes against the CURRENT vocab plus two drop
    sentinels (the infrequent-item marker and the empty token, which
    would otherwise read as unknown and force the slow path on every
    block of a trailing-delimiter CSV). A block with genuinely unknown
    tokens takes one Python pass to extend `vocab`/`index` in place,
    then re-encodes — but only if that pass actually added something;
    steady-state blocks of a vocabulary-stable stream never touch
    per-row Python. `region` is True exactly at item positions holding
    a REAL vocab code (sentinels, ids and short rows excluded), so
    callers can fold counts straight off (codes[region], row_of[region]).
    """
    from avenir_tpu.core.stream import iter_byte_blocks, prefetched

    sentinels = ([marker] if marker is not None else []) + [""]
    for path in paths:
        for data in prefetched(iter_byte_blocks(path, block_bytes),
                               depth=1):
            codes, offsets = seq_encode_native(data, delim,
                                               vocab + sentinels)
            n = offsets.shape[0] - 1
            if n <= 0:
                continue
            region = csr_region_mask(offsets, skip, codes.shape[0])
            if (codes[region] < 0).any():
                added = False
                for ln in data.decode("utf-8", "replace").split("\n"):
                    if not ln.strip():
                        continue
                    for tok in [t.strip(" \t\r")
                                for t in ln.split(delim)][skip:]:
                        if tok and tok != marker and tok not in index:
                            index[tok] = len(vocab)
                            vocab.append(tok)
                            added = True
                if added:
                    codes, offsets = seq_encode_native(data, delim,
                                                       vocab + sentinels)
            v = len(vocab)
            np.logical_and(region, codes >= 0, out=region)
            np.logical_and(region, codes < v, out=region)   # sentinels drop
            yield codes, offsets, region, n


def distinct_row_code_counts(row_of: np.ndarray, codes: np.ndarray,
                             region: np.ndarray, v: int) -> np.ndarray:
    """counts[c] = #rows whose region tokens include code c, each row
    counted once (the multi-hot k=1 support algebra): in-place sort +
    consecutive-diff dedup, so the int64 key array is the only
    token-sized temporary — no np.unique copy."""
    keys = row_of[region].astype(np.int64) * v + codes[region]
    keys.sort()
    if not keys.shape[0]:
        return np.zeros(v, np.int64)
    uniq = np.empty(keys.shape[0], bool)
    uniq[0] = True
    np.not_equal(keys[1:], keys[:-1], out=uniq[1:])
    return np.bincount((keys[uniq] % v).astype(np.intp), minlength=v)


def extract_column_native(data: bytes, delim: str, ordinal: int
                          ) -> Optional[np.ndarray]:
    """One column's trimmed tokens for every non-blank line of a raw text
    block (short rows yield ''), as a numpy unicode array — the open-
    vocabulary companion to seq_encode_native (entity ids cannot
    dictionary-encode). None when the native library is unavailable."""
    lib = _get_lib()
    if lib is None:
        return None
    d = delim.encode()
    if len(d) != 1:
        return None
    raw = _extract_column_bytes(lib, data, d, ordinal)
    return np.array(raw.decode("utf-8", "replace").split("\n")[:-1])
