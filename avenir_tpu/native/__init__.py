from avenir_tpu.native.ingest import native_available, parse_csv_native
