"""Fault tolerance for the job-server fleet: supervision, leases, hedging.

PR 12 built the fleet's happy path; this module is the back half the
ROADMAP's fleet item names: a host process dying mid-scan must not
strand the requests it had claimed, and a host running hot must not
hold the tail hostage. The license for all of it is the repo's
idempotency contract: every request is byte-identical by construction
(the merge-algebra and stream-invariance audits prove it) and every
result is nonce-namespaced and atomically renamed into place, so
RE-EXECUTION IS ALWAYS SAFE — a requeued or hedged duplicate of a
request that later finishes anyway is a harmless identical write,
never a conflict. That is exactly the framing of "Leveraging Coding
Techniques for Speeding up Distributed Computing" (arXiv:1802.03049):
when recomputation is free of coordination, redundancy beats waiting.

Four pieces, policy here, mechanism in :mod:`avenir_tpu.net.fleet` and
:mod:`avenir_tpu.net.router`:

- **Supervision** — the fleet front watches its host subprocesses: the
  exit code (a dead process is certain), the spool heartbeat (the
  host's ``metrics.json`` mtime — a ``serve --spool`` host refreshes
  it from its scheduler tick, so a frozen file means a wedged or
  stopped process), and ``/healthz`` for hosts that expose a listener
  (:func:`probe_healthz`). A dead host is restarted with capped
  exponential backoff; a host that dies repeatedly inside the
  quarantine window is QUARANTINED — dropped from placement until an
  operator reinstates it (:class:`RestartTracker` is the policy).
- **Request leases** — every placed request carries a lease file
  (host id, claim time, TTL, attempt trail) under the fleet root
  (:class:`LeaseStore`). The front renews leases while the assigned
  host stays healthy; when the host dies or stops heartbeating, the
  expired lease is swept and the request REQUEUED to a different
  healthy host (the failed ones excluded), capped at
  ``max_requeues`` so a request that kills every host it touches
  becomes an in-band failure row instead of a fleet-wide crash loop.
- **Hedged tail dispatch** — when one host's rolled-up queue-wait p99
  (its served histogram, or the age its oldest PENDING request has
  already accrued — a live lower bound of the same number) runs past
  ``hedge_multiple``× the fleet median, the front mirrors that host's
  queued requests onto the least-loaded compatible host and takes
  whichever result lands first (:func:`hot_hosts` is the decision).
  The mirror is charged against the budget vector like any placement.
- **Failover + reintegration** — the router drops a quarantined or
  dead host out of its sticky map (corpora re-place by the normal
  least-loaded rule, counted as ``failovers``); a recovered host
  re-earns affinity through hits, never through a map reset.

Everything is deterministic under test: the chaos harness
(``bench_scaling.fleet_fault_tripwire``) SIGKILLs a host mid-batch and
asserts zero lost and zero conflicting results, byte-identical to solo
twins; the hedging leg stalls a host and asserts the mirror fires and
the first result wins.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from avenir_tpu.core.atomic import (publish_json, sched_point,
                                    sweep_stale_tmps, unique_tmp)


@dataclass
class FaultPolicy:
    """The fleet's fault-tolerance knobs, all in one place.

    ``supervise=False`` turns the whole layer off (the fleet behaves
    exactly as the PR-12 happy path: a dead host raises FleetError).
    The defaults are serving-scale; tests and the chaos harness dial
    them down for determinism."""

    supervise: bool = True
    #: supervisor tick granularity
    poll_interval_s: float = 0.25
    #: metrics.json older than this on a live process = stalled host
    heartbeat_timeout_s: float = 10.0
    #: restart backoff: base * 2^deaths, capped
    restart_backoff_base_s: float = 0.5
    restart_backoff_cap_s: float = 10.0
    #: deaths inside the window before the host is quarantined
    max_restarts: int = 3
    quarantine_window_s: float = 120.0
    #: lease TTL: how long a request may sit on an UNHEALTHY host
    #: before the front requeues it (healthy hosts renew their leases)
    lease_ttl_s: float = 10.0
    #: attempts before a request is failed in-band instead of requeued
    #: (a poison request must not crash-loop the whole fleet)
    max_requeues: int = 2
    #: how long a STRANDED request (attempt trail covers every host,
    #: none healthy) may wait for a restarting/stalled host to recover
    #: before it is abandoned in-band — the bound that keeps "never
    #: hang to the collect() timeout" true even when the only hosts
    #: left are permanently wedged (STALLED never restarts: only an
    #: exit code triggers respawn)
    stranded_patience_s: float = 60.0
    #: hedge when a host's queue-wait p99 (or oldest pending age) runs
    #: past this multiple of the fleet median
    hedge_multiple: float = 4.0
    #: the median is floored here so an all-idle fleet (median ~0) does
    #: not hedge every microscopic wobble
    hedge_floor_ms: float = 1000.0
    hedge: bool = True


#: host supervision states (the router mirrors these as availability)
SERVING = "serving"
RESTARTING = "restarting"
STALLED = "stalled"
QUARANTINED = "quarantined"
STOPPED = "stopped"

#: states a host can take NEW placements in
PLACEABLE_STATES = (SERVING,)


class RestartTracker:
    """Restart/quarantine policy for ONE host: record deaths, answer
    the backoff delay before the next respawn, and flip to quarantine
    when the host dies ``max_restarts`` times inside the window. Pure
    bookkeeping — callers pass ``now`` so tests drive the clock. The
    clock is ``time.monotonic()``: backoff and the quarantine window
    are in-process durations, and an NTP step of the wall clock must
    never stretch or collapse them (the fleet passes its monotonic
    tick time; only lease files persisted across processes carry wall
    timestamps)."""

    def __init__(self, policy: FaultPolicy):
        self.policy = policy
        self.deaths: List[float] = []

    def record_death(self, now: float) -> str:
        """Record one death at `now`; returns the next state —
        :data:`RESTARTING` (respawn after :meth:`backoff_s`) or
        :data:`QUARANTINED` (stop respawning)."""
        self.deaths.append(now)
        window = self.policy.quarantine_window_s
        recent = [t for t in self.deaths if now - t <= window]
        self.deaths = recent
        if len(recent) > self.policy.max_restarts:
            return QUARANTINED
        return RESTARTING

    def backoff_s(self) -> float:
        """Capped exponential backoff before the next respawn."""
        deaths = max(len(self.deaths), 1)
        return min(self.policy.restart_backoff_base_s
                   * (2.0 ** (deaths - 1)),
                   self.policy.restart_backoff_cap_s)

    @property
    def recent_deaths(self) -> int:
        """Deaths still inside the quarantine window — the number the
        quarantine verdict is judged on, NOT a lifetime restart count
        (the fleet tracks that itself)."""
        return len(self.deaths)


@dataclass
class Lease:
    """One placed request's claim record: who holds it, since when,
    for how long, and the attempt trail (hosts already tried — the
    requeue excludes them)."""

    name: str
    host: int
    claimed_at: float
    ttl_s: float
    attempts: int = 1
    hosts: List[int] = field(default_factory=list)
    nonce: Optional[str] = None

    def expired(self, now: float) -> bool:
        return now - self.claimed_at > self.ttl_s

    def to_dict(self) -> Dict:
        return {"name": self.name, "host": self.host,
                "claimed_at": self.claimed_at, "ttl_s": self.ttl_s,
                "attempts": self.attempts, "hosts": list(self.hosts),
                "nonce": self.nonce}

    @classmethod
    def from_dict(cls, obj: Dict) -> "Lease":
        return cls(name=str(obj["name"]), host=int(obj["host"]),
                   claimed_at=float(obj["claimed_at"]),
                   ttl_s=float(obj["ttl_s"]),
                   attempts=int(obj.get("attempts", 1)),
                   hosts=[int(h) for h in obj.get("hosts", [])],
                   nonce=obj.get("nonce"))


class LeaseStore:
    """Lease files under ``<fleet-root>/leases/`` — one JSON per
    outstanding request, atomically renamed in (the spool discipline),
    removed when the result is swept. On-disk so the claim trail
    survives a front restart and an operator can inspect exactly which
    host owes which request (``ls leases/`` is the debugging surface
    the chaos harness reads back)."""

    def __init__(self, root: str):
        self.dir = os.path.join(root, "leases")
        os.makedirs(self.dir, exist_ok=True)
        # startup GC: tmp files a hard-killed front left behind (the
        # age gate keeps a concurrent writer's live tmp safe)
        sweep_stale_tmps(self.dir)

    def path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def write(self, lease: Lease) -> str:
        return publish_json(lease.to_dict(), self.path(lease.name),
                            site="lease.write")

    def renew(self, lease: Lease, now: float) -> None:
        """Re-stamp the claim time — the sweep for a HEALTHY host."""
        sched_point("lease.renew")
        lease.claimed_at = now
        self.write(lease)

    def load(self, name: str) -> Optional[Lease]:
        try:
            with open(self.path(name)) as fh:
                return Lease.from_dict(json.load(fh))
        except (OSError, ValueError, KeyError):
            return None           # torn mid-rename or already swept

    def take(self, name: str) -> Optional[Lease]:
        """Atomically CLAIM a lease file for exclusive handling: rename
        it aside (exactly one of N racing sweepers wins the rename),
        parse the taken copy, remove the aside, return the Lease — or
        None when someone else took/removed it first or the copy is
        torn. This is the sweep's compare-and-swap: between a plain
        :meth:`load` and the requeue that acts on it, a healthy front
        may RENEW the lease, and destroying that renewal double-places
        the request. ``take`` moves the decision onto one atomic
        rename: whatever state the taken copy shows is the state the
        caller owns. The aside uses the protocol tmp naming so a
        crashed taker's leftover is GC'd by :func:`sweep_stale_tmps`
        and never read back as a live lease by :meth:`names`."""
        sched_point("lease.sweep")
        aside = unique_tmp(self.path(name))
        try:
            os.rename(self.path(name), aside)
        except OSError:
            return None            # lost the race (taken or removed)
        sched_point("lease.sweep")
        try:
            with open(aside) as fh:
                return Lease.from_dict(json.load(fh))
        except (OSError, ValueError, KeyError):
            return None           # torn by an external writer
        finally:
            try:
                os.remove(aside)
            except OSError:
                pass

    def remove(self, name: str) -> None:
        try:
            os.remove(self.path(name))
        except OSError:
            pass

    def names(self) -> List[str]:
        try:
            return sorted(n for n in os.listdir(self.dir)
                          if not n.endswith(".tmp"))
        except OSError:
            return []


def hot_hosts(p99_by_host: Dict[int, float],
              pending_age_ms: Dict[int, float],
              policy: FaultPolicy,
              healthy: Sequence[int]) -> List[int]:
    """The hedge decision: which healthy hosts' queued requests should
    be mirrored. A host is HOT when its effective queue-wait p99 — the
    max of its rolled-up served p99 and the age its oldest pending
    request has already accrued (a live lower bound of the p99 a
    stalled host will eventually report) — exceeds ``hedge_multiple``
    times the fleet median (floored at ``hedge_floor_ms``). Pure
    function: the chaos harness and tests drive it with synthetic
    numbers."""
    if not policy.hedge or len(healthy) < 2:
        return []
    effective = {
        h: max(p99_by_host.get(h, 0.0), pending_age_ms.get(h, 0.0))
        for h in healthy}
    ordered = sorted(effective.values())
    # LOWER middle for even counts: with 2 hosts the upper middle IS
    # the slow host, which would set its own threshold and never hedge
    median = ordered[(len(ordered) - 1) // 2]
    threshold = policy.hedge_multiple * max(median,
                                            policy.hedge_floor_ms)
    return [h for h, eff in sorted(effective.items())
            if eff > threshold]


def probe_healthz(address: str, timeout: float = 2.0) -> Optional[str]:
    """The ``/healthz`` status string of a listener-fronted host
    (``"serving"``, ``"draining"``, ``"quarantined"``, ``"restarting"``
    — the states :meth:`NetListener.set_health_state` surfaces), or
    None when the probe fails (connection refused = the process is
    gone; the exit-code check is the authority there)."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(f"{address}/healthz",
                                    timeout=timeout) as resp:
            return json.load(resp).get("status")
    except urllib.error.HTTPError as exc:
        try:
            return json.loads(exc.read() or b"{}").get("status")
        except ValueError:
            return None
    except (OSError, ValueError):
        return None


def heartbeat_age_s(metrics_path: str, now: Optional[float] = None
                    ) -> Optional[float]:
    """Seconds since the host last refreshed its ``metrics.json``
    heartbeat, or None when the file does not exist yet (a host still
    booting has no heartbeat to be stale)."""
    try:
        mtime = os.stat(metrics_path).st_mtime
    except OSError:
        return None
    return (time.time() if now is None else now) - mtime


class Supervisor:
    """The fleet's supervision thread: calls ``tick()`` every
    ``interval_s`` until stopped. The tick body lives on the Fleet
    (where the locks already are); this class owns only the thread's
    lifecycle — started by ``Fleet.start``, joined (bounded) by
    ``Fleet.stop`` — so the graftlint --flow thread contract has one
    obvious owner. A tick that raises is recorded and the loop keeps
    going: supervision must outlive a transient filesystem hiccup."""

    def __init__(self, tick, interval_s: float):
        import threading

        self._tick = tick
        self._interval_s = float(interval_s)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._errors: List[str] = []
        self._thread = threading.Thread(target=self._loop,
                                        name="avenir-fleet-supervisor",
                                        daemon=True)

    def start(self) -> "Supervisor":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as exc:  # noqa: BLE001 — supervision survives
                with self._lock:
                    self._errors.append(f"{type(exc).__name__}: {exc}")
                    del self._errors[:-8]
            self._stop.wait(self._interval_s)

    def errors(self) -> List[str]:
        with self._lock:
            return list(self._errors)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._thread.join(timeout)
