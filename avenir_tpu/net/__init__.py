"""avenir-net: the network front half of the resident job server.

Three layers over the transport-agnostic server/spool surface that PR 9
deliberately left open (`ROADMAP.md` "networked, multi-host job-server
fleet"):

- **Listener** (:mod:`avenir_tpu.net.listener`): a stdlib-only
  JSON-over-HTTP/1.1 edge wrapping ``JobServer.submit``/``result``.
  Backpressure is wired to the admission model: a request whose priced
  bytes would push the edge's outstanding total past the server budget,
  or whose tenant queue is past its depth bound, is answered
  ``429 Retry-After`` (or held at the edge, per policy) instead of
  being queued toward OOM. ``GET /metrics`` serves the live snapshot,
  ``GET /healthz`` the drain state.
- **Affinity router** (:mod:`avenir_tpu.net.router`): places requests
  across N server processes by corpus affinity — a tenant's corpus
  keeps hitting the process whose WarmStore already pins its encoded
  blocks and managed checkpoints — against a per-host priced-bytes
  budget *vector* (``price_request_bytes`` generalized to a vector of
  per-host ceilings), with spillover to the least-loaded host with
  headroom and per-profile fold-cost weighting from the autotune store.
- **Fleet** (:mod:`avenir_tpu.net.fleet`): N ``serve --spool``
  subprocesses (same host first; the spool is already host-agnostic),
  a front loop routing requests into per-host spools and rolling the
  per-host ``metrics.json`` snapshots up into one fleet view through
  the additive ``LatencyHistogram.merge`` algebra. Surfaced as
  ``python -m avenir_tpu fleet``; load-tested open-loop by
  ``tools/fleet_load.py``; gated by ``bench_scaling.fleet_tripwire``.
"""

from avenir_tpu.net.fleet import Fleet, fleet_main
from avenir_tpu.net.listener import EdgePolicy, NetListener
from avenir_tpu.net.router import AffinityRouter, RouterError

__all__ = ["AffinityRouter", "RouterError", "EdgePolicy", "NetListener",
           "Fleet", "fleet_main"]
