"""A fleet of job-server processes behind one affinity router.

One resident JobServer amortizes scans/compiles across tenants but is
still one Python process on one core-set; the fleet layer is the
scale-out: N ``serve --spool`` subprocesses (same host here — the spool
transport is already host-agnostic, so a host list later is a mount
away), each with its own spool, byte budget and warm state, fed by an
:class:`~avenir_tpu.net.router.AffinityRouter` that keeps a corpus
hitting the process whose WarmStore already pins its encoded blocks and
checkpoints, against a per-host priced-bytes budget vector.

The front half runs in the CALLER's process:

- :class:`Fleet` — spawn/stop the server processes, ``submit`` request
  objects (priced by ``price_request_bytes``, placed by the router,
  written atomically into the placed host's spool ``in/``),
  ``collect`` result rows from the per-host ``out/`` dirs, and roll
  the per-host ``metrics.json`` snapshots into ONE fleet view through
  the additive ``LatencyHistogram.merge`` algebra
  (``obs.report.merge_snapshots``) with the router's placement stats
  attached.
- :func:`fleet_main` — ``python -m avenir_tpu fleet``: a fleet-level
  spool (requests into ``<root>/in/``, results out of ``<root>/out/``)
  so tenants address ONE directory and the router fans out behind it.
  SIGTERM/SIGINT drain gracefully: stop claiming, finish in-flight,
  final merged metrics.json, exit 0.

Placement cost: when a profile store (``avenir_tpu.tune``) is
configured, the router's tie-break consults the measured per-chunk fold
cost of each (job, corpus) — a corpus whose folds are measured
expensive counts for more pending load than its bytes alone say.

Fault tolerance (avenir-fault, :mod:`avenir_tpu.net.fault`): a
supervisor thread watches the host processes (exit code + spool
heartbeat = the host's ``metrics.json`` mtime), restarts a dead host
with capped exponential backoff and quarantines one that dies
repeatedly; every placed request carries a LEASE file under
``<root>/leases/`` that the front renews while the host stays healthy
and sweeps when it does not — the request requeues to a different
healthy host (failed ones excluded), and because results are
nonce-namespaced, byte-identical by construction and atomically
renamed into place, a slow original finishing late is a harmless
duplicate write, never a conflict. When a healthy host's queue-wait
tail runs hot past the fleet median, its queued requests are MIRRORED
to the least-loaded compatible host (hedged dispatch, charged against
the budget vector) and the first result to land wins. All of it is
policy-driven by :class:`~avenir_tpu.net.fault.FaultPolicy` and gated
by ``bench_scaling.fleet_fault_tripwire``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from avenir_tpu.core.atomic import publish_json
from avenir_tpu.net import fault
from avenir_tpu.net.fault import (FaultPolicy, Lease, LeaseStore,
                                  RestartTracker, Supervisor)
from avenir_tpu.net.router import AffinityRouter, Placement
from avenir_tpu.server.spool import (nonce_result_name,
                                     request_from_json, spool_dirs)

#: fleet front poll granularity (seconds)
_POLL_SECS = 0.1
#: price-memo freshness: long enough to amortize an arrival burst over
#: a hot corpus, short enough that a growing refresh corpus re-prices
_PRICE_MEMO_TTL_SECS = 30.0
#: price-memo size bound for resident fronts
_PRICE_MEMO_MAX = 4096


def _pkg_parent() -> str:
    import avenir_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(avenir_tpu.__file__)))


def affinity_key(request) -> Tuple:
    """The router's sticky key: the corpus identity (mode + absolute
    input paths) — the component of ``server.compat_key`` warm state
    actually keys on. Everything else (job, conf) may vary per request
    without moving the corpus off its warm host."""
    return (request.mode,
            tuple(os.path.abspath(p) for p in request.inputs))


def score_affinity_key(kind: str, model: str) -> Tuple:
    """The QUERY path's sticky key: the model identity. A host that
    scored an artifact holds it loaded in its ModelCache (and its
    jitted predict compiled), so repeat scores are cheapest exactly
    there — the same warmth argument ``affinity_key`` makes for
    corpora, at model granularity. Request row / round / conf may vary
    without moving the model off its warm host (they are excluded from
    ``core.keys.model_tuple`` for the same reason)."""
    return ("score", kind, os.path.abspath(model))


class ScoreFront:
    """Model-affinity fan-out for ``POST /score`` across listener
    URLs: every score places through an :class:`AffinityRouter` keyed
    by :func:`score_affinity_key`, so one artifact's queries pin to
    one host's warm ModelCache while distinct models spread across the
    fleet. One persistent HTTP/1.1 connection per (thread, host) —
    the keep-alive socket is what keeps per-score transport cost below
    the score itself."""

    def __init__(self, urls: Sequence[str],
                 budgets: Optional[Sequence[int]] = None):
        if not urls:
            raise ValueError("score front needs at least one listener")
        self.urls = [u.rstrip("/") for u in urls]
        self.router = AffinityRouter(
            list(budgets) if budgets else [1 << 30] * len(self.urls))
        self._local = threading.local()
        # every connection ever handed out, across ALL threads —
        # close() runs on one thread but must reach the keep-alive
        # sockets the other scoring threads opened
        self._conns_lock = threading.Lock()
        self._all_conns: List = []

    def _conn(self, host: int, fresh: bool = False):
        import http.client
        from urllib.parse import urlsplit as _split
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        conn = conns.get(host)
        if fresh and conn is not None:
            conn.close()
            with self._conns_lock:
                if conn in self._all_conns:
                    self._all_conns.remove(conn)
            conn = None
        if conn is None:
            conn = conns[host] = http.client.HTTPConnection(
                _split(self.urls[host]).netloc, timeout=120)
            with self._conns_lock:
                self._all_conns.append(conn)
        return conn

    @staticmethod
    def _decode(resp) -> Dict:
        """The response body as a dict; a torn/non-JSON body (a host
        dying mid-write) decodes to {} so the status check below turns
        it into a FleetError instead of a raw traceback."""
        try:
            payload = json.loads(resp.read())
        except (OSError, ValueError):
            return {}
        return payload if isinstance(payload, dict) else {}

    def score(self, kind: str, model: str, row: str,
              conf: Optional[Dict[str, str]] = None,
              action: str = "score", req_id: str = "",
              timeout: float = 30.0) -> Dict:
        """Route one score (or reward append) to the model's warm
        host; returns the decoded response body. Raises FleetError on
        a non-200 answer (the body's error text attached)."""
        import http.client
        if action == "reward" and not req_id:
            # a reward append is only retry-safe when the journal can
            # nonce-dedupe it: the fresh-connection retry below can
            # land after the host already committed the first send, so
            # an empty req_id would double-apply the observation. Mint
            # one; both sends carry the same body, so the second
            # dedupes server-side.
            req_id = uuid.uuid4().hex
        body = json.dumps({"kind": kind, "model": model, "row": row,
                           "conf": conf or {}, "action": action,
                           "req_id": req_id}).encode()
        placement = self.router.place(score_affinity_key(kind, model),
                                      priced_bytes=len(body))
        if placement is None:
            raise FleetError("no score host has budget headroom")
        try:
            target = f"/score?timeout={timeout}"
            headers = {"Content-Type": "application/json"}
            conn = self._conn(placement.host)
            try:
                conn.request("POST", target, body, headers)
                resp = conn.getresponse()
                payload = self._decode(resp)
            except (OSError, http.client.HTTPException):
                # the host may have idle-closed the persistent socket;
                # one fresh-connection retry, then the error is real
                conn = self._conn(placement.host, fresh=True)
                conn.request("POST", target, body, headers)
                resp = conn.getresponse()
                payload = self._decode(resp)
            if resp.status != 200:
                raise FleetError(
                    f"score host {placement.host} answered "
                    f"{resp.status}: {payload.get('error')}")
            return payload
        finally:
            self.router.release(placement)

    def snapshot(self) -> Dict:
        return self.router.snapshot()

    def close(self) -> None:
        with self._conns_lock:
            conns, self._all_conns = self._all_conns, []
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        local = getattr(self._local, "conns", None)
        if local:
            local.clear()


class FleetError(RuntimeError):
    """A fleet host died or refused to start."""


class _Copy:
    """One spooled COPY of an outstanding request: the original
    placement, a requeue, or a hedged mirror — each with its own spool
    name, out path and budget accounting."""

    __slots__ = ("placement", "name", "out_path")

    def __init__(self, placement: Placement, name: str, out_path: str):
        self.placement = placement
        self.name = name
        self.out_path = out_path


class _Outstanding:
    """One submitted request the front is waiting on. ``copies`` holds
    every spooled copy (original + requeues + mirrors); the first
    result to land on ANY copy's out path wins and releases all of
    them — re-execution is safe by the idempotency contract, so a late
    duplicate is an identical write, never a conflict.

    ``submitted_at`` and ``stranded_at`` are ``time.monotonic()``
    stamps: they drive in-process age/patience arithmetic (the hedge's
    pending-age clock, the stranded-patience bound), which an NTP step
    of the wall clock must never stretch or collapse. Only the lease's
    ``claimed_at`` — persisted to disk and compared against file
    mtimes across processes — stays wall-clock."""

    __slots__ = ("copies", "obj", "submitted_at", "lease", "mirrored",
                 "stranded_at")

    def __init__(self, copy: _Copy, obj: Dict, submitted_at: float,
                 lease: Lease):
        self.copies = [copy]
        self.obj = obj
        self.submitted_at = submitted_at
        self.lease = lease
        self.mirrored = False
        #: when the request first became STRANDED (trail covers every
        #: host, none healthy) — the patience clock _rescue_stranded
        #: abandons on; None while the request has a way forward
        self.stranded_at: Optional[float] = None


class Fleet:
    """N job-server processes + the affinity front (module docstring).

    Construct, ``start()``, ``submit()`` request objects (the spool
    JSON schema), ``collect()`` rows, ``stop()``. The budget vector is
    one ``budget_mb`` entry per host; ``profile_dir`` opts placement
    into fold-cost weighting and is forwarded to every host as its
    autotune store.

    Single-writer: one Fleet coordinates one spool tree — request
    names come from a per-instance sequence and every ``in/`` spool
    write is this process's alone (hosts only ever RENAME requests out
    and publish results to ``out/``). The one cross-process seam, the
    lease trail, is serialized through ``LeaseStore.take``'s
    rename-aside CAS (graftlint --race, lease.sweep site)."""

    def __init__(self, root: str, hosts: int = 2,
                 budget_mb: float = 3072.0, workers: int = 1,
                 warm_budget_mb: float = 256.0,
                 metrics_interval_s: float = 0.5,
                 profile_dir: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 pin_cores: Optional[Sequence[int]] = None,
                 fault_policy: Optional[FaultPolicy] = None,
                 listen_addresses: Optional[Dict[int, str]] = None):
        """``pin_cores``: pin host i to CPU ``pin_cores[i % len]``
        (Linux ``sched_setaffinity``; ignored where unsupported). On a
        shared box an UNPINNED single process borrows every core
        through XLA's intra-op threads, so a same-box fleet-vs-one
        comparison measures nothing — pinning one core per host is
        what makes a single machine a faithful proxy for N hosts
        (``bench_scaling.fleet_tripwire`` relies on it).

        ``listen_addresses``: base URL per host index (e.g.
        ``{0: "http://127.0.0.1:8191"}``) for hosts that run a
        ``--listen`` edge — the supervisor then heartbeats those hosts
        through ``fault.probe_healthz`` (/healthz) instead of the
        metrics.json mtime: a listener answering "serving"/"draining"
        is live; a refused probe or a quarantined/restarting overlay
        marks the host stalled and out of placement. The exit-code
        check stays authoritative for death either way."""
        if hosts < 1:
            raise ValueError("fleet needs at least one host")
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.host_dirs = [os.path.join(self.root, f"host{i}")
                          for i in range(hosts)]
        self.budget_bytes = int(budget_mb * (1 << 20))
        self.router = AffinityRouter([self.budget_bytes] * hosts)
        self.workers = int(workers)
        self.warm_budget_mb = float(warm_budget_mb)
        self.metrics_interval_s = float(metrics_interval_s)
        self.profile_dir = profile_dir
        self._env = env
        self.pin_cores = list(pin_cores) if pin_cores else None
        self.fault = fault_policy or FaultPolicy()
        self.listen_addresses = dict(listen_addresses or {})
        #: per-host (stamped_at, hb_live) memo of the last /healthz
        #: probe: the probe is a blocking HTTP round trip (a WEDGED
        #: listener holds the connection to the timeout — the exact
        #: state it exists to detect), so it must not run every tick or
        #: stalled hosts would stall the whole supervisor loop past the
        #: lease-renewal window; probing at half the heartbeat budget
        #: keeps detection latency inside the same bound the mtime
        #: heartbeat has
        self._probe_memo: Dict[int, Tuple[float, bool]] = {}
        self._procs: List[Optional[subprocess.Popen]] = [None] * hosts
        self._logs: List[str] = [
            os.path.join(d, "server.log") for d in self.host_dirs]
        self._lock = threading.Lock()
        self._seq = 0
        self._outstanding: Dict[str, _Outstanding] = {}
        # ---- fault-tolerance state (avenir_tpu.net.fault) ----
        self._leases = LeaseStore(self.root)
        self._trackers = [RestartTracker(self.fault)
                          for _ in range(hosts)]
        self._host_state = [fault.SERVING] * hosts
        self._restart_at: List[Optional[float]] = [None] * hosts
        #: wall-clock spawn stamp — compared against lease claimed_at
        #: (a persisted wall timestamp) for the incarnation check
        self._spawned_at = [0.0] * hosts
        #: monotonic spawn stamp — drives boot-grace and heartbeat-age
        #: fallbacks (in-process durations; immune to NTP steps)
        self._spawned_mono = [0.0] * hosts
        self._supervisor: Optional[Supervisor] = None
        # a heartbeat bound tighter than the metrics refresh would mark
        # every host stalled between writes
        self._hb_timeout = max(self.fault.heartbeat_timeout_s,
                               4.0 * self.metrics_interval_s)
        self._fault_stats = {"requeues": 0, "respools": 0,
                             "restarts": 0, "quarantined": 0,
                             "abandoned": 0}
        self._restart_counts = [0] * hosts
        #: finished rows swept off disk but not yet collect()ed — the
        #: submit loop's capacity sweep must never lose a row a later
        #: named collect() will ask for
        self._collected: Dict[str, Dict] = {}
        # pricing memo: corpus_stats head-samples the corpus per call,
        # so an open-loop front pricing hundreds of arrivals over a few
        # hot corpora would pay the sample per request; identical
        # (job, conf, corpus, mode) submissions price once, and the
        # profile-store fold cost rides along. Entries expire (a
        # refresh corpus GROWS between rounds — a price from its
        # smallest snapshot must not undercount the vector forever)
        # and the dict is bounded for resident fronts. Value:
        # (priced_bytes, cost_ms, stamped_at).
        self._price_memo: Dict[Tuple, Tuple] = {}

    # ------------------------------------------------------------ lifecycle
    def _host_env(self) -> Dict[str, str]:
        env = dict(os.environ if self._env is None else self._env)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (_pkg_parent(), env.get("PYTHONPATH")) if p)
        return env

    def _spawn_host(self, i: int) -> None:
        """(Re)spawn host `i`'s ``serve --spool`` process — shared by
        ``start()`` and the supervisor's restart path, so a restarted
        host comes back with the identical config (budget, state root,
        core pin) it died with."""
        host_dir = self.host_dirs[i]
        os.makedirs(host_dir, exist_ok=True)
        cmd = [sys.executable, "-m", "avenir_tpu", "serve",
               "--spool", host_dir,
               "--workers", str(self.workers),
               "--budget-mb", str(self.budget_bytes / (1 << 20)),
               "--warm-budget-mb", str(self.warm_budget_mb),
               "--state-root", os.path.join(host_dir, "state"),
               "--metrics-interval", str(self.metrics_interval_s)]
        if self.profile_dir:
            # hosts share ONE profile store: a fold cost measured on
            # any host informs placement for all of them
            cmd += ["--autotune-dir", self.profile_dir]
        preexec = None
        if self.pin_cores and hasattr(os, "sched_setaffinity"):
            core = self.pin_cores[i % len(self.pin_cores)]
            preexec = (lambda c=core:
                       os.sched_setaffinity(0, {c}))
        with open(self._logs[i], "ab") as log:
            proc = subprocess.Popen(cmd, stdout=log, stderr=log,
                                    env=self._host_env(),
                                    cwd=_pkg_parent(),
                                    preexec_fn=preexec)
        with self._lock:
            self._procs[i] = proc
            self._spawned_at[i] = time.time()
            self._spawned_mono[i] = time.monotonic()

    def start(self, timeout: float = 60.0) -> "Fleet":
        for i in range(len(self.host_dirs)):
            self._spawn_host(i)
        deadline = time.perf_counter() + timeout
        for i, host_dir in enumerate(self.host_dirs):
            in_dir = os.path.join(host_dir, "in")
            while not os.path.isdir(in_dir):
                # strict at boot: a host that cannot START is a config
                # error the caller must see, not a runtime fault for
                # the supervisor to mask by restarting forever
                self._check_alive(strict=True)
                if time.perf_counter() > deadline:
                    raise FleetError(
                        f"host {i} did not open its spool within "
                        f"{timeout}s (log: {self._logs[i]})")
                time.sleep(_POLL_SECS)
        if self.fault.supervise:
            self._supervisor = Supervisor(
                self._fault_tick, self.fault.poll_interval_s).start()
        return self

    def _check_alive(self, strict: bool = False) -> None:
        """With supervision on, a dead host is the SUPERVISOR's problem
        (restart/quarantine) and callers only fail when every host is
        quarantined — nothing left to requeue to. ``strict`` (boot, or
        supervision off) keeps the PR-12 behavior: any dead host
        raises."""
        if self.fault.supervise and not strict:
            with self._lock:
                states = list(self._host_state)
            if all(s == fault.QUARANTINED for s in states):
                raise FleetError(
                    "every fleet host is quarantined (died "
                    f"> {self.fault.max_restarts} times inside "
                    f"{self.fault.quarantine_window_s}s); logs: "
                    f"{self._logs}")
            return
        for i, proc in enumerate(self._procs):
            rc = proc.poll() if proc is not None else None
            if rc is not None and rc != 0:
                tail = _tail(self._logs[i])
                raise FleetError(
                    f"fleet host {i} exited rc={rc}; log tail:\n{tail}")

    def host_pid(self, i: int) -> Optional[int]:
        """Host `i`'s live process id (None while dead/quarantined) —
        the chaos harness's SIGKILL target."""
        with self._lock:
            proc = self._procs[i]
        return proc.pid if proc is not None else None

    def host_state(self, i: int) -> str:
        with self._lock:
            return self._host_state[i]

    def reinstate(self, i: int) -> None:
        """Operator reintegration of a quarantined host: clear its
        death record and respawn it. The sticky map is NOT restored —
        the host re-earns affinity through fresh hits, so a flapping
        host cannot yank corpora back and forth."""
        with self._lock:
            if self._host_state[i] != fault.QUARANTINED:
                raise FleetError(
                    f"host {i} is {self._host_state[i]}, not "
                    f"quarantined")
            self._trackers[i] = RestartTracker(self.fault)
        self._spawn_host(i)
        with self._lock:
            self._restart_counts[i] += 1
        self._set_host_state(i, fault.SERVING)

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ submitting
    def price(self, obj: Dict) -> Tuple[object, int, Optional[float]]:
        """(request, priced bytes, fold cost ms) of one request object
        — the placement inputs. Pricing uses the same oracle the hosts
        admit with; fold cost comes from the shared profile store when
        one is configured."""
        req = request_from_json(obj)
        memo_key = (req.job, req.mode,
                    tuple(os.path.abspath(p) for p in req.inputs),
                    json.dumps(req.conf, sort_keys=True)
                    if isinstance(req.conf, dict) else str(req.conf))
        now = time.perf_counter()
        with self._lock:
            hit = self._price_memo.get(memo_key)
            if hit is not None and now - hit[2] < _PRICE_MEMO_TTL_SECS:
                return req, hit[0], hit[1]
        priced = self._pricer()(req)
        cost = None
        if self.profile_dir:
            # the fold cost rides the same memo: re-reading the profile
            # store's JSON per arrival would pay a disk read per
            # request on exactly the hot-corpus path the memo exists
            # for
            from avenir_tpu import tune

            cost = tune.placement_cost_ms(self.profile_dir, req.job,
                                          req.conf, req.inputs)
        with self._lock:
            if len(self._price_memo) >= _PRICE_MEMO_MAX:
                self._price_memo = {
                    k: v for k, v in self._price_memo.items()
                    if now - v[2] < _PRICE_MEMO_TTL_SECS}
                if len(self._price_memo) >= _PRICE_MEMO_MAX:
                    self._price_memo.clear()
            self._price_memo[memo_key] = (priced, cost, now)
        return req, priced, cost

    def _pricer(self):
        """The front's pricing oracle — the SAME one the hosts admit
        with: the residual-corrected tuned pricer when a profile store
        is configured (the hosts get it via --autotune-dir), the bare
        footprint model otherwise. A front that raw-priced what a host
        tuned-prices would place work the host then fast-fails."""
        fn = getattr(self, "_pricer_fn", None)
        if fn is not None:
            return fn
        from avenir_tpu.server.jobserver import (DEFAULT_RESERVE_BYTES,
                                                 price_request_bytes)

        if self.profile_dir:
            from avenir_tpu import tune

            base = tune.make_tuned_pricer(self.profile_dir,
                                          base=price_request_bytes)
        else:
            base = price_request_bytes
        self._pricer_fn = fn = \
            lambda req: int(base([req], DEFAULT_RESERVE_BYTES))
        return fn

    def submit(self, obj: Dict, block: bool = True,
               timeout: float = 600.0,
               count_held: bool = True) -> Optional[str]:
        """Route one request object to a host spool; returns the fleet
        request name to ``collect`` on, or None when every host is over
        its budget-vector entry and ``block`` is False. Blocking waits
        for a host to free capacity — the fleet-front analog of the
        single server's admission hold. ``count_held=False`` marks a
        caller-level retry of an arrival already counted held."""
        req, priced, cost = self.price(obj)
        key = affinity_key(req)
        deadline = time.perf_counter() + timeout
        while True:
            placement = self.router.place(key, priced, cost,
                                          count_held=count_held)
            if placement is not None:
                break
            count_held = False        # this arrival is counted now
            # capacity frees only when finished requests are swept off
            # disk — a blocking submit must sweep ITSELF or a saturated
            # single-threaded front would spin the full timeout while
            # every host sits idle with its results already written
            self._sweep()
            if not block:
                return None
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"no host freed budget for a {priced}-byte request "
                    f"within {timeout}s")
            self._check_alive()
            time.sleep(_POLL_SECS)
        return self._spool_to(placement, obj)

    def submit_to(self, host: int, obj: Dict) -> str:
        """Pin one request to `host`, bypassing the router (warmup
        traffic that must touch a SPECIFIC process). Accounted against
        the budget vector like any placement."""
        req, priced, cost = self.price(obj)
        placement = self.router.assign_to(host, affinity_key(req),
                                          priced, cost)
        return self._spool_to(placement, obj)

    def _next_name(self) -> str:
        with self._lock:
            self._seq += 1
            return f"r{self._seq:06d}.json"

    def _write_copy(self, placement: Placement, name: str,
                    obj: Dict) -> _Copy:
        """Spool one copy of `obj` into its placed host's ``in/``
        (atomic tmp+rename) and return the copy record."""
        host_dir = self.host_dirs[placement.host]
        out_name = nonce_result_name(name, obj.get("nonce"))
        out_path = os.path.join(host_dir, "out", out_name)
        publish_json(obj, os.path.join(host_dir, "in", name))
        return _Copy(placement, name, out_path)

    def _spool_to(self, placement: Placement, obj: Dict) -> str:
        name = self._next_name()
        now = time.time()
        lease = Lease(name=name, host=placement.host, claimed_at=now,
                      ttl_s=self.fault.lease_ttl_s,
                      hosts=[placement.host], nonce=obj.get("nonce"))
        # lease BEFORE the spool write: the supervisor must never see a
        # claimed request it has no lease record for
        self._leases.write(lease)
        copy = self._write_copy(placement, name, obj)
        with self._lock:
            self._outstanding[name] = _Outstanding(
                copy, obj, time.monotonic(), lease)
        return name

    # ------------------------------------------------------------ collecting
    def ready(self) -> List[str]:
        """Names of submitted requests whose result row is available
        (already swept, or on disk) — what a non-blocking front sweep
        collects."""
        with self._lock:
            entries = [(n, [c.out_path for c in e.copies])
                       for n, e in self._outstanding.items()]
            banked = list(self._collected)
        return banked + [n for n, paths in entries
                         if any(os.path.exists(p) for p in paths)]

    def _sweep(self) -> int:
        """Move every finished request's row off disk into the
        collected bank and release its router accounting — the FIRST
        copy (original, requeue or mirror) whose row landed wins; the
        others' late identical writes are ignored. Returns how many
        were swept. Idempotent and safe to call from the submit loop,
        the collect loop and the supervisor tick — a banked row waits
        for its named ``collect``."""
        with self._lock:
            entries = [(n, e, list(e.copies))
                       for n, e in self._outstanding.items()]
        swept = 0
        for name, entry, copies in entries:
            row = None
            for copy in copies:
                if not os.path.exists(copy.out_path):
                    continue
                # the publish is atomic, but this reader still races
                # deletion (another sweeper collecting the same name):
                # a vanished/torn row is absent, never a crash
                try:
                    with open(copy.out_path) as fh:
                        row = json.load(fh)
                except (OSError, ValueError):
                    continue
                break                     # first-write-wins
            if row is None:
                continue
            with self._lock:
                if self._outstanding.pop(name, None) is None:
                    continue              # raced another sweeper
                self._collected[name] = row
                copies = list(entry.copies)
            _release_placements(self.router, copies)
            self._leases.remove(name)
            swept += 1
        return swept

    def collect(self, names: Optional[Sequence[str]] = None,
                timeout: float = 600.0) -> Dict[str, Dict]:
        """Block until every named request (default: all submitted,
        uncollected) has a result row; returns {name: row}. Router
        accounting is released as each row is swept off disk."""
        with self._lock:
            wanted = list(names) if names is not None else \
                list(self._outstanding) + list(self._collected)
            unknown = [n for n in wanted
                       if n not in self._outstanding
                       and n not in self._collected]
        if unknown:
            raise KeyError(f"unknown fleet request(s) {unknown}")
        rows: Dict[str, Dict] = {}
        deadline = time.perf_counter() + timeout
        while True:
            self._sweep()
            with self._lock:
                for name in wanted:
                    if name not in rows and name in self._collected:
                        rows[name] = self._collected.pop(name)
            if len(rows) == len(wanted):
                return rows
            self._check_alive()
            if time.perf_counter() > deadline:
                missing = [n for n in wanted if n not in rows]
                raise TimeoutError(
                    f"fleet results {missing} not served within "
                    f"{timeout}s")
            time.sleep(_POLL_SECS)

    # -------------------------------------------------------- fault tolerance
    def _fault_tick(self) -> None:
        """One supervisor pass (fault.Supervisor drives this every
        ``poll_interval_s``): sweep finished results, watch the host
        processes, sweep/renew leases, hedge the hot tail. Two clocks:
        ``wall`` stamps/compares the persisted lease records (cross-
        process file timestamps), ``mono`` drives every in-process
        duration (backoff, boot grace, patience, hedge age)."""
        wall = time.time()
        mono = time.monotonic()
        self._sweep()
        self._supervise_hosts(wall, mono)
        self._sweep_leases(wall, mono)
        if self.fault.hedge:
            self._hedge(mono)

    def _set_host_state(self, i: int, state: str) -> None:
        with self._lock:
            self._host_state[i] = state
        self.router.set_host_state(i, state)

    def _supervise_hosts(self, now: float,
                         mono: Optional[float] = None) -> None:
        """Host supervision for one tick. ``now`` is wall-clock (only
        the heartbeat mtime comparison needs it); ``mono`` drives
        death/backoff/boot-grace arithmetic — restart scheduling must
        not stretch or collapse under an NTP step."""
        mono = time.monotonic() if mono is None else mono
        for i in range(len(self.host_dirs)):
            with self._lock:
                state = self._host_state[i]
                proc = self._procs[i]
                restart_at = self._restart_at[i]
                spawned_mono = self._spawned_mono[i]
            if state in (fault.QUARANTINED, fault.STOPPED):
                continue
            rc = proc.poll() if proc is not None else None
            if proc is not None and rc is not None:
                # death is certain (exit code in hand): requeue its
                # leases NOW — waiting out the TTL buys nothing
                verdict = self._trackers[i].record_death(mono)
                with self._lock:
                    self._procs[i] = None
                if verdict == fault.QUARANTINED:
                    self._set_host_state(i, fault.QUARANTINED)
                    with self._lock:
                        self._fault_stats["quarantined"] += 1
                else:
                    self._set_host_state(i, fault.RESTARTING)
                    with self._lock:
                        self._restart_at[i] = \
                            mono + self._trackers[i].backoff_s()
                continue
            if state == fault.RESTARTING:
                if proc is None and restart_at is not None \
                        and mono >= restart_at:
                    self._spawn_host(i)
                    with self._lock:
                        self._fault_stats["restarts"] += 1
                        self._restart_counts[i] += 1
                        self._restart_at[i] = None
                elif proc is not None:
                    # booted when the spool is back: placements resume;
                    # affinity is re-EARNED through hits, never reset
                    if os.path.isdir(os.path.join(self.host_dirs[i],
                                                  "in")):
                        self._set_host_state(i, fault.SERVING)
                continue
            # alive host: a listener-fronted host heartbeats through
            # /healthz (fault.probe_healthz — "serving"/"draining"
            # answers are live, a refused probe or a quarantined/
            # restarting overlay is not); spool-only hosts heartbeat
            # through the metrics.json mtime. Either way a live
            # process that stopped answering is wedged or stopped
            # (SIGSTOP, hard IO stall) and must not take new
            # placements
            booting = mono - spawned_mono <= self._hb_timeout
            addr = self.listen_addresses.get(i)
            if addr is not None:
                hb_live = self._probe_host(i, addr, mono)
                if state == fault.SERVING and not hb_live \
                        and not booting:
                    self._set_host_state(i, fault.STALLED)
                elif state == fault.STALLED and hb_live:
                    self._set_host_state(i, fault.SERVING)
                continue
            age = fault.heartbeat_age_s(
                os.path.join(self.host_dirs[i], "metrics.json"), now)
            if age is None:
                age = mono - spawned_mono
            if state == fault.SERVING and age > self._hb_timeout \
                    and not booting:
                self._set_host_state(i, fault.STALLED)
            elif state == fault.STALLED and age <= self._hb_timeout:
                self._set_host_state(i, fault.SERVING)

    def _probe_host(self, i: int, addr: str, mono: float) -> bool:
        """Memoized /healthz liveness of a listener-fronted host:
        re-probes at most every hb_timeout/2 with a timeout bounded
        well under the heartbeat budget, so N wedged listeners can
        never stall the supervisor tick past the lease-renewal
        window. The memo ages on the monotonic clock — a wall step
        must not force (or starve) a re-probe."""
        hit = self._probe_memo.get(i)
        if hit is not None and mono - hit[0] < self._hb_timeout / 2.0:
            return hit[1]
        timeout = min(2.0, max(self._hb_timeout / 4.0, 0.25))
        status = fault.probe_healthz(addr, timeout=timeout)
        hb_live = status in ("serving", "draining")
        self._probe_memo[i] = (mono, hb_live)
        return hb_live

    @staticmethod
    def _copy_on(entry: _Outstanding, host: int) -> _Copy:
        """The entry's newest copy spooled AT `host` (the lease host's
        own spool file — requeues and mirrors live elsewhere)."""
        for copy in reversed(entry.copies):
            if copy.placement.host == host:
                return copy
        return entry.copies[-1]

    def _sweep_leases(self, now: float,
                      mono: Optional[float] = None) -> None:
        """Renew the leases of requests sitting on healthy hosts;
        requeue the ones whose host died (immediately) or went
        stale/stalled past the lease TTL. A lease predating its host's
        CURRENT incarnation is stranded even though the host looks
        healthy: a claim taken by the dead process sits in its old
        ``work/`` dir, which a restarted host never re-adopts — those
        requeue too (or re-spool to the restarted host when no other
        host can take them).

        ``now`` is wall-clock — lease claimed_at stamps and the
        incarnation comparison are persisted wall timestamps; ``mono``
        feeds the stranded-patience clock and the hedge's pending-age
        restart (in-process durations)."""
        mono = time.monotonic() if mono is None else mono
        with self._lock:
            entries = list(self._outstanding.items())
        for name, entry in entries:
            lease = entry.lease
            with self._lock:
                state = self._host_state[lease.host]
                dead = self._procs[lease.host] is None
                spawned_at = self._spawned_at[lease.host]
            healthy = state == fault.SERVING and not dead
            if healthy and lease.claimed_at < spawned_at:
                # pre-restart lease: if the spool file still sits in
                # in/, the new incarnation will claim it normally —
                # restamp and move on; otherwise the old process died
                # holding the claim and the request must move
                copy = self._copy_on(entry, lease.host)
                in_path = os.path.join(self.host_dirs[lease.host],
                                       "in", copy.name)
                if os.path.exists(in_path):
                    self._leases.renew(lease, now)
                elif not self._requeue(name, entry, now, mono):
                    self._respool(name, entry, now, mono)
                continue
            if healthy:
                if now - lease.claimed_at > lease.ttl_s / 2.0:
                    self._leases.renew(lease, now)
                continue
            if dead or state in (fault.RESTARTING, fault.QUARANTINED) \
                    or lease.expired(now):
                taken = None
                if not dead and state not in (fault.RESTARTING,
                                              fault.QUARANTINED):
                    # pure TTL expiry: the verdict above came from an
                    # IN-MEMORY stamp, and a concurrent front may have
                    # renewed the lease FILE since — a plain
                    # requeue-on-load would destroy that renewal and
                    # double-place the request. take() is the CAS:
                    # exactly one sweeper owns the file, and whatever
                    # the taken copy says is the truth acted on.
                    taken = self._leases.take(name)
                    if taken is None:
                        continue   # completed or taken under us
                    if not taken.expired(now):
                        self._leases.write(taken)  # renewed under us
                        continue
                    entry.lease = lease = taken    # own the real trail
                if not self._requeue(name, entry, now, mono):
                    if taken is not None:
                        # took the file but could not move the request:
                        # put the trail back on disk before waiting,
                        # so the claim stays operator-visible and the
                        # next tick's take() finds it again
                        self._leases.write(taken)
                    # the requeue found no excluded-compliant host: a
                    # STRANDED request (trail covers every host) must
                    # respool or abandon in-band, never hang until the
                    # caller's collect() timeout
                    self._rescue_stranded(name, entry, now, mono)

    def _requeue(self, name: str, entry: _Outstanding, now: float,
                 mono: Optional[float] = None) -> bool:
        """Move one stranded request to a different healthy host,
        excluding every host it already failed on. Capped at
        ``max_requeues`` attempts — a request that kills every host it
        touches becomes an in-band failure row, never a fleet-wide
        crash loop. Returns True when the request was handled (moved
        or abandoned), False when no excluded-compliant host had
        headroom this tick."""
        lease = entry.lease
        if lease.attempts > self.fault.max_requeues:
            self._abandon(
                name, entry,
                f"request abandoned after {lease.attempts} attempts "
                f"across hosts {lease.hosts} (max_requeues="
                f"{self.fault.max_requeues})")
            return True
        req, priced, cost = self.price(entry.obj)
        placement = self.router.place(affinity_key(req), priced, cost,
                                      count_held=False,
                                      exclude=lease.hosts)
        if placement is None:
            return False           # no healthy headroom yet: next tick
        stranded = self._copy_on(entry, lease.host)
        new_name = self._next_name()
        copy = self._write_copy(placement, new_name, entry.obj)
        with self._lock:
            # append-under-membership: a sweep that popped the entry
            # already released every copy it could SEE, so a late copy
            # must release itself instead of joining the entry
            landed = name not in self._outstanding
            if not landed:
                entry.copies.append(copy)
                self._fault_stats["requeues"] += 1
        if landed:
            self.router.release(placement)
            try:
                os.remove(os.path.join(
                    self.host_dirs[placement.host], "in", new_name))
            except OSError:
                pass
            return True
        # best-effort unspool of the stranded copy: if the old host's
        # in/ file is still unclaimed, removing it stops a restarted
        # host from re-running work that now lives elsewhere (a claimed
        # copy is beyond reach — its late result is a harmless
        # duplicate write)
        try:
            os.remove(os.path.join(self.host_dirs[lease.host], "in",
                                   stranded.name))
        except OSError:
            pass
        lease.host = placement.host
        lease.claimed_at = now
        lease.attempts += 1
        lease.hosts.append(placement.host)
        # the hedge's pending-age clock restarts with the new host: an
        # inherited age would make a fresh requeue target look hot
        entry.submitted_at = \
            time.monotonic() if mono is None else mono
        self._leases.write(lease)
        return True

    def _abandon(self, name: str, entry: _Outstanding,
                 error: str) -> None:
        """Resolve one outstanding request as an in-band failure row:
        the terminal move for a poison request past the requeue cap
        and for a stranded request no host can ever take again. The
        row honors the nonce namespace, every copy's placement is
        released, the lease removed — the caller's collect() returns
        a failure instead of timing out."""
        lease = entry.lease
        row = {"ok": False, "error": error}
        if lease.nonce:
            row["nonce"] = lease.nonce
        with self._lock:
            if self._outstanding.pop(name, None) is None:
                return             # raced a sweep: the result landed
            self._collected[name] = row
            self._fault_stats["abandoned"] += 1
            copies = list(entry.copies)
        _release_placements(self.router, copies)
        self._leases.remove(name)

    def _rescue_stranded(self, name: str, entry: _Outstanding,
                         now: float,
                         mono: Optional[float] = None) -> None:
        """A request the requeue could not move this tick. Distinguish
        'no headroom yet' (an untried SERVING host may still take it —
        wait, capacity frees when results land) from STRANDED: the
        attempt trail covers every host, so no requeue can ever land.
        A stranded request resolves in-band — respooled to a healthy
        trail host (re-execution is safe by the idempotency contract,
        and the respool's attempt bump walks it into the max_requeues
        cap if the failures keep coming) or abandoned with a failure
        row: immediately when every host is quarantined/stopped, and
        after ``stranded_patience_s`` when the only hosts left are
        restarting/stalled (a brief stall recovers; a permanently
        wedged host must not hold the request to the collect()
        timeout — STALLED never respawns, only an exit code does).
        ``attempts`` only grows on moves, so the cap alone can never
        fire for a request nobody can move. The patience clock runs on
        ``mono`` — a wall step must neither abandon a request early
        nor hold it past the bound."""
        mono = time.monotonic() if mono is None else mono
        lease = entry.lease
        with self._lock:
            states = list(self._host_state)
            procs = list(self._procs)
        trail = set(lease.hosts)
        if any(h not in trail and s == fault.SERVING
               for h, s in enumerate(states)):
            entry.stranded_at = None
            return                 # headroom wait: capacity frees
        healthy_trail = [h for h in sorted(trail)
                         if h < len(states)
                         and states[h] == fault.SERVING
                         and procs[h] is not None]
        if healthy_trail:
            entry.stranded_at = None
            self._respool(name, entry, now, mono,
                          host=healthy_trail[0])
            return
        if any(s in (fault.RESTARTING, fault.STALLED) for s in states):
            # a host may yet recover: wait, but only within patience
            if entry.stranded_at is None:
                entry.stranded_at = mono
            if mono - entry.stranded_at \
                    <= self.fault.stranded_patience_s:
                return
        self._abandon(
            name, entry,
            f"request stranded: attempt trail {sorted(trail)} covers "
            f"every host and none is healthy (states {states})")

    def _respool(self, name: str, entry: _Outstanding, now: float,
                 mono: Optional[float] = None,
                 host: Optional[int] = None) -> None:
        """Re-spool a stranded request into a trail host's OWN in/ —
        the fallback when the requeue exclusion leaves no other host.
        Default target: the lease host (the restarted-incarnation
        case: the new process never saw the claim the old one died
        holding); a stranded request whose lease host stays dead
        respools to any healthy trail host instead. Re-execution is
        safe, so handing the request back beats never serving it. The
        copy rides that host's EXISTING placement charge (same host,
        same request — not new load)."""
        lease = entry.lease
        if lease.attempts > self.fault.max_requeues:
            return                 # the requeue cap will abandon it
        host = lease.host if host is None else host
        prior = self._copy_on(entry, host)
        new_name = self._next_name()
        copy = self._write_copy(prior.placement, new_name, entry.obj)
        with self._lock:
            landed = name not in self._outstanding
            if not landed:
                entry.copies.append(copy)
                self._fault_stats["respools"] += 1
        if landed:                 # raced a sweep: just unspool it
            try:
                os.remove(os.path.join(
                    self.host_dirs[host], "in", new_name))
            except OSError:
                pass
            return
        lease.host = host
        lease.claimed_at = now
        lease.attempts += 1
        entry.submitted_at = \
            time.monotonic() if mono is None else mono
        self._leases.write(lease)

    def _rolled_p99(self) -> Dict[int, Tuple[float, int]]:
        """Each host's rolled-up (queue-wait p99 ms, served count)
        from its own metrics snapshot — the hedging signal's served
        half. The count gates hedging: a host that has never finished
        a request has no measured tail to run hot — it is warming up,
        not straggling."""
        out: Dict[int, Tuple[float, int]] = {}
        for i, host_dir in enumerate(self.host_dirs):
            try:
                with open(os.path.join(host_dir, "metrics.json")) as fh:
                    snap = json.load(fh)
                hist = (snap.get("hists") or {}).get("queue_wait_ms",
                                                     {})
                out[i] = (float(hist.get("p99", 0.0)),
                          int(hist.get("count", 0)))
            except (OSError, ValueError):
                out[i] = (0.0, 0)
        return out

    def _hedge(self, mono: float) -> None:
        """Hedged tail dispatch: when one host's queue-wait tail runs
        past ``hedge_multiple``x the fleet median, mirror its queued
        requests onto the least-loaded compatible host and let the
        first result win (module docstring; fault.hot_hosts is the
        decision). The pending-age clock is monotonic: a wall step
        must not make every queued request look instantly hot."""
        with self._lock:
            healthy = [i for i, s in enumerate(self._host_state)
                       if s == fault.SERVING]
            entries = list(self._outstanding.items())
        pending_age: Dict[int, float] = {}
        for _name, entry in entries:
            if entry.mirrored:
                continue
            age_ms = (mono - entry.submitted_at) * 1000.0
            host = entry.lease.host
            pending_age[host] = max(pending_age.get(host, 0.0), age_ms)
        rolled = self._rolled_p99()
        hot = fault.hot_hosts({h: p99 for h, (p99, _n) in rolled.items()},
                              pending_age, self.fault, healthy)
        # only a host with a MEASURED tail (>=1 served request) can be
        # "hot": a host still compiling its first request is cold, and
        # mirroring its queue would just double the warmup bill
        hot = [h for h in hot if rolled.get(h, (0.0, 0))[1] > 0]
        if not hot:
            return
        for name, entry in entries:
            if entry.mirrored or entry.lease.host not in hot:
                continue
            req, priced, cost = self.price(entry.obj)
            placement = self.router.place_mirror(
                affinity_key(req), priced, cost,
                exclude=entry.lease.hosts)
            if placement is None:
                continue           # no headroom: hedging never holds
            mirror_name = self._next_name()
            copy = self._write_copy(placement, mirror_name, entry.obj)
            with self._lock:
                landed = name not in self._outstanding
                if not landed:
                    entry.copies.append(copy)
                    entry.mirrored = True
            if landed:             # raced a sweep: release the mirror
                self.router.release(placement)
                try:
                    os.remove(os.path.join(
                        self.host_dirs[placement.host], "in",
                        mirror_name))
                except OSError:
                    pass
                continue
            entry.lease.hosts.append(placement.host)
            self._leases.write(entry.lease)

    def fault_snapshot(self) -> Dict:
        """The supervision view the merged fleet metrics carry: per-
        host state + restart counts, the requeue/hedge counters, and
        any errors the supervisor loop survived."""
        with self._lock:
            states = list(self._host_state)
            stats = dict(self._fault_stats)
            restarts = list(self._restart_counts)
        return {
            "hosts": [{"host": i, "state": s, "restarts": restarts[i],
                       "recent_deaths":
                           self._trackers[i].recent_deaths}
                      for i, s in enumerate(states)],
            "stats": stats,
            "leases_outstanding": len(self._leases.names()),
            "supervisor_errors": (self._supervisor.errors()
                                  if self._supervisor else []),
        }

    # --------------------------------------------------------------- metrics
    def merged_metrics(self) -> Dict:
        """The fleet snapshot: per-host metrics.json files folded into
        one through the additive histogram merge, with the router's
        placement stats and budget-vector occupancy attached
        (docs/observability.md "Fleet roll-up")."""
        from avenir_tpu.obs.report import merge_snapshots

        snaps = []
        for host_dir in self.host_dirs:
            path = os.path.join(host_dir, "metrics.json")
            try:
                with open(path) as fh:
                    snaps.append(json.load(fh))
            except (OSError, ValueError):
                continue            # host not up yet / mid-rename
        merged = merge_snapshots(snaps)
        merged["router"] = self.router.snapshot()
        merged["supervision"] = self.fault_snapshot()
        return merged

    def write_metrics(self, path: Optional[str] = None) -> str:
        path = path or os.path.join(self.root, "metrics.json")
        return publish_json(self.merged_metrics(), path)

    # ------------------------------------------------------------- stopping
    def stop(self, timeout: float = 120.0) -> List[Optional[int]]:
        """Graceful fleet shutdown: stop the supervisor (no restarts
        racing the teardown), SIGCONT + SIGTERM every live host (their
        handlers drain: finish claimed work, final per-host
        metrics.json, exit 0 — the SIGCONT first so a stopped/stalled
        host can even SEE the signal), join, write the final merged
        metrics. Returns the per-host exit codes; a host that needed
        SIGKILL reports rc < 0, a host already dead/quarantined reports
        None."""
        if self._supervisor is not None:
            self._supervisor.stop()
            self._supervisor = None
        with self._lock:
            self._host_state = [fault.STOPPED] * len(self.host_dirs)
            procs = list(self._procs)
        for proc in procs:
            if proc is not None and proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGCONT)
                    proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        codes: List[Optional[int]] = []
        deadline = time.perf_counter() + timeout
        for proc in procs:
            if proc is None:
                codes.append(None)
                continue
            remaining = max(deadline - time.perf_counter(), 0.1)
            try:
                codes.append(proc.wait(timeout=remaining))
            except subprocess.TimeoutExpired:
                proc.kill()
                codes.append(proc.wait())
        with self._lock:
            self._procs = [None] * len(self.host_dirs)
        try:
            self.write_metrics()
        except OSError:
            pass
        return codes


def _release_placements(router: AffinityRouter,
                        copies: Sequence[_Copy]) -> None:
    """Release every DISTINCT placement behind an entry's copies — a
    re-spooled copy shares its predecessor's placement (same host,
    same charge), so releasing per copy would double-credit the
    budget vector."""
    seen: set = set()
    for copy in copies:
        if id(copy.placement) in seen:
            continue
        seen.add(id(copy.placement))
        router.release(copy.placement)


def _tail(path: str, nbytes: int = 800) -> str:
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            fh.seek(max(fh.tell() - nbytes, 0))
            return fh.read().decode(errors="replace")
    except OSError:
        return "<no log>"


# --------------------------------------------------------------------------
# the fleet CLI
# --------------------------------------------------------------------------
def fleet_main(argv) -> int:
    """``python -m avenir_tpu fleet --root DIR --hosts N [...]`` — the
    fleet-level spool session (module docstring)."""
    import argparse

    from avenir_tpu.server.spool import (_claim, install_drain_handlers,
                                         load_claimed)

    ap = argparse.ArgumentParser(prog="avenir_tpu fleet")
    ap.add_argument("--root", required=True,
                    help="fleet root: requests in <root>/in, results in "
                         "<root>/out, hosts under <root>/host<i>")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--workers", type=int, default=1,
                    help="worker threads per host process (default 1)")
    ap.add_argument("--budget-mb", type=float, default=3072.0,
                    help="per-host admission budget — one entry of the "
                         "fleet's budget vector (default 3072)")
    ap.add_argument("--once", action="store_true",
                    help="serve what is spooled, drain, exit")
    ap.add_argument("--profile-dir", default=None,
                    help="autotune profile store consulted for "
                         "fold-cost-weighted placement")
    ap.add_argument("--metrics-interval", type=float, default=1.0)
    ap.add_argument("--no-supervise", action="store_true",
                    help="disable host supervision/leases/hedging "
                         "(PR-12 behavior: a dead host is fatal)")
    ap.add_argument("--lease-ttl", type=float,
                    default=FaultPolicy.lease_ttl_s,
                    help="request lease TTL in seconds before an "
                         "unhealthy host's claims requeue (default "
                         f"{FaultPolicy.lease_ttl_s})")
    ap.add_argument("--hedge-multiple", type=float,
                    default=FaultPolicy.hedge_multiple,
                    help="mirror a host's queued requests when its "
                         "queue-wait p99 exceeds this multiple of the "
                         "fleet median (default "
                         f"{FaultPolicy.hedge_multiple}; <=0 disables)")
    args = ap.parse_args(argv)

    in_dir, work_dir, out_dir = spool_dirs(args.root)
    policy = FaultPolicy(
        supervise=not args.no_supervise, lease_ttl_s=args.lease_ttl,
        hedge=args.hedge_multiple > 0,
        hedge_multiple=max(args.hedge_multiple, 0.1))
    fleet = Fleet(args.root, hosts=args.hosts, budget_mb=args.budget_mb,
                  workers=args.workers, profile_dir=args.profile_dir,
                  metrics_interval_s=min(args.metrics_interval, 1.0),
                  fault_policy=policy)
    stop_event = threading.Event()
    should_stop = install_drain_handlers(stop_event)
    failures = 0
    #: fleet request name -> (client name, nonce, work path): the work
    #: file survives until the final out/ row lands (serve_spool's own
    #: discipline), so a front crash never silently loses an accepted
    #: request — the file is still in work/ for recovery
    submitted: Dict[str, Tuple[str, Optional[str], str]] = {}
    #: claimed but not yet placeable (every host over its vector
    #: entry): retried each pass — the front must stay live (writing
    #: rows, refreshing metrics, noticing SIGTERM) while work is held,
    #: so placement is never allowed to block the loop. The bool marks
    #: whether the arrival was already counted held (transition-only).
    backlog: List[Tuple[str, Dict, str, bool]] = []

    def finish(work_path: str) -> None:
        try:
            os.remove(work_path)
        except OSError:
            pass

    def fail_row(name: str, obj, exc: BaseException,
                 work_path: str) -> None:
        row = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
        # failure rows honor the nonce namespace too — a nonce-polling
        # client must see its failure, not wait forever on an
        # un-prefixed row
        nonce = obj.get("nonce") if isinstance(obj, dict) else None
        if isinstance(nonce, str) and nonce:
            row["nonce"] = nonce
        _write_row(out_dir, nonce_result_name(
            name, nonce if isinstance(nonce, str) and nonce else None),
            row)
        finish(work_path)

    fleet.start()
    try:
        last_metrics = 0.0
        while True:
            stopping = should_stop()
            if not stopping:
                for name, work_path in _claim(in_dir, work_dir):
                    obj = None
                    try:
                        # torn bytes dead-letter (never re-claimed);
                        # validation runs before routing so a bad
                        # request is reported in-band, not a front
                        # crash
                        obj = load_claimed(args.root, name, work_path)
                        request_from_json(obj)
                        backlog.append((name, obj, work_path, True))
                    except Exception as exc:  # noqa: BLE001 — in-band
                        failures += 1
                        fail_row(name, obj, exc, work_path)
            # place what the budget vector has room for; the rest stays
            # backlogged (claimed work still drains during a stop)
            still: List[Tuple[str, Dict, str, bool]] = []
            for name, obj, work_path, first in backlog:
                try:
                    fname = fleet.submit(obj, block=False,
                                         count_held=first)
                except Exception as exc:  # noqa: BLE001 — in-band
                    failures += 1
                    fail_row(name, obj, exc, work_path)
                    continue
                if fname is None:
                    still.append((name, obj, work_path, False))
                else:
                    submitted[fname] = (name, obj.get("nonce"),
                                        work_path)
            backlog = still
            # non-blocking sweep: collect whatever is ready
            ready = fleet.ready()
            done = fleet.collect(ready, timeout=30.0) if ready else {}
            for fname, row in done.items():
                client_name, nonce, work_path = submitted.pop(
                    fname, (fname, None, ""))
                failures += 0 if row.get("ok") else 1
                _write_row(out_dir,
                           nonce_result_name(client_name, nonce), row)
                if work_path:
                    finish(work_path)
            now = time.perf_counter()
            if now - last_metrics >= args.metrics_interval:
                last_metrics = now
                try:
                    fleet.write_metrics()
                except OSError:
                    pass
            drained = not submitted and not backlog
            try:
                spooled = any(n.endswith(".json")
                              for n in os.listdir(in_dir))
            except OSError:
                spooled = False
            if stopping and drained:
                break
            if args.once and drained and not spooled:
                break
            time.sleep(_POLL_SECS)
    finally:
        fleet.stop()
    print(json.dumps({"fleet": "done", "failed": failures,
                      "router": fleet.router.snapshot()}),
          file=sys.stderr)
    return 1 if failures else 0


def _write_row(out_dir: str, name: str, row: Dict) -> None:
    publish_json(row, os.path.join(out_dir, name), indent=1)
