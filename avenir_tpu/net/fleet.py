"""A fleet of job-server processes behind one affinity router.

One resident JobServer amortizes scans/compiles across tenants but is
still one Python process on one core-set; the fleet layer is the
scale-out: N ``serve --spool`` subprocesses (same host here — the spool
transport is already host-agnostic, so a host list later is a mount
away), each with its own spool, byte budget and warm state, fed by an
:class:`~avenir_tpu.net.router.AffinityRouter` that keeps a corpus
hitting the process whose WarmStore already pins its encoded blocks and
checkpoints, against a per-host priced-bytes budget vector.

The front half runs in the CALLER's process:

- :class:`Fleet` — spawn/stop the server processes, ``submit`` request
  objects (priced by ``price_request_bytes``, placed by the router,
  written atomically into the placed host's spool ``in/``),
  ``collect`` result rows from the per-host ``out/`` dirs, and roll
  the per-host ``metrics.json`` snapshots into ONE fleet view through
  the additive ``LatencyHistogram.merge`` algebra
  (``obs.report.merge_snapshots``) with the router's placement stats
  attached.
- :func:`fleet_main` — ``python -m avenir_tpu fleet``: a fleet-level
  spool (requests into ``<root>/in/``, results out of ``<root>/out/``)
  so tenants address ONE directory and the router fans out behind it.
  SIGTERM/SIGINT drain gracefully: stop claiming, finish in-flight,
  final merged metrics.json, exit 0.

Placement cost: when a profile store (``avenir_tpu.tune``) is
configured, the router's tie-break consults the measured per-chunk fold
cost of each (job, corpus) — a corpus whose folds are measured
expensive counts for more pending load than its bytes alone say.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from avenir_tpu.net.router import AffinityRouter, Placement
from avenir_tpu.server.spool import (nonce_result_name,
                                     request_from_json, spool_dirs)

#: fleet front poll granularity (seconds)
_POLL_SECS = 0.1
#: price-memo freshness: long enough to amortize an arrival burst over
#: a hot corpus, short enough that a growing refresh corpus re-prices
_PRICE_MEMO_TTL_SECS = 30.0
#: price-memo size bound for resident fronts
_PRICE_MEMO_MAX = 4096


def _pkg_parent() -> str:
    import avenir_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(avenir_tpu.__file__)))


def affinity_key(request) -> Tuple:
    """The router's sticky key: the corpus identity (mode + absolute
    input paths) — the component of ``server.compat_key`` warm state
    actually keys on. Everything else (job, conf) may vary per request
    without moving the corpus off its warm host."""
    return (request.mode,
            tuple(os.path.abspath(p) for p in request.inputs))


class FleetError(RuntimeError):
    """A fleet host died or refused to start."""


class _Outstanding:
    """One submitted request the front is waiting on."""

    __slots__ = ("placement", "out_path", "work_name")

    def __init__(self, placement: Placement, out_path: str,
                 work_name: str):
        self.placement = placement
        self.out_path = out_path
        self.work_name = work_name


class Fleet:
    """N job-server processes + the affinity front (module docstring).

    Construct, ``start()``, ``submit()`` request objects (the spool
    JSON schema), ``collect()`` rows, ``stop()``. The budget vector is
    one ``budget_mb`` entry per host; ``profile_dir`` opts placement
    into fold-cost weighting and is forwarded to every host as its
    autotune store."""

    def __init__(self, root: str, hosts: int = 2,
                 budget_mb: float = 3072.0, workers: int = 1,
                 warm_budget_mb: float = 256.0,
                 metrics_interval_s: float = 0.5,
                 profile_dir: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 pin_cores: Optional[Sequence[int]] = None):
        """``pin_cores``: pin host i to CPU ``pin_cores[i % len]``
        (Linux ``sched_setaffinity``; ignored where unsupported). On a
        shared box an UNPINNED single process borrows every core
        through XLA's intra-op threads, so a same-box fleet-vs-one
        comparison measures nothing — pinning one core per host is
        what makes a single machine a faithful proxy for N hosts
        (``bench_scaling.fleet_tripwire`` relies on it)."""
        if hosts < 1:
            raise ValueError("fleet needs at least one host")
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.host_dirs = [os.path.join(self.root, f"host{i}")
                          for i in range(hosts)]
        self.budget_bytes = int(budget_mb * (1 << 20))
        self.router = AffinityRouter([self.budget_bytes] * hosts)
        self.workers = int(workers)
        self.warm_budget_mb = float(warm_budget_mb)
        self.metrics_interval_s = float(metrics_interval_s)
        self.profile_dir = profile_dir
        self._env = env
        self.pin_cores = list(pin_cores) if pin_cores else None
        self._procs: List[subprocess.Popen] = []
        self._logs: List[str] = []
        self._lock = threading.Lock()
        self._seq = 0
        self._outstanding: Dict[str, _Outstanding] = {}
        #: finished rows swept off disk but not yet collect()ed — the
        #: submit loop's capacity sweep must never lose a row a later
        #: named collect() will ask for
        self._collected: Dict[str, Dict] = {}
        # pricing memo: corpus_stats head-samples the corpus per call,
        # so an open-loop front pricing hundreds of arrivals over a few
        # hot corpora would pay the sample per request; identical
        # (job, conf, corpus, mode) submissions price once, and the
        # profile-store fold cost rides along. Entries expire (a
        # refresh corpus GROWS between rounds — a price from its
        # smallest snapshot must not undercount the vector forever)
        # and the dict is bounded for resident fronts. Value:
        # (priced_bytes, cost_ms, stamped_at).
        self._price_memo: Dict[Tuple, Tuple] = {}

    # ------------------------------------------------------------ lifecycle
    def start(self, timeout: float = 60.0) -> "Fleet":
        env = dict(os.environ if self._env is None else self._env)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (_pkg_parent(), env.get("PYTHONPATH")) if p)
        for i, host_dir in enumerate(self.host_dirs):
            os.makedirs(host_dir, exist_ok=True)
            log_path = os.path.join(host_dir, "server.log")
            cmd = [sys.executable, "-m", "avenir_tpu", "serve",
                   "--spool", host_dir,
                   "--workers", str(self.workers),
                   "--budget-mb", str(self.budget_bytes / (1 << 20)),
                   "--warm-budget-mb", str(self.warm_budget_mb),
                   "--state-root", os.path.join(host_dir, "state"),
                   "--metrics-interval", str(self.metrics_interval_s)]
            if self.profile_dir:
                # hosts share ONE profile store: a fold cost measured on
                # any host informs placement for all of them
                cmd += ["--autotune-dir", self.profile_dir]
            preexec = None
            if self.pin_cores and hasattr(os, "sched_setaffinity"):
                core = self.pin_cores[i % len(self.pin_cores)]
                preexec = (lambda c=core:
                           os.sched_setaffinity(0, {c}))
            with open(log_path, "ab") as log:
                proc = subprocess.Popen(cmd, stdout=log, stderr=log,
                                        env=env, cwd=_pkg_parent(),
                                        preexec_fn=preexec)
            self._procs.append(proc)
            self._logs.append(log_path)
        deadline = time.perf_counter() + timeout
        for i, host_dir in enumerate(self.host_dirs):
            in_dir = os.path.join(host_dir, "in")
            while not os.path.isdir(in_dir):
                self._check_alive()
                if time.perf_counter() > deadline:
                    raise FleetError(
                        f"host {i} did not open its spool within "
                        f"{timeout}s (log: {self._logs[i]})")
                time.sleep(_POLL_SECS)
        return self

    def _check_alive(self) -> None:
        for i, proc in enumerate(self._procs):
            rc = proc.poll()
            if rc is not None and rc != 0:
                tail = _tail(self._logs[i])
                raise FleetError(
                    f"fleet host {i} exited rc={rc}; log tail:\n{tail}")

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ submitting
    def price(self, obj: Dict) -> Tuple[object, int, Optional[float]]:
        """(request, priced bytes, fold cost ms) of one request object
        — the placement inputs. Pricing uses the same oracle the hosts
        admit with; fold cost comes from the shared profile store when
        one is configured."""
        req = request_from_json(obj)
        memo_key = (req.job, req.mode,
                    tuple(os.path.abspath(p) for p in req.inputs),
                    json.dumps(req.conf, sort_keys=True)
                    if isinstance(req.conf, dict) else str(req.conf))
        now = time.perf_counter()
        with self._lock:
            hit = self._price_memo.get(memo_key)
            if hit is not None and now - hit[2] < _PRICE_MEMO_TTL_SECS:
                return req, hit[0], hit[1]
        priced = self._pricer()(req)
        cost = None
        if self.profile_dir:
            # the fold cost rides the same memo: re-reading the profile
            # store's JSON per arrival would pay a disk read per
            # request on exactly the hot-corpus path the memo exists
            # for
            from avenir_tpu import tune

            cost = tune.placement_cost_ms(self.profile_dir, req.job,
                                          req.conf, req.inputs)
        with self._lock:
            if len(self._price_memo) >= _PRICE_MEMO_MAX:
                self._price_memo = {
                    k: v for k, v in self._price_memo.items()
                    if now - v[2] < _PRICE_MEMO_TTL_SECS}
                if len(self._price_memo) >= _PRICE_MEMO_MAX:
                    self._price_memo.clear()
            self._price_memo[memo_key] = (priced, cost, now)
        return req, priced, cost

    def _pricer(self):
        """The front's pricing oracle — the SAME one the hosts admit
        with: the residual-corrected tuned pricer when a profile store
        is configured (the hosts get it via --autotune-dir), the bare
        footprint model otherwise. A front that raw-priced what a host
        tuned-prices would place work the host then fast-fails."""
        fn = getattr(self, "_pricer_fn", None)
        if fn is not None:
            return fn
        from avenir_tpu.server.jobserver import (DEFAULT_RESERVE_BYTES,
                                                 price_request_bytes)

        if self.profile_dir:
            from avenir_tpu import tune

            base = tune.make_tuned_pricer(self.profile_dir,
                                          base=price_request_bytes)
        else:
            base = price_request_bytes
        self._pricer_fn = fn = \
            lambda req: int(base([req], DEFAULT_RESERVE_BYTES))
        return fn

    def submit(self, obj: Dict, block: bool = True,
               timeout: float = 600.0,
               count_held: bool = True) -> Optional[str]:
        """Route one request object to a host spool; returns the fleet
        request name to ``collect`` on, or None when every host is over
        its budget-vector entry and ``block`` is False. Blocking waits
        for a host to free capacity — the fleet-front analog of the
        single server's admission hold. ``count_held=False`` marks a
        caller-level retry of an arrival already counted held."""
        req, priced, cost = self.price(obj)
        key = affinity_key(req)
        deadline = time.perf_counter() + timeout
        while True:
            placement = self.router.place(key, priced, cost,
                                          count_held=count_held)
            if placement is not None:
                break
            count_held = False        # this arrival is counted now
            # capacity frees only when finished requests are swept off
            # disk — a blocking submit must sweep ITSELF or a saturated
            # single-threaded front would spin the full timeout while
            # every host sits idle with its results already written
            self._sweep()
            if not block:
                return None
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"no host freed budget for a {priced}-byte request "
                    f"within {timeout}s")
            self._check_alive()
            time.sleep(_POLL_SECS)
        return self._spool_to(placement, obj)

    def submit_to(self, host: int, obj: Dict) -> str:
        """Pin one request to `host`, bypassing the router (warmup
        traffic that must touch a SPECIFIC process). Accounted against
        the budget vector like any placement."""
        req, priced, cost = self.price(obj)
        placement = self.router.assign_to(host, affinity_key(req),
                                          priced, cost)
        return self._spool_to(placement, obj)

    def _spool_to(self, placement: Placement, obj: Dict) -> str:
        with self._lock:
            self._seq += 1
            name = f"r{self._seq:06d}.json"
        host_dir = self.host_dirs[placement.host]
        out_name = nonce_result_name(name, obj.get("nonce"))
        out_path = os.path.join(host_dir, "out", out_name)
        tmp = os.path.join(host_dir, f".{name}.tmp")
        with open(tmp, "w") as fh:
            json.dump(obj, fh)
        os.replace(tmp, os.path.join(host_dir, "in", name))
        with self._lock:
            self._outstanding[name] = _Outstanding(placement, out_path,
                                                   out_name)
        return name

    # ------------------------------------------------------------ collecting
    def ready(self) -> List[str]:
        """Names of submitted requests whose result row is available
        (already swept, or on disk) — what a non-blocking front sweep
        collects."""
        with self._lock:
            entries = list(self._outstanding.items())
            banked = list(self._collected)
        return banked + [n for n, e in entries
                         if os.path.exists(e.out_path)]

    def _sweep(self) -> int:
        """Move every finished request's row off disk into the
        collected bank and release its router accounting. Returns how
        many were swept. Idempotent and safe to call from the submit
        loop — a banked row waits for its named ``collect``."""
        with self._lock:
            entries = list(self._outstanding.items())
        swept = 0
        for name, entry in entries:
            if not os.path.exists(entry.out_path):
                continue
            with open(entry.out_path) as fh:
                row = json.load(fh)
            with self._lock:
                if self._outstanding.pop(name, None) is None:
                    continue              # raced another sweeper
                self._collected[name] = row
            self.router.release(entry.placement)
            swept += 1
        return swept

    def collect(self, names: Optional[Sequence[str]] = None,
                timeout: float = 600.0) -> Dict[str, Dict]:
        """Block until every named request (default: all submitted,
        uncollected) has a result row; returns {name: row}. Router
        accounting is released as each row is swept off disk."""
        with self._lock:
            wanted = list(names) if names is not None else \
                list(self._outstanding) + list(self._collected)
            unknown = [n for n in wanted
                       if n not in self._outstanding
                       and n not in self._collected]
        if unknown:
            raise KeyError(f"unknown fleet request(s) {unknown}")
        rows: Dict[str, Dict] = {}
        deadline = time.perf_counter() + timeout
        while True:
            self._sweep()
            with self._lock:
                for name in wanted:
                    if name not in rows and name in self._collected:
                        rows[name] = self._collected.pop(name)
            if len(rows) == len(wanted):
                return rows
            self._check_alive()
            if time.perf_counter() > deadline:
                missing = [n for n in wanted if n not in rows]
                raise TimeoutError(
                    f"fleet results {missing} not served within "
                    f"{timeout}s")
            time.sleep(_POLL_SECS)

    # --------------------------------------------------------------- metrics
    def merged_metrics(self) -> Dict:
        """The fleet snapshot: per-host metrics.json files folded into
        one through the additive histogram merge, with the router's
        placement stats and budget-vector occupancy attached
        (docs/observability.md "Fleet roll-up")."""
        from avenir_tpu.obs.report import merge_snapshots

        snaps = []
        for host_dir in self.host_dirs:
            path = os.path.join(host_dir, "metrics.json")
            try:
                with open(path) as fh:
                    snaps.append(json.load(fh))
            except (OSError, ValueError):
                continue            # host not up yet / mid-rename
        merged = merge_snapshots(snaps)
        merged["router"] = self.router.snapshot()
        return merged

    def write_metrics(self, path: Optional[str] = None) -> str:
        path = path or os.path.join(self.root, "metrics.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(self.merged_metrics(), fh)
        os.replace(tmp, path)
        return path

    # ------------------------------------------------------------- stopping
    def stop(self, timeout: float = 120.0) -> List[int]:
        """Graceful fleet shutdown: SIGTERM every host (their handlers
        drain: finish claimed work, final per-host metrics.json, exit
        0), join, write the final merged metrics. Returns the per-host
        exit codes; a host that needed SIGKILL reports rc < 0."""
        for proc in self._procs:
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        codes: List[int] = []
        deadline = time.perf_counter() + timeout
        for proc in self._procs:
            remaining = max(deadline - time.perf_counter(), 0.1)
            try:
                codes.append(proc.wait(timeout=remaining))
            except subprocess.TimeoutExpired:
                proc.kill()
                codes.append(proc.wait())
        self._procs = []
        try:
            self.write_metrics()
        except OSError:
            pass
        return codes


def _tail(path: str, nbytes: int = 800) -> str:
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            fh.seek(max(fh.tell() - nbytes, 0))
            return fh.read().decode(errors="replace")
    except OSError:
        return "<no log>"


# --------------------------------------------------------------------------
# the fleet CLI
# --------------------------------------------------------------------------
def fleet_main(argv) -> int:
    """``python -m avenir_tpu fleet --root DIR --hosts N [...]`` — the
    fleet-level spool session (module docstring)."""
    import argparse

    from avenir_tpu.server.spool import _claim, install_drain_handlers

    ap = argparse.ArgumentParser(prog="avenir_tpu fleet")
    ap.add_argument("--root", required=True,
                    help="fleet root: requests in <root>/in, results in "
                         "<root>/out, hosts under <root>/host<i>")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--workers", type=int, default=1,
                    help="worker threads per host process (default 1)")
    ap.add_argument("--budget-mb", type=float, default=3072.0,
                    help="per-host admission budget — one entry of the "
                         "fleet's budget vector (default 3072)")
    ap.add_argument("--once", action="store_true",
                    help="serve what is spooled, drain, exit")
    ap.add_argument("--profile-dir", default=None,
                    help="autotune profile store consulted for "
                         "fold-cost-weighted placement")
    ap.add_argument("--metrics-interval", type=float, default=1.0)
    args = ap.parse_args(argv)

    in_dir, work_dir, out_dir = spool_dirs(args.root)
    fleet = Fleet(args.root, hosts=args.hosts, budget_mb=args.budget_mb,
                  workers=args.workers, profile_dir=args.profile_dir,
                  metrics_interval_s=min(args.metrics_interval, 1.0))
    stop_event = threading.Event()
    should_stop = install_drain_handlers(stop_event)
    failures = 0
    #: fleet request name -> (client name, nonce, work path): the work
    #: file survives until the final out/ row lands (serve_spool's own
    #: discipline), so a front crash never silently loses an accepted
    #: request — the file is still in work/ for recovery
    submitted: Dict[str, Tuple[str, Optional[str], str]] = {}
    #: claimed but not yet placeable (every host over its vector
    #: entry): retried each pass — the front must stay live (writing
    #: rows, refreshing metrics, noticing SIGTERM) while work is held,
    #: so placement is never allowed to block the loop. The bool marks
    #: whether the arrival was already counted held (transition-only).
    backlog: List[Tuple[str, Dict, str, bool]] = []

    def finish(work_path: str) -> None:
        try:
            os.remove(work_path)
        except OSError:
            pass

    def fail_row(name: str, obj, exc: BaseException,
                 work_path: str) -> None:
        row = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
        # failure rows honor the nonce namespace too — a nonce-polling
        # client must see its failure, not wait forever on an
        # un-prefixed row
        nonce = obj.get("nonce") if isinstance(obj, dict) else None
        if isinstance(nonce, str) and nonce:
            row["nonce"] = nonce
        _write_row(out_dir, nonce_result_name(
            name, nonce if isinstance(nonce, str) and nonce else None),
            row)
        finish(work_path)

    fleet.start()
    try:
        last_metrics = 0.0
        while True:
            stopping = should_stop()
            if not stopping:
                for name, work_path in _claim(in_dir, work_dir):
                    obj = None
                    try:
                        with open(work_path) as fh:
                            obj = json.load(fh)
                        # validate before routing so a bad request is
                        # reported in-band, not a front crash
                        request_from_json(obj)
                        backlog.append((name, obj, work_path, True))
                    except Exception as exc:  # noqa: BLE001 — in-band
                        failures += 1
                        fail_row(name, obj, exc, work_path)
            # place what the budget vector has room for; the rest stays
            # backlogged (claimed work still drains during a stop)
            still: List[Tuple[str, Dict, str, bool]] = []
            for name, obj, work_path, first in backlog:
                try:
                    fname = fleet.submit(obj, block=False,
                                         count_held=first)
                except Exception as exc:  # noqa: BLE001 — in-band
                    failures += 1
                    fail_row(name, obj, exc, work_path)
                    continue
                if fname is None:
                    still.append((name, obj, work_path, False))
                else:
                    submitted[fname] = (name, obj.get("nonce"),
                                        work_path)
            backlog = still
            # non-blocking sweep: collect whatever is ready
            ready = fleet.ready()
            done = fleet.collect(ready, timeout=30.0) if ready else {}
            for fname, row in done.items():
                client_name, nonce, work_path = submitted.pop(
                    fname, (fname, None, ""))
                failures += 0 if row.get("ok") else 1
                _write_row(out_dir,
                           nonce_result_name(client_name, nonce), row)
                if work_path:
                    finish(work_path)
            now = time.perf_counter()
            if now - last_metrics >= args.metrics_interval:
                last_metrics = now
                try:
                    fleet.write_metrics()
                except OSError:
                    pass
            drained = not submitted and not backlog
            try:
                spooled = any(n.endswith(".json")
                              for n in os.listdir(in_dir))
            except OSError:
                spooled = False
            if stopping and drained:
                break
            if args.once and drained and not spooled:
                break
            time.sleep(_POLL_SECS)
    finally:
        fleet.stop()
    print(json.dumps({"fleet": "done", "failed": failures,
                      "router": fleet.router.snapshot()}),
          file=sys.stderr)
    return 1 if failures else 0


def _write_row(out_dir: str, name: str, row: Dict) -> None:
    tmp = os.path.join(out_dir, name + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(row, fh, indent=1)
    os.replace(tmp, os.path.join(out_dir, name))
