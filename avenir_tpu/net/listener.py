"""JSON-over-HTTP/1.1 edge for the resident job server. Stdlib only.

The spool transports (stdin JSON-lines, maildir directory) are hermetic
but single-host-single-client; this listener is the network front the
ROADMAP's fleet item asks for, deliberately thin: every request body is
the SAME JSON object the spool speaks (``spool.request_from_json``),
every response row the same shape ``spool.result_to_json`` writes, so a
tenant can move between ``--stdin``, ``--spool`` and ``--listen``
without changing a byte of its request.

Surface:

- ``POST /submit`` — submit one request. ``?wait=1`` blocks for the
  result row (200); otherwise 202 with the ``req_id`` to poll.
- ``GET /result/<req_id>`` — 200 with the result row once served
  (fetching releases it), 202 while pending, 404 for unknown ids.
  ``?timeout=S`` long-polls.
- ``GET /metrics`` — the live ``metrics.json`` snapshot
  (``JobServer.metrics_snapshot``) plus an ``edge`` section.
- ``GET /healthz`` — 200 ``{"status": "serving"}`` /
  503 ``{"status": "draining"}``: the drain state a fleet router or
  load balancer health-checks. Supervision can overlay
  ``"quarantined"`` / ``"restarting"`` via :meth:`set_health_state`
  (503 as well) so operators and a fleet front probing the edge see
  the same state the supervisor acted on.

**Backpressure is wired to the admission model, at the edge.** The
single server already refuses to RUN over budget (the priced-bytes
admission gate), but an unbounded accept loop could still queue
requests toward OOM. The edge closes that hole: each request is priced
by the server's own pricer (``JobServer.price`` — the same oracle the
scheduler admits with) and accepted only while the edge's outstanding
priced total stays inside the budget and the tenant's queue inside its
depth bound. Over either limit the edge answers ``429`` with a
``Retry-After`` header (``shed_mode="reject"``, the default) or parks
the accept in the handler thread until capacity frees
(``shed_mode="hold"``). So the server's priced peak can never exceed
its budget AND the queue in front of it is bounded — the two halves of
the OOM-free claim ``tests/test_net.py`` pins.

Thread shape (the graftlint --flow contract): one accept loop
(``ThreadingHTTPServer.serve_forever`` — per-connection handler
threads are the stdlib's, daemonic and bounded by the request), plus
one reaper thread releasing finished requests' edge accounting; both
bound in ``_threads`` and joined by ``stop()``.
"""

from __future__ import annotations

import json
import math
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from avenir_tpu.models.artifact import ModelFormatSkew
from avenir_tpu.server.jobserver import JobServer, ServerClosed, Ticket
from avenir_tpu.server.score import (ScoreError, ScoreTimeout,
                                     score_request_from_json)
from avenir_tpu.server.spool import request_from_json, result_to_json

#: default blocking wait for one /score (override with ?timeout=; a
#: coalesced score answers in ms — this bound only catches wedges)
_SCORE_WAIT_S = 30.0

#: reaper poll bound — how long a finished request's priced bytes can
#: linger before the edge releases them
_REAP_SECS = 0.05


@dataclass
class EdgePolicy:
    """The edge's backpressure knobs.

    ``shed_mode``: "reject" answers 429-with-Retry-After the moment a
    request would breach a bound; "hold" parks the accept until
    capacity frees (bounded by ``hold_timeout_s``, then 429 anyway —
    an edge must never hold forever). ``budget_bytes``: the edge's
    outstanding-priced ceiling, defaulting to the server's own
    admission budget. ``max_tenant_depth``: per-tenant queued-request
    bound. ``retry_after_s``: the 429 Retry-After hint."""

    shed_mode: str = "reject"
    budget_bytes: Optional[int] = None
    max_tenant_depth: int = 64
    #: the 429 Retry-After hint, jittered ±`retry_jitter` per response
    #: so a synchronized cohort of shed clients does not retry in
    #: lockstep and re-stampede the edge at one instant
    retry_after_s: float = 1.0
    retry_jitter: float = 0.2
    hold_timeout_s: float = 30.0
    wait_timeout_s: float = 600.0
    #: a served-but-never-fetched result is dropped after this long —
    #: a fire-and-forget client must not grow a resident edge forever
    result_ttl_s: float = 600.0

    def __post_init__(self):
        if self.shed_mode not in ("reject", "hold"):
            raise ValueError(
                f"unknown shed_mode {self.shed_mode!r} "
                f"(expected 'reject' or 'hold')")


class _EdgeEntry:
    """One accepted request's edge bookkeeping."""

    __slots__ = ("ticket", "priced", "released", "released_at")

    def __init__(self, ticket: Ticket, priced: int):
        self.ticket = ticket
        self.priced = priced
        self.released = False
        self.released_at = 0.0


class _Httpd(ThreadingHTTPServer):
    # handler threads die with their connection; the accept loop itself
    # is joined by NetListener.stop()
    daemon_threads = True
    listener: "NetListener"


class NetListener:
    """The HTTP edge over one :class:`JobServer` (module docstring).

    Construct with ``port=0`` for an ephemeral port (tests and
    single-host fleets MUST — fixed ports are how network tests flake),
    ``start()``, read ``port``, ``stop()`` when done. The listener owns
    only its accept/reaper threads; the JobServer's lifecycle stays the
    caller's."""

    def __init__(self, server: JobServer, host: str = "127.0.0.1",
                 port: int = 0, policy: Optional[EdgePolicy] = None):
        import dataclasses

        self.server = server
        # a COPY: resolving the default budget must not write through
        # to a caller's policy object shared with another listener
        self.policy = dataclasses.replace(policy) if policy \
            else EdgePolicy()
        if self.policy.budget_bytes is None:
            self.policy.budget_bytes = server.budget_bytes
        self._httpd = _Httpd((host, port), _Handler)
        self._httpd.listener = self
        self._lock = threading.Lock()
        self._capacity = threading.Condition(self._lock)
        self._outstanding: Dict[str, _EdgeEntry] = {}
        self._outstanding_priced = 0
        self._draining = False
        self._health_state: Optional[str] = None
        self._stop = threading.Event()
        self._threads: list = []
        self._stats: Dict[str, int] = {
            "accepted": 0, "rejected": 0, "held_accepts": 0,
            "completed": 0,
        }

    # ------------------------------------------------------------ lifecycle
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "NetListener":
        # daemon + joined-by-stop(): the join in stop() is the real
        # lifecycle; daemon means a listener abandoned by a crashed
        # caller can never wedge interpreter exit
        t = threading.Thread(target=self._httpd.serve_forever,
                             kwargs={"poll_interval": 0.1},
                             name="avenir-net-accept", daemon=True)
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._reaper_loop,
                             name="avenir-net-reaper", daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def begin_drain(self) -> None:
        """Flip /healthz to draining and refuse new submissions (503);
        in-flight requests keep serving and stay fetchable."""
        with self._lock:
            self._draining = True
            self._capacity.notify_all()
        self.server.begin_drain()

    def set_health_state(self, state: Optional[str]) -> None:
        """Overlay a supervision state on ``/healthz`` —
        ``"quarantined"`` / ``"restarting"`` (503, new submissions
        refused with the state in-band) or None to return to normal
        serving. This is how a supervisor makes its verdict visible to
        the operators and fleet fronts health-checking the edge."""
        if state is not None and state not in ("quarantined",
                                               "restarting"):
            raise ValueError(
                f"unknown health state {state!r} (expected "
                f"'quarantined', 'restarting' or None)")
        with self._lock:
            self._health_state = state
            self._capacity.notify_all()

    def health_state(self) -> str:
        """The /healthz status string: draining wins (an operator
        decision), then the supervision overlay, then serving."""
        with self._lock:
            if self._draining:
                return "draining"
            return self._health_state or "serving"

    def retry_after_s(self) -> float:
        """One 429's Retry-After hint: the policy value jittered
        ±``retry_jitter`` so shed clients spread their retries instead
        of re-stampeding in lockstep."""
        import random

        jitter = max(min(self.policy.retry_jitter, 1.0), 0.0)
        return self.policy.retry_after_s * random.uniform(1.0 - jitter,
                                                          1.0 + jitter)

    def stop(self) -> None:
        """Stop accepting and join the accept/reaper threads. Does NOT
        shut the JobServer down — callers drain/stop it themselves."""
        self._stop.set()
        self._httpd.shutdown()
        threads, self._threads = self._threads, []
        for t in threads:
            t.join(10.0)
        self._httpd.server_close()

    def __enter__(self) -> "NetListener":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------ edge accounting
    def _reaper_loop(self) -> None:
        while not self._stop.is_set():
            now = time.perf_counter()
            with self._capacity:
                freed = 0
                expired = []
                for entry_id, entry in self._outstanding.items():
                    if not entry.released and entry.ticket.done:
                        entry.released = True
                        entry.released_at = now
                        freed += entry.priced
                        self._stats["completed"] += 1
                    elif entry.released and now - entry.released_at \
                            > self.policy.result_ttl_s:
                        # fetched results pop in take_result; a client
                        # that never polls must not pin its JobResult
                        # (and the reaper's sweep cost) forever
                        expired.append(entry_id)
                for entry_id in expired:
                    self._outstanding.pop(entry_id, None)
                if freed:
                    self._outstanding_priced -= freed
                    self._capacity.notify_all()
                self._capacity.wait(_REAP_SECS)

    def try_accept(self, tenant: str, priced: int) -> Tuple[bool, str]:
        """Reserve edge capacity for one priced request: (accepted,
        reason). Honors the policy's shed mode — "hold" parks here
        until capacity frees or the hold bound passes."""
        deadline = time.perf_counter() + self.policy.hold_timeout_s
        held = False
        with self._capacity:
            while True:
                if self._draining:
                    return False, "draining"
                if self._health_state is not None:
                    # supervision overlay: a quarantined/restarting
                    # edge refuses new work in-band, like draining
                    return False, self._health_state
                reason = self._over_limit_locked(tenant, priced)
                if reason is None:
                    self._outstanding_priced += priced
                    self._stats["accepted"] += 1
                    if held:
                        self._stats["held_accepts"] += 1
                    return True, "accepted"
                if self.policy.shed_mode != "hold":
                    self._stats["rejected"] += 1
                    return False, reason
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    self._stats["rejected"] += 1
                    return False, reason
                held = True
                self._capacity.wait(min(remaining, _REAP_SECS * 4))

    def _over_limit_locked(self, tenant: str, priced: int
                           ) -> Optional[str]:
        if self._outstanding_priced + priced > self.policy.budget_bytes:
            return ("priced in-flight bytes "
                    f"{self._outstanding_priced + priced} would exceed "
                    f"the {self.policy.budget_bytes}-byte budget")
        if self.server.queue_depth(tenant) >= self.policy.max_tenant_depth:
            return (f"tenant {tenant!r} queue depth at the "
                    f"{self.policy.max_tenant_depth} bound")
        return None

    def register(self, entry_id: str, ticket: Ticket, priced: int) -> None:
        with self._capacity:
            old = self._outstanding.get(entry_id)
            if old is not None and not old.released:
                # a client reused a req_id while the first submission
                # was still in flight: last-submit-wins for the fetch,
                # but the replaced entry's priced bytes must be freed
                # or the edge budget leaks shut permanently
                old.released = True
                self._outstanding_priced -= old.priced
                self._capacity.notify_all()
            self._outstanding[entry_id] = _EdgeEntry(ticket, priced)

    def release_unsubmitted(self, priced: int) -> None:
        """Undo a try_accept reservation whose submit failed."""
        with self._capacity:
            self._outstanding_priced -= priced
            self._capacity.notify_all()

    def take_result(self, entry_id: str, timeout: float = 0.0
                    ) -> Tuple[Optional[Dict], bool]:
        """(result row or None, known): the row once the ticket is done
        — fetching pops the entry — else (None, True) while pending."""
        with self._lock:
            entry = self._outstanding.get(entry_id)
        if entry is None:
            return None, False
        if not entry.ticket._done.wait(timeout):
            return None, True
        with self._capacity:
            entry = self._outstanding.pop(entry_id, None)
            if entry is None:                  # raced another fetcher
                return None, False
            if not entry.released:
                entry.released = True
                self._outstanding_priced -= entry.priced
                self._stats["completed"] += 1
                self._capacity.notify_all()
        return result_to_json(entry.ticket), True

    def edge_stats(self) -> Dict:
        with self._lock:
            return {
                **{k: int(v) for k, v in self._stats.items()},
                "outstanding_requests": len(self._outstanding),
                "outstanding_priced_bytes": int(self._outstanding_priced),
                "budget_bytes": int(self.policy.budget_bytes),
                "max_tenant_depth": int(self.policy.max_tenant_depth),
                "shed_mode": self.policy.shed_mode,
                "draining": self._draining,
                "health_state": (self._health_state
                                 if not self._draining else "draining")
                or "serving",
            }

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining


class _Handler(BaseHTTPRequestHandler):
    server_version = "avenir-net/1"
    protocol_version = "HTTP/1.1"

    # ------------------------------------------------------------- plumbing
    def log_message(self, fmt, *args):      # noqa: D102 — stdlib hook
        pass                                # the metrics surface IS the log

    def _reply(self, code: int, obj: Dict,
               headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _query(self) -> Dict[str, str]:
        q = parse_qs(urlsplit(self.path).query)
        return {k: v[-1] for k, v in q.items()}

    def _query_timeout(self, q: Dict[str, str],
                       default: float) -> Optional[float]:
        """The ?timeout= parameter as a float, or None AFTER answering
        400 — client input must never crash the handler thread. Capped
        at the policy's wait bound: a client-chosen timeout must not
        pin a handler thread and its socket past what the edge would
        grant its own blocking waits."""
        listener: NetListener = self.server.listener
        try:
            timeout = max(float(q.get("timeout", default)), 0.0)
        except (TypeError, ValueError):
            self._reply(400, {"ok": False,
                              "error": f"invalid timeout "
                                       f"{q.get('timeout')!r}"})
            return None
        return min(timeout, listener.policy.wait_timeout_s)

    def _handle_score(self) -> None:
        """``POST /score`` — the query path. Persistent HTTP/1.1
        connections matter here the way they never did for /submit:
        a coalesced score answers in single-digit ms, so per-request
        TCP setup would dominate; ``_reply`` always sends
        Content-Length, which is what keeps the socket reusable.
        Scores bypass the priced-bytes edge (a row costs no scan) but
        respect the drain gate like every submission."""
        listener: NetListener = self.server.listener
        try:
            length = int(self.headers.get("Content-Length") or 0)
            req = score_request_from_json(
                json.loads(self.rfile.read(length)))
        except (ValueError, KeyError, TypeError) as exc:
            self._reply(400, {"ok": False,
                              "error": f"{type(exc).__name__}: {exc}"})
            return
        if listener.draining or listener.server.draining:
            self._reply(503, {"ok": False, "status": "draining"})
            return
        timeout = self._query_timeout(self._query(), _SCORE_WAIT_S)
        if timeout is None:
            return
        plane = listener.server.score_plane()
        try:
            if req.action == "reward":
                ack = plane.reward(req)
                self._reply(200, {"ok": True, "req_id": req.req_id,
                                  **ack})
                return
            result = plane.score(req, timeout=timeout)
        except ModelFormatSkew as exc:
            # refuse-and-go-cold: a foreign/torn artifact stamp is the
            # operator's problem, never parsed blind
            self._reply(409, {"ok": False, "error": str(exc)})
            return
        except ScoreTimeout as exc:
            self._reply(504, {"ok": False, "error": str(exc)})
            return
        except (ScoreError, OSError, KeyError, ValueError) as exc:
            self._reply(400, {"ok": False,
                              "error": f"{type(exc).__name__}: {exc}"})
            return
        self._reply(200, {"ok": True, **result.to_json()})

    # --------------------------------------------------------------- routes
    def do_POST(self) -> None:              # noqa: N802 — stdlib name
        listener: NetListener = self.server.listener
        path = urlsplit(self.path).path
        if path == "/score":
            self._handle_score()
            return
        if path != "/submit":
            self._reply(404, {"error": f"no such route {path}"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            req = request_from_json(json.loads(self.rfile.read(length)))
            priced = listener.server.price([req])
        except (ValueError, KeyError, TypeError) as exc:
            self._reply(400, {"ok": False,
                              "error": f"{type(exc).__name__}: {exc}"})
            return
        accepted, reason = listener.try_accept(req.tenant, priced)
        if not accepted:
            if reason in ("draining", "quarantined", "restarting"):
                self._reply(503, {"ok": False, "status": reason})
                return
            retry_s = listener.retry_after_s()
            self._reply(429, {"ok": False, "error": reason,
                              "retry_after_s": round(retry_s, 3)},
                        headers={"Retry-After":
                                 str(max(int(math.ceil(retry_s)), 1))})
            return
        try:
            ticket = listener.server.submit(req)
        except (ServerClosed, KeyError, ValueError) as exc:
            listener.release_unsubmitted(priced)
            code = 503 if isinstance(exc, ServerClosed) else 400
            self._reply(code, {"ok": False,
                               "error": f"{type(exc).__name__}: {exc}"})
            return
        listener.register(req.req_id, ticket, priced)
        q = self._query()
        if q.get("wait") in ("1", "true"):
            timeout = self._query_timeout(
                q, listener.policy.wait_timeout_s)
            if timeout is None:
                return                   # 400 sent; the job still runs
            row, _known = listener.take_result(req.req_id, timeout)
            if row is None:
                self._reply(202, {"req_id": req.req_id,
                                  "status": "pending"})
                return
            self._reply(200 if row["ok"] else 500, row)
            return
        self._reply(202, {"req_id": req.req_id, "status": "queued",
                          "priced_bytes": priced})

    def do_GET(self) -> None:               # noqa: N802 — stdlib name
        listener: NetListener = self.server.listener
        path = urlsplit(self.path).path
        if path == "/healthz":
            status = listener.health_state()
            self._reply(200 if status == "serving" else 503,
                        {"status": status,
                         "queued": listener.server.queue_depth(),
                         "edge": listener.edge_stats()})
            return
        if path == "/metrics":
            snap = listener.server.metrics_snapshot()
            snap["edge"] = listener.edge_stats()
            self._reply(200, snap)
            return
        if path.startswith("/result/"):
            entry_id = path[len("/result/"):]
            timeout = self._query_timeout(self._query(), 0.0)
            if timeout is None:
                return                   # 400 sent
            row, known = listener.take_result(entry_id, timeout)
            if row is not None:
                self._reply(200 if row["ok"] else 500, row)
            elif known:
                self._reply(202, {"req_id": entry_id,
                                  "status": "pending"})
            else:
                self._reply(404, {"error": f"unknown req_id {entry_id}"})
            return
        self._reply(404, {"error": f"no such route {path}"})
