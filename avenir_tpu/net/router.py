"""Affinity placement across a fleet of job-server hosts.

The single-server admission controller prices every dispatch in bytes
against ONE budget; a fleet generalizes that scalar to a budget
*vector* — one priced-bytes ceiling per host — and adds a placement
question: which host should a request hit?

The answer that keeps the fleet fast is affinity: a host that already
served a corpus holds its WarmStore pins (encoded-block caches, managed
checkpoints) and its jit-compiled fold executables, so a repeat request
over that corpus is cheapest exactly there. The router keeps a sticky
``affinity key -> host`` map (the key is the corpus identity — the same
paths component ``server.compat_key`` batches on) and routes:

1. **Affinity hit** — the sticky host has budget headroom: place there.
2. **Spill** — the sticky host is over its vector entry: place on the
   least-loaded host with headroom (the coded-dispatch framing of
   arXiv:1802.03049 — redundancy beats waiting), WITHOUT moving the
   sticky mapping, so the corpus returns to its warm host when the
   pressure passes.
3. **Miss** — unseen key: least-loaded host with headroom becomes the
   sticky host.
4. **Held** — no host has headroom: ``place`` returns None and the
   caller holds (fleet front) or sheds (listener edge) the request;
   the budget vector is NEVER breached by placement.

Fault awareness (avenir-fault, :mod:`avenir_tpu.net.fault`): each host
carries a supervision state (``serving`` / ``restarting`` / ``stalled``
/ ``quarantined``); only ``serving`` hosts take new placements. A
sticky mapping whose host left ``serving`` is DROPPED on the next
placement for that corpus (counted as a ``failover``) and the corpus
re-places by the normal least-loaded rule — so when the host recovers
it re-earns affinity through fresh hits, never through a map reset.
``place_mirror`` is the hedged-dispatch placement: least-loaded serving
host outside an exclusion set, charged against the budget vector like
any placement but never touching the sticky map (the corpus still
belongs to its slow warm host; the mirror is insurance, not a move).

"Least loaded" orders hosts by priced-bytes utilisation
(``assigned/budget``), tie-broken by pending fold cost — the autotune
profile store's measured per-chunk fold means (``tune.placement_cost_ms``)
when the caller supplies them — then by host index, so placement is
deterministic for a given submission order.

Thread shape: one lock around all mutable state; ``place``/``release``
are safe from any thread (the fleet front and a listener edge may share
one router).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Hashable, List, Optional, Sequence


class RouterError(RuntimeError):
    """A request's priced bytes exceed every host's budget entry — it
    can never be placed, mirroring the single-server AdmissionError."""


@dataclass
class HostLoad:
    """One host's slice of the budget vector plus its live load."""

    budget_bytes: int
    assigned_bytes: int = 0
    assigned_requests: int = 0
    pending_cost_ms: float = 0.0
    peak_assigned_bytes: int = 0
    placed_total: int = 0
    #: supervision state (avenir_tpu.net.fault); only "serving" hosts
    #: take new placements
    state: str = "serving"

    @property
    def available(self) -> bool:
        return self.state == "serving"

    def utilisation(self) -> float:
        return self.assigned_bytes / self.budget_bytes \
            if self.budget_bytes > 0 else float(self.assigned_requests)

    def fits(self, priced: int) -> bool:
        return self.assigned_bytes + priced <= self.budget_bytes


@dataclass
class Placement:
    """``place``'s receipt: hand it back to ``release`` so the router
    never depends on the caller recomputing the priced bytes."""

    host: int
    priced_bytes: int
    cost_ms: float = 0.0
    kind: str = "miss"   # "hit" | "spill" | "miss" | "pinned" | "hedge"
    key: Hashable = field(default=None, repr=False)


class AffinityRouter:
    """Sticky corpus->host placement against a per-host budget vector
    (module docstring has the policy)."""

    def __init__(self, budgets: Sequence[int]):
        if not budgets:
            raise ValueError("router needs at least one host budget")
        self.hosts: List[HostLoad] = [HostLoad(int(b)) for b in budgets]
        self._affinity: Dict[Hashable, int] = {}
        self._lock = threading.Lock()
        self.stats: Dict[str, int] = {
            "placed": 0, "affinity_hits": 0, "affinity_misses": 0,
            "spills": 0, "held": 0, "failovers": 0, "hedges": 0,
        }

    # ------------------------------------------------------------ placing
    def place(self, key: Hashable, priced_bytes: int,
              cost_ms: Optional[float] = None,
              count_held: bool = True,
              exclude: Sequence[int] = ()) -> Optional[Placement]:
        """Place one request of `priced_bytes` with affinity `key`;
        None when every host is over its vector entry (caller holds or
        sheds). Raises :class:`RouterError` when the request exceeds
        every budget entry even on an idle fleet.

        ``count_held=False`` marks a RETRY of an arrival already
        counted held — pollers re-placing every 0.1s must not inflate
        the held stat 10x per second held (the same transition-not-
        re-check rule the server's admission_holds counter follows).

        ``exclude`` removes hosts from consideration for THIS placement
        (the requeue path excludes every host a request already failed
        on); an excluded sticky host keeps its mapping — exclusion is
        per-request, failover is per-host-state."""
        priced = max(int(priced_bytes), 0)
        cost = float(cost_ms) if cost_ms else 0.0
        banned = set(exclude)
        with self._lock:
            if not any(priced <= h.budget_bytes for h in self.hosts):
                raise RouterError(
                    f"request priced at {priced} bytes exceeds every "
                    f"host budget "
                    f"{[h.budget_bytes for h in self.hosts]}")
            sticky = self._affinity.get(key)
            if sticky is not None and not self.hosts[sticky].available:
                # the warm host is down/quarantined: drop the mapping —
                # the corpus re-places least-loaded and the recovered
                # host re-earns affinity through hits, never a map reset
                self._affinity.pop(key, None)
                self.stats["failovers"] += 1
                sticky = None
            if sticky is not None and sticky not in banned \
                    and self.hosts[sticky].fits(priced):
                self.stats["affinity_hits"] += 1
                return self._assign(sticky, priced, cost, "hit", key)
            candidates = [i for i, h in enumerate(self.hosts)
                          if h.available and h.fits(priced)
                          and i not in banned]
            if not candidates:
                if count_held:
                    self.stats["held"] += 1
                return None
            best = min(candidates, key=lambda i: (
                self.hosts[i].utilisation(),
                self.hosts[i].pending_cost_ms, i))
            if sticky is None:
                # unseen corpus: the chosen host becomes its warm home
                self._affinity[key] = best
                self.stats["affinity_misses"] += 1
                return self._assign(best, priced, cost, "miss", key)
            # sticky host over budget (or excluded for this request):
            # spill WITHOUT moving the sticky mapping — the corpus
            # returns to its warm host later
            self.stats["spills"] += 1
            return self._assign(best, priced, cost, "spill", key)

    def _assign(self, host: int, priced: int, cost: float, kind: str,
                key: Hashable) -> Placement:
        h = self.hosts[host]
        h.assigned_bytes += priced
        h.assigned_requests += 1
        h.pending_cost_ms += cost
        h.placed_total += 1
        h.peak_assigned_bytes = max(h.peak_assigned_bytes,
                                    h.assigned_bytes)
        self.stats["placed"] += 1
        return Placement(host, priced, cost, kind, key)

    def assign_to(self, host: int, key: Hashable, priced_bytes: int,
                  cost_ms: Optional[float] = None) -> Placement:
        """Pin one request to `host`, bypassing affinity (warmup
        traffic that must touch a specific process). Accounted against
        the budget vector like any placement; does not move sticky
        mappings."""
        with self._lock:
            return self._assign(host, max(int(priced_bytes), 0),
                                float(cost_ms) if cost_ms else 0.0,
                                "pinned", key)

    def place_mirror(self, key: Hashable, priced_bytes: int,
                     cost_ms: Optional[float] = None,
                     exclude: Sequence[int] = ()
                     ) -> Optional[Placement]:
        """The hedged-dispatch placement: least-loaded SERVING host
        outside `exclude` (the slow host and any host already carrying
        a copy) with budget headroom, charged against the vector like
        any placement, never touching the sticky map. None when no
        compatible host has headroom — a hedge is opportunistic
        insurance, never worth holding for."""
        priced = max(int(priced_bytes), 0)
        cost = float(cost_ms) if cost_ms else 0.0
        banned = set(exclude)
        with self._lock:
            candidates = [i for i, h in enumerate(self.hosts)
                          if h.available and h.fits(priced)
                          and i not in banned]
            if not candidates:
                return None
            best = min(candidates, key=lambda i: (
                self.hosts[i].utilisation(),
                self.hosts[i].pending_cost_ms, i))
            self.stats["hedges"] += 1
            return self._assign(best, priced, cost, "hedge", key)

    def set_host_state(self, host: int, state: str) -> None:
        """Record host `host`'s supervision state (``serving`` /
        ``restarting`` / ``stalled`` / ``quarantined``). Any state but
        ``serving`` removes the host from NEW placements; its sticky
        mappings fail over lazily on the next placement that needs
        them. Existing assignments keep their accounting until
        released — a dead host's priced bytes come back when its
        requests complete elsewhere."""
        with self._lock:
            self.hosts[host].state = str(state)

    def host_state(self, host: int) -> str:
        with self._lock:
            return self.hosts[host].state

    def release(self, placement: Placement) -> None:
        """The placed request finished (or was abandoned): return its
        budget slice and pending cost to the host."""
        with self._lock:
            h = self.hosts[placement.host]
            h.assigned_bytes -= placement.priced_bytes
            h.assigned_requests -= 1
            h.pending_cost_ms -= placement.cost_ms

    # --------------------------------------------------------------- view
    def snapshot(self) -> Dict:
        """The router's metrics row for the fleet ``metrics.json``:
        placement counters plus the per-host budget-vector occupancy
        (assigned/peak/budget bytes — the fleet-level generalization of
        the single server's ``inflight`` section)."""
        with self._lock:
            return {
                "stats": dict(self.stats),
                "affinity_keys": len(self._affinity),
                "hosts": [{
                    "host": i,
                    "state": h.state,
                    "budget_bytes": h.budget_bytes,
                    "assigned_bytes": h.assigned_bytes,
                    "assigned_requests": h.assigned_requests,
                    "peak_assigned_bytes": h.peak_assigned_bytes,
                    "pending_cost_ms": round(h.pending_cost_ms, 3),
                    "placed_total": h.placed_total,
                } for i, h in enumerate(self.hosts)],
            }

    def affinity_hit_rate(self) -> float:
        """Fraction of ROUTED placements that landed on their sticky
        warm host (the fleet tripwire's warm-locality gate). Pinned
        placements (``assign_to`` warmups) are not routing decisions
        and do not dilute the rate."""
        with self._lock:
            routed = (self.stats["affinity_hits"]
                      + self.stats["affinity_misses"]
                      + self.stats["spills"])
            return self.stats["affinity_hits"] / routed if routed else 0.0
