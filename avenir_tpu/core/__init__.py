"""Core layer: schema metadata, config, columnar ingest, metrics.

Replaces the role of the reference's external `chombo` library
(FeatureSchema/FeatureField, Utility.setConfiguration, Tuple writables)
with columnar, device-friendly equivalents.
"""
