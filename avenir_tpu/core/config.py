"""Job configuration: flat .properties files with per-job key prefixes.

The reference passes a flat properties file to every job via
`-Dconf.path=...`; chombo's `Utility.setConfiguration` splices the entries
into the Hadoop Configuration and jobs read namespaced keys like `nen.*`,
`dtb.*`, `bad.*` plus shared un-prefixed keys (`field.delim.regex`,
`num.reducer`, `debug.on`) — see resource/knn.properties and
resource/detr.properties. Required params fail fast
(chombo Utility.assertIntConfigParam, e.g. reinforce/GreedyRandomBandit.java:112).

This module reads the *same* files unchanged. `JobConfig` is the analog of a
job's view of the Hadoop Configuration: typed getters with a job prefix that
fall back to the un-prefixed shared key, and assert-variants that raise a
clear error when a required key is missing.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional


def load_properties(path: str) -> Dict[str, str]:
    """Parse a java-style .properties file into a dict.

    Supports `#`/`!` comments, `key=value` and `key: value`, trailing
    backslash line continuation, and strips whitespace around keys/values.
    Empty values are kept as empty strings (the reference leaves optional
    keys empty, e.g. `dtb.min.info.gain.limit=` in detr.properties).
    """
    props: Dict[str, str] = {}
    with open(path, "r") as fh:
        pending = ""
        for raw in fh:
            line = pending + raw.rstrip("\n")
            pending = ""
            stripped = line.strip()
            if not stripped or stripped.startswith("#") or stripped.startswith("!"):
                continue
            if stripped.endswith("\\"):
                pending = stripped[:-1]
                continue
            m = re.match(r"([^=:]+)[=:](.*)", stripped)
            if not m:
                continue
            props[m.group(1).strip()] = m.group(2).strip()
    return props


def parse_properties_string(text: str) -> Dict[str, str]:
    props: Dict[str, str] = {}
    for stripped in (ln.strip() for ln in text.splitlines()):
        if not stripped or stripped.startswith("#") or stripped.startswith("!"):
            continue
        m = re.match(r"([^=:]+)[=:](.*)", stripped)
        if m:
            props[m.group(1).strip()] = m.group(2).strip()
    return props


_TRUE = {"true", "yes", "1", "on"}


class MissingConfigError(KeyError):
    """A required configuration key is absent (or empty)."""


class JobConfig:
    """A job's typed view over the flat properties, with a key prefix.

    `get*("top.match.count")` on a JobConfig with prefix "nen" resolves
    `nen.top.match.count`, then the bare `top.match.count`, then the default.
    This mirrors how reference jobs combine per-job prefixed keys with shared
    keys in one file.
    """

    def __init__(self, props: Dict[str, str], prefix: str = ""):
        self.props = dict(props)
        self.prefix = prefix

    @classmethod
    def from_file(cls, path: str, prefix: str = "") -> "JobConfig":
        return cls(load_properties(path), prefix)

    def scoped(self, prefix: str) -> "JobConfig":
        """Same properties viewed under a different job prefix."""
        return JobConfig(self.props, prefix)

    # ------------------------------------------------------------ raw lookup
    def _lookup(self, key: str) -> Optional[str]:
        if self.prefix:
            val = self.props.get(f"{self.prefix}.{key}")
            if val is not None and val != "":
                return val
        val = self.props.get(key)
        if val is not None and val != "":
            return val
        return None

    def has(self, key: str) -> bool:
        return self._lookup(key) is not None

    # --------------------------------------------------------- typed getters
    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        val = self._lookup(key)
        return val if val is not None else default

    def get_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        val = self._lookup(key)
        return int(val) if val is not None else default

    def get_float(self, key: str, default: Optional[float] = None) -> Optional[float]:
        val = self._lookup(key)
        return float(val) if val is not None else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        val = self._lookup(key)
        return val.lower() in _TRUE if val is not None else default

    def get_list(self, key: str, default: Optional[List[str]] = None,
                 delim: str = ",") -> Optional[List[str]]:
        val = self._lookup(key)
        if val is None:
            return default
        return [tok.strip() for tok in val.split(delim) if tok.strip() != ""]

    def get_int_list(self, key: str, default: Optional[List[int]] = None,
                     delim: str = ",") -> Optional[List[int]]:
        toks = self.get_list(key, None, delim)
        return [int(t) for t in toks] if toks is not None else default

    def get_float_list(self, key: str, default: Optional[List[float]] = None,
                       delim: str = ",") -> Optional[List[float]]:
        toks = self.get_list(key, None, delim)
        return [float(t) for t in toks] if toks is not None else default

    # ------------------------------------------------------ required getters
    def _require(self, key: str, val: Any, what: str) -> Any:
        if val is None:
            full = f"{self.prefix}.{key}" if self.prefix else key
            raise MissingConfigError(f"missing required {what} config param: {full}")
        return val

    def assert_get(self, key: str) -> str:
        return self._require(key, self._lookup(key), "string")

    def assert_int(self, key: str) -> int:
        return int(self._require(key, self._lookup(key), "int"))

    def assert_float(self, key: str) -> float:
        return float(self._require(key, self._lookup(key), "float"))

    def assert_list(self, key: str, delim: str = ",") -> List[str]:
        return self._require(key, self.get_list(key, None, delim), "list")

    # ---------------------------------------------------------- shared keys
    @property
    def field_delim(self) -> str:
        return self.props.get("field.delim", self.props.get("field.delim.out", ","))

    @property
    def field_delim_regex(self) -> str:
        return self.props.get("field.delim.regex", ",")

    @property
    def debug_on(self) -> bool:
        return self.props.get("debug.on", "false").lower() in _TRUE

    def __repr__(self) -> str:
        return f"JobConfig(prefix={self.prefix!r}, {len(self.props)} keys)"
