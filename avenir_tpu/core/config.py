"""Job configuration: flat .properties files with per-job key prefixes.

The reference passes a flat properties file to every job via
`-Dconf.path=...`; chombo's `Utility.setConfiguration` splices the entries
into the Hadoop Configuration and jobs read namespaced keys like `nen.*`,
`dtb.*`, `bad.*` plus shared un-prefixed keys (`field.delim.regex`,
`num.reducer`, `debug.on`) — see resource/knn.properties and
resource/detr.properties. Required params fail fast
(chombo Utility.assertIntConfigParam, e.g. reinforce/GreedyRandomBandit.java:112).

This module reads the *same* files unchanged. `JobConfig` is the analog of a
job's view of the Hadoop Configuration: typed getters with a job prefix that
fall back to the un-prefixed shared key, and assert-variants that raise a
clear error when a required key is missing.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional


def load_properties(path: str) -> Dict[str, str]:
    """Parse a java-style .properties file into a dict.

    Supports `#`/`!` comments, `key=value` and `key: value`, trailing
    backslash line continuation, and strips whitespace around keys/values.
    Empty values are kept as empty strings (the reference leaves optional
    keys empty, e.g. `dtb.min.info.gain.limit=` in detr.properties).
    """
    props: Dict[str, str] = {}
    with open(path, "r") as fh:
        pending = ""
        for raw in fh:
            line = pending + raw.rstrip("\n")
            pending = ""
            stripped = line.strip()
            if not stripped or stripped.startswith("#") or stripped.startswith("!"):
                continue
            if stripped.endswith("\\"):
                pending = stripped[:-1]
                continue
            m = re.match(r"([^=:]+)[=:](.*)", stripped)
            if not m:
                continue
            props[m.group(1).strip()] = m.group(2).strip()
    return props


def parse_properties_string(text: str) -> Dict[str, str]:
    props: Dict[str, str] = {}
    for stripped in (ln.strip() for ln in text.splitlines()):
        if not stripped or stripped.startswith("#") or stripped.startswith("!"):
            continue
        m = re.match(r"([^=:]+)[=:](.*)", stripped)
        if m:
            props[m.group(1).strip()] = m.group(2).strip()
    return props


def load_hocon(path: str) -> Dict[str, Dict[str, str]]:
    """Parse the HOCON subset the reference's Spark layer uses
    (resource/atmTrans.conf, sup.conf; consumed per job block by
    chombo-spark JobConfiguration, MarkovStateTransitionModel.scala:43-46):
    one `jobName { ... }` block per job, `key = value` / `key: value`
    entries, `//`/`#` comments, quoted or bare scalars, and `[a, "b"]`
    lists. Nested blocks flatten to dotted keys. Values normalize to the
    .properties string convention — lists become comma-joined strings — so
    a JobConfig over a block behaves exactly like one over a properties
    file."""
    blocks: Dict[str, Dict[str, str]] = {}
    stack: List[str] = []
    with open(path) as fh:
        text = fh.read()
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("//") or line.startswith("#"):
            continue
        if line.endswith("{"):
            stack.append(line[:-1].strip())
            continue
        if line == "}":
            if not stack:
                raise ValueError(f"{path}: unbalanced '}}'")
            stack.pop()
            continue
        m = re.match(r"([^=:{]+?)\s*[=:]\s*(.*)$", line)
        if not m:
            continue
        key, val = m.group(1).strip(), m.group(2).strip()
        if not stack:
            raise ValueError(f"{path}: top-level entry {key!r} outside a job block")
        block = stack[0]
        dotted = ".".join(stack[1:] + [key])
        blocks.setdefault(block, {})[dotted] = _hocon_value(val)
    if stack:
        raise ValueError(f"{path}: unclosed block {stack[-1]!r}")
    return blocks


def _hocon_value(val: str) -> str:
    val = val.strip()
    if val.startswith("[") and val.endswith("]"):
        inner = val[1:-1].strip()
        if not inner:
            return ""
        return ",".join(_hocon_value(tok) for tok in inner.split(","))
    if len(val) >= 2 and val[0] == val[-1] and val[0] in "\"'":
        return val[1:-1]
    return val


_TRUE = {"true", "yes", "1", "on"}


class MissingConfigError(KeyError):
    """A required configuration key is absent (or empty)."""


class JobConfig:
    """A job's typed view over the flat properties, with a key prefix.

    `get*("top.match.count")` on a JobConfig with prefix "nen" resolves
    `nen.top.match.count`, then the bare `top.match.count`, then the default.
    This mirrors how reference jobs combine per-job prefixed keys with shared
    keys in one file.
    """

    def __init__(self, props: Dict[str, str], prefix: str = ""):
        self.props = dict(props)
        self.prefix = prefix

    @classmethod
    def from_file(cls, path: str, prefix: str = "") -> "JobConfig":
        return cls(load_properties(path), prefix)

    @classmethod
    def from_hocon(cls, path: str, block: str, prefix: str = "") -> "JobConfig":
        """A job's view of one HOCON job block (the Spark-surface config,
        e.g. resource/atmTrans.conf driving contTimeStateTransitionStats)."""
        blocks = load_hocon(path)
        if block not in blocks:
            raise MissingConfigError(
                f"no block {block!r} in {path} (has: {', '.join(sorted(blocks))})")
        return cls(blocks[block], prefix)

    def scoped(self, prefix: str) -> "JobConfig":
        """Same properties viewed under a different job prefix."""
        return JobConfig(self.props, prefix)

    # ------------------------------------------------------------ raw lookup
    def _lookup(self, key: str) -> Optional[str]:
        if self.prefix:
            val = self.props.get(f"{self.prefix}.{key}")
            if val is not None and val != "":
                return val
        val = self.props.get(key)
        if val is not None and val != "":
            return val
        return None

    def has(self, key: str) -> bool:
        return self._lookup(key) is not None

    # --------------------------------------------------------- typed getters
    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        val = self._lookup(key)
        return val if val is not None else default

    def get_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        val = self._lookup(key)
        return int(val) if val is not None else default

    def get_float(self, key: str, default: Optional[float] = None) -> Optional[float]:
        val = self._lookup(key)
        return float(val) if val is not None else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        val = self._lookup(key)
        return val.lower() in _TRUE if val is not None else default

    def get_list(self, key: str, default: Optional[List[str]] = None,
                 delim: str = ",") -> Optional[List[str]]:
        val = self._lookup(key)
        if val is None:
            return default
        return [tok.strip() for tok in val.split(delim) if tok.strip() != ""]

    def get_int_list(self, key: str, default: Optional[List[int]] = None,
                     delim: str = ",") -> Optional[List[int]]:
        toks = self.get_list(key, None, delim)
        return [int(t) for t in toks] if toks is not None else default

    def get_float_list(self, key: str, default: Optional[List[float]] = None,
                       delim: str = ",") -> Optional[List[float]]:
        toks = self.get_list(key, None, delim)
        return [float(t) for t in toks] if toks is not None else default

    # ------------------------------------------------------ required getters
    def _require(self, key: str, val: Any, what: str) -> Any:
        if val is None:
            full = f"{self.prefix}.{key}" if self.prefix else key
            raise MissingConfigError(f"missing required {what} config param: {full}")
        return val

    def assert_get(self, key: str) -> str:
        return self._require(key, self._lookup(key), "string")

    def assert_int(self, key: str) -> int:
        return int(self._require(key, self._lookup(key), "int"))

    def assert_float(self, key: str) -> float:
        return float(self._require(key, self._lookup(key), "float"))

    def assert_list(self, key: str, delim: str = ",") -> List[str]:
        return self._require(key, self.get_list(key, None, delim), "list")

    # ---------------------------------------------------------- shared keys
    @property
    def field_delim(self) -> str:
        return self.props.get("field.delim", self.props.get("field.delim.out", ","))

    @property
    def field_delim_regex(self) -> str:
        # field.delim.in is the HOCON/Spark-surface spelling
        return self.props.get("field.delim.regex",
                              self.props.get("field.delim.in", ","))

    @property
    def debug_on(self) -> bool:
        return self.props.get("debug.on", "false").lower() in _TRUE

    def __repr__(self) -> str:
        return f"JobConfig(prefix={self.prefix!r}, {len(self.props)} keys)"
