"""Atomic-publish discipline: the one way a shared file commits.

Every shared-filesystem protocol in this repo — spool results, leases,
ledger claims/states, shard plans, checkpoints, sidecar manifests, tune
profiles — publishes through the same three-step discipline: write the
complete payload to a UNIQUELY-NAMED SIBLING tmp file, commit it with
one atomic ``os.replace`` (or ``os.link`` for first-commit-wins), and
clean the tmp up on every exit path. A reader then sees either no file
or a complete one, two racing writers can never collide on a tmp name,
and a rename can never silently become a cross-filesystem copy (the
tmp is a sibling by construction). graftlint's proto tier
(analysis/proto.py) checks the discipline statically and this module is
its runtime half:

- :func:`unique_tmp` / :func:`publish_bytes` / :func:`publish_json` —
  the shared publish helpers the protocol modules commit through.
- :func:`crash_point` — the ``AVENIR_PROTO_CRASH`` kill-injection hook:
  each registered commit site calls it immediately before and after
  its rename, and the crash-point auditor (``graftlint --proto``) runs
  a real job per site with the hook armed, hard-kills the process at
  both stages, and asserts recovery is byte-identical to an uncrashed
  run. Production never sets the variable, so the hook is a dict probe.
- :func:`sched_point` — the ``AVENIR_RACE_SCHED`` file-turnstile hook:
  each registered interleave site calls it at every schedule-sensitive
  step, and the interleaving explorer (``graftlint --race``) steps two
  REAL actor processes through exhaustive + seeded schedules, asserting
  the shared outcome is schedule-independent. Same production contract
  as ``crash_point``: one env probe, nothing more.
- :func:`sweep_stale_tmps` — startup GC for the tmp files hard-killed
  writers leave behind: age-gated (mtime), so a LIVE tmp mid-commit is
  never swept, and matched on the ``.tmp`` naming convention only, so
  committed artifacts are never touched.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import List, Optional

#: the kill-injection env var: ``"<site>:<stage>"`` hard-exits the
#: process at that registered commit point (graftlint --proto only)
CRASH_ENV = "AVENIR_PROTO_CRASH"

#: the interleaving-turnstile env var: ``"<turnstile-dir>:<actor-idx>"``
#: parks the process at every :func:`sched_point` until the scheduler
#: grants its next step (graftlint --race only)
SCHED_ENV = "AVENIR_RACE_SCHED"

#: how long a parked actor waits for a grant before declaring the
#: scheduler dead — generous, the explorer normally grants in ~1ms
SCHED_TIMEOUT_S = 120.0

#: crash stages every registered commit site exposes
BEFORE_RENAME = "before-rename"
AFTER_RENAME = "after-rename"

#: the injected crash's exit code — distinguishable from a real error
CRASH_EXIT = 43

#: a tmp file untouched for this long is orphaned: no publish in this
#: repo holds a tmp open for minutes, so the only writer that can have
#: left it is one that died before its rename
STALE_TMP_AGE_S = 300.0


def crash_point(site: str, stage: str) -> None:
    """Hard-kill the process (``os._exit``) when the auditor armed this
    exact ``site:stage``; a no-op (one env probe) otherwise. Called by
    every registered commit site right before and right after its
    atomic rename — the two instants a crash must provably not corrupt
    or strand shared state."""
    if os.environ.get(CRASH_ENV, "") == f"{site}:{stage}":
        os._exit(CRASH_EXIT)


#: per-arming step counters for :func:`sched_point` — keyed by the env
#: value so a fresh turnstile dir (a new explored schedule) restarts
#: the sequence at 0 inside a long-lived actor process
_SCHED_SEQ: dict = {}


def sched_point(name: str) -> None:
    """Deterministic-interleaving hook: a no-op (one env probe) in
    production; when ``AVENIR_RACE_SCHED=<turnstile-dir>:<actor-idx>``
    is set, the process PARKS here until the interleaving explorer
    (``graftlint --race``) grants its next step. The rendezvous is
    file-based so any two real protocol actors can be stepped without
    shared memory: the actor atomically publishes
    ``ready.<actor>.<seq>`` (content: `name`, so the scheduler can
    trace WHICH protocol step it is granting) into the turnstile dir,
    then polls for the matching ``go.<actor>.<seq>`` token. Every
    registered interleave site (analysis/race.py INTERLEAVE_SITES)
    calls it at each step where schedule order can change the shared
    outcome — right where the matching ``crash_point`` sits, plus the
    reads a concurrent writer can invalidate."""
    spec = os.environ.get(SCHED_ENV, "")
    if not spec:
        return
    turnstile, _, actor = spec.rpartition(":")
    seq = _SCHED_SEQ.get(spec, 0)
    _SCHED_SEQ[spec] = seq + 1
    tag = f"{actor}.{seq:04d}"
    ready = os.path.join(turnstile, f"ready.{tag}")
    wip = ready + ".wip"
    with open(wip, "w") as fh:
        fh.write(name)
    os.replace(wip, ready)
    go = os.path.join(turnstile, f"go.{tag}")
    deadline = time.monotonic() + SCHED_TIMEOUT_S
    while not os.path.exists(go):
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"sched_point({name!r}): no grant for step {tag} "
                f"within {SCHED_TIMEOUT_S:.0f}s (scheduler gone?)")
        time.sleep(0.0005)


def unique_tmp(path: str) -> str:
    """A uniquely-named tmp path in the SAME directory as `path`: two
    racing writers can never collide on it, and the commit rename is
    same-filesystem (atomic) by construction. Dot-prefixed so directory
    scans for committed names never pick it up; ``.tmp``-suffixed so
    :func:`sweep_stale_tmps` can GC it if the writer dies."""
    head, base = os.path.split(path)
    return os.path.join(head, f".{base}.{uuid.uuid4().hex[:8]}.tmp")


def publish_bytes(payload: bytes, path: str, site: Optional[str] = None,
                  fsync: bool = False) -> str:
    """Atomically publish `payload` at `path`: unique sibling tmp,
    ``os.replace``, tmp removed on every failure path. ``site`` names a
    registered commit point (the crash-point auditor's hook fires on
    both sides of the rename); ``fsync`` flushes the payload to disk
    before the commit (the sidecar manifest's durability contract)."""
    tmp = unique_tmp(path)
    try:
        with open(tmp, "wb") as fh:
            fh.write(payload)
            if fsync:
                fh.flush()
                os.fsync(fh.fileno())
        if site is not None:
            crash_point(site, BEFORE_RENAME)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    if site is not None:
        crash_point(site, AFTER_RENAME)
    return path


def publish_json(obj, path: str, site: Optional[str] = None,
                 indent: Optional[int] = None,
                 fsync: bool = False) -> str:
    """:func:`publish_bytes` for one JSON document."""
    return publish_bytes(json.dumps(obj, indent=indent).encode("utf-8"),
                         path, site=site, fsync=fsync)


def is_tmp_name(name: str) -> bool:
    """True when `name` follows the protocol tmp naming convention —
    the only files :func:`sweep_stale_tmps` may remove."""
    base = os.path.basename(name)
    return base.endswith(".tmp") or ".tmp." in base


def sweep_stale_tmps(root: str,
                     min_age_s: float = STALE_TMP_AGE_S) -> List[str]:
    """GC orphaned protocol tmp files under `root` (recursively): every
    ``*.tmp`` / ``*.tmp.*`` file whose mtime is older than `min_age_s`
    is removed. Called at writer startup (ledger, lease store, spool
    server, checkpoint store, profile store, sidecar writer) so a
    hard-killed writer's leftovers do not accumulate forever. The age
    gate is what keeps a LIVE tmp safe: a concurrent writer mid-commit
    wrote its tmp moments ago, far inside any sane `min_age_s`, while
    an orphan by definition stopped aging when its writer died.
    Returns the removed paths; every OSError (racing sweepers, the
    writer committing first) is survived."""
    removed: List[str] = []
    if not os.path.isdir(root):
        return removed
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(dirnames)
        for name in sorted(filenames):
            if not is_tmp_name(name):
                continue
            path = os.path.join(dirpath, name)
            try:
                if time.time() - os.stat(path).st_mtime <= min_age_s:
                    continue
                os.remove(path)
            except OSError:
                continue
            removed.append(path)
    return removed
