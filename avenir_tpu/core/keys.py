"""Canonical cache-key digests and the view-neutral key registry.

Every cache in this reproduction — the sidecar directory, the
incremental checkpoint, the warm miner source, the exec-coalesce map,
the autotune profile — is keyed by a digest of its *view*: the inputs
and configuration that determine the served bytes. Those digests used
to live where each cache lived, six hand-maintained recipes that could
(and did) drift. This module is the single home for the recipes; the
cache modules call through it, and ``graftlint --keys`` perturbs every
registered key site to prove each recipe still covers its view.

Two registries live here as *data* the lint tier verifies:

- :data:`VIEW_NEUTRAL_KEYS` — config-key substrings that must NEVER
  fold into a view digest (they name where driver state lives or
  whether the tuner records, not how bytes are parsed or folded).
  Formerly a hand-maintained skip list inside ``runner._conf_digest``.
- :func:`key_site` — the no-op annotation marking each key function
  with the ``KEY_SITES`` registry name it implements, cross-checked in
  both directions by the auditor (like commit/sched points).

Byte-compatibility contract: every digest here is byte-identical to
the recipe it replaced, pinned by test — upgrading must not invalidate
a single on-disk cache.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Sequence

#: Config-key SUBSTRINGS that are view-neutral by contract: matching
#: keys only name WHERE driver state lives / whether the tuner records
#: — never how bytes are parsed or folded. The autotune control keys
#: must be digest-neutral so a job server injecting its profile dir
#: (or an operator flipping recording on) does not invalidate every
#: checkpoint; the knob keys the tuner OVERLAYS (block size etc.) are
#: ordinary prefixed props and stay in the digest, which is what
#: re-scans cold exactly when a knob value actually changes.
#: ``graftlint --keys`` verifies both directions: conf-keyed caches
#: must skip these (keys-overdigested-neutral, plus a live spurious-
#: miss probe) and must fold everything else they read
#: (keys-undigested-input, plus a live stale-serve probe).
VIEW_NEUTRAL_KEYS = (
    "incremental.state.dir",
    "stream.autotune",
)


def is_view_neutral(key: str) -> bool:
    """Whether a config key is declared view-neutral (substring match,
    the historical ``_conf_digest`` semantics)."""
    return any(frag in key for frag in VIEW_NEUTRAL_KEYS)


def key_site(name: str) -> str:
    """No-op marker binding a key function to its ``KEY_SITES`` entry.

    Purely declarative — returns its argument so the call is free of
    side effects. ``graftlint --keys`` cross-checks these annotations
    against the registry in both directions: an annotated site missing
    from the registry, or a registered site with no annotation, fails
    the audit (the commit/sched-point contract).
    """
    return name


# ===================================================== conf-view digest
def conf_digest(cfg) -> str:
    """Content digest of the configuration view a cached artifact was
    computed under: every prefixed property (minus the
    :data:`VIEW_NEUTRAL_KEYS` matches) plus the schema file's BYTES
    when one is configured. A restored carry must have parsed its
    prefix under the same view of the corpus the delta will be parsed
    under — any conf or schema-content change invalidates the cache.
    Deliberately conservative: a changed block size or checkpoint
    interval also re-scans cold (folds are proven chunk-invariant, but
    a rare cold refresh is cheaper than reasoning about which keys are
    view-affecting as the conf surface grows).

    key-covered: all — every non-neutral prefixed property folds in.
    """
    key_site("checkpoint.manifest")
    h = hashlib.sha1()
    for k in sorted(cfg.props):
        if is_view_neutral(k):
            continue
        h.update(f"{k}={cfg.props[k]}\n".encode())
    schema_path = cfg.get("feature.schema.file.path")
    if schema_path:
        try:
            with open(schema_path, "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(b"<unreadable schema>")
    return h.hexdigest()


# ==================================================== corpus identities
def state_digest(canonical: str, inputs: Sequence[str]) -> str:
    """Stable identity of a (job, input set): blake2b over the job's
    canonical name and the absolute input paths. Names WHERE durable
    per-(job, corpus) state lives (incremental state dirs, server
    checkpoint dirs) — content-independent on purpose, the state is
    supposed to FOLLOW a corpus through appends; content validity is
    proven separately by the stored block fingerprints.

    normalization: abspath — paths fold as ``os.path.abspath``.
    """
    return hashlib.blake2b(
        "\0".join([canonical] + [os.path.abspath(p) for p in inputs])
        .encode(), digest_size=8).hexdigest()


def corpus_digest(inputs: Sequence[str]) -> str:
    """Stable identity of an input set: blake2b over the absolute paths
    (the incremental state-dir recipe, minus the job). Content-
    independent on purpose: an autotune profile is supposed to FOLLOW a
    corpus through appends — the signals it holds age out of the window
    naturally.

    normalization: abspath — paths fold as ``os.path.abspath``.
    """
    key_site("autotune.profile")
    return hashlib.blake2b(
        "\0".join(os.path.abspath(p) for p in inputs).encode(),
        digest_size=8).hexdigest()


# ================================================= sidecar directories
def sidecar_config_digest(format_version: int, kind: str, delim: str,
                          block_bytes: int, extra) -> str:
    """The sidecar directory's parse-view digest: format version, scan
    kind, delimiter, block size, and the kind-specific extra (dataset:
    the normalized schema digest; bytes: the skip count). Any change
    names a DIFFERENT directory — the sidecar never invalidates in
    place, stale views just stop being referenced and age out under
    the byte budget.

    normalization: json — the view folds as a sorted-keys JSON list.
    """
    return hashlib.sha1(json.dumps(
        [format_version, kind, delim, int(block_bytes), extra],
        sort_keys=True).encode()).hexdigest()


# ==================================================== job-server tuples
def compat_tuple(mode: str, inputs: Sequence[str], kind: str,
                 block_mb: float, delim: str, schema) -> tuple:
    """The batching key: two requests with EQUAL keys can ride one
    SharedScan pass (same mode, same corpus, same scan kind, same
    stream block size, same field delimiter, and — for Dataset folds —
    the same schema file: exactly the preconditions
    ``runner.run_shared`` / ``run_incremental_shared`` enforce).

    normalization: abspath — paths fold as ``os.path.abspath``;
    block size rounds to 6 decimals so float formatting cannot split a
    batch.
    """
    key_site("compat.batch")
    return (mode,
            tuple(os.path.abspath(p) for p in inputs),
            kind,
            round(float(block_mb), 6),
            delim,
            schema)


def source_tuple(canonical: str, inputs: Sequence[str], delim: str,
                 skip: int, marker, tid_ord: int) -> tuple:
    """Warm identity of a miner source: the scan-shaping config
    (delimiter, skipped meta fields, infrequent-item marker,
    transaction-id ordinal) plus the corpus paths. Mining parameters
    (support threshold, max length) deliberately EXCLUDED — pass 1
    does not depend on them, so one warm source serves any mining
    request over the corpus. Content validity is the cache's own
    per-block fingerprint gate, not this tuple.

    normalization: abspath — paths fold as ``os.path.abspath``.
    key-covered: fia.support.threshold fia.item.set.length
    fia.max.item.set.length — pass-1-independent mining parameters.
    """
    key_site("warm.miner")
    return (canonical,
            tuple(os.path.abspath(p) for p in inputs),
            delim,
            int(skip),
            marker,
            int(tid_ord))


def model_tuple(kind: str, path: str, artifact_digest: str,
                schema_digest: str, format_version: int,
                dims: Sequence) -> tuple:
    """Warm identity of a SERVED model (the score plane's model cache,
    server/score.py): the scoreable family, the artifact path and its
    CONTENT digest (a retrained artifact under the same path is a
    different model — the cache must miss, never serve the old fit),
    the schema digest shaping feature encoding ('' for families that
    parse without one), the artifact's stamped ``format_version`` (0
    when unstamped — a foreign restamp must miss, not hit a warm entry
    loaded under the old layout), and the kind dims: the loader/
    classifier config that shapes the in-memory object (delimiter,
    class labels, threshold, bandit journal digest, ...). Request-time
    parameters (the row, bandit round/algorithm) deliberately EXCLUDED
    — one warm model serves any request over the artifact.

    normalization: abspath — the artifact path folds as
    ``os.path.abspath``; dims fold as a tuple of strings.
    key-covered: score.batch.window.ms score.batch.max
    score.cache.budget.mb — dispatch shaping and cache budget knobs
    change HOW a model is served, never WHAT it computes.
    """
    key_site("score.model")
    return (kind,
            os.path.abspath(path),
            artifact_digest,
            schema_digest,
            int(format_version),
            tuple(str(d) for d in dims))
