"""Chunked streaming CSV ingest: the 1B-row scale path.

The reference streams unbounded HDFS files through mappers one line at a
time (bayesian/BayesianDistribution.java:137 map() sees a single line; no
job ever holds an input split in memory). The TPU-native analog is block
streaming: read fixed-size byte blocks, cut at the last newline, columnar-
parse each block (native C++ single pass when built — native/csv_ingest.cpp)
and hand the algorithm a sequence of Dataset chunks whose sufficient
statistics it folds in. Count algebra is additive (NaiveBayesModel.
accumulate/merge, Markov bigram counts, Apriori supports), so chunked
ingest changes nothing about the result — host RSS stays O(block), not
O(file), which is what makes the BASELINE.md 1B-row metric physically
reachable on one host.

`prefetched()` overlaps host parsing of block k+1 with device compute on
block k in a daemon thread — the map/compute overlap Hadoop gets from
running mappers concurrently with the shuffle, without the shuffle.
"""

from __future__ import annotations

import os
import queue
import re
import threading
from typing import Iterable, Iterator, Optional, Tuple, TypeVar

from avenir_tpu import obs as _obs
from avenir_tpu.core.dataset import Dataset
from avenir_tpu.core.schema import FeatureSchema

DEFAULT_BLOCK_BYTES = 64 << 20
#: default queued-items depth of the outer prefetched() job feeds — the
#: `stream.prefetch.depth` conf key overrides it per job (the autotuner
#: moves it from measured stall attribution; analysis/mem.py prices the
#: blocks-in-flight terms from the same number)
DEFAULT_PREFETCH_DEPTH = 2
# first non-whitespace byte, located without copying the block the way
# bytes.strip() would (pattern.search scans the buffer in place)
_NONWS = re.compile(rb"\S")

T = TypeVar("T")


class CsvBlockReader:
    """Iterate Dataset chunks of a CSV file without loading it whole.

    Blocks are `block_bytes` of file data extended to the next newline;
    every chunk parses against the *same* schema object, so dictionary
    codes stay consistent across chunks (data-discovered vocabularies
    extend in place — see dataset._discover_cardinality)."""

    def __init__(self, path: str, schema: FeatureSchema, delim: str = ",",
                 block_bytes: int = DEFAULT_BLOCK_BYTES, engine: str = "auto",
                 keep_raw: bool = False,
                 byte_range: Optional[Tuple[int, int]] = None):
        """byte_range=(start, end) restricts the reader to one INPUT SPLIT
        of the file with the Hadoop LineRecordReader boundary contract
        (the multi-host ingest analog of an HDFS split): a split starting
        mid-line skips forward past its first newline (the previous split
        owns that line), and a split owns every line that STARTS before
        `end` — reading past `end` to finish the boundary line. Covering
        [0, size) with disjoint ranges therefore yields every line exactly
        once."""
        if not os.path.exists(path):
            raise FileNotFoundError(f"no such CSV file: {path!r}")
        if block_bytes < 1:
            raise ValueError(f"block_bytes must be positive, got {block_bytes}")
        if byte_range is not None:
            s, e = byte_range
            if s < 0 or e < s:
                raise ValueError(f"invalid byte_range {byte_range}")
        self.path = path
        self.schema = schema
        self.delim = delim
        self.block_bytes = block_bytes
        self.engine = engine
        self.keep_raw = keep_raw
        self.byte_range = byte_range

    def __iter__(self) -> Iterator[Dataset]:
        # one copy of the split-boundary algorithm: the byte blocks come
        # from iter_byte_blocks (same LineRecordReader contract), parsed
        # against the shared schema. The block read runs in a prefetch
        # thread so file IO overlaps the native parse (a ctypes call
        # releases the GIL) on multi-core hosts
        # depth=1: one block ahead is all the IO/parse overlap needs, and
        # it caps the raw bytes in flight at ~2 x block_bytes (jobs stack
        # an outer prefetched() of parsed Datasets on top of this)
        for blk in prefetched(iter_byte_blocks(self.path, self.block_bytes,
                                               self.byte_range), depth=1):
            yield self._parse(blk)

    def _parse(self, chunk: bytes) -> Dataset:
        t0 = _obs.now()
        ds = Dataset.from_csv(chunk, self.schema, delim=self.delim,
                              engine=self.engine, keep_raw=self.keep_raw)
        _obs.record("stream.parse", t0, path=self.path, nbytes=len(chunk),
                    rows=len(ds))
        return ds


def iter_csv_chunks(path: str, schema: FeatureSchema, delim: str = ",",
                    block_bytes: int = DEFAULT_BLOCK_BYTES,
                    engine: str = "auto",
                    keep_raw: bool = False) -> Iterator[Dataset]:
    """Yield Dataset chunks of `path`; a small file yields one chunk."""
    return iter(CsvBlockReader(path, schema, delim, block_bytes, engine,
                               keep_raw))


_DONE = object()

#: Audit/test hook: when set, called with no arguments once per item a
#: prefetched() worker produces (before the queue put). The chunk-
#: invariance auditor (analysis/flow.py) installs a deterministic-jitter
#: scheduler here to prove streamed folds don't depend on producer
#: timing, and a counting hook to prove chunk layouts actually differ.
#: Production leaves it None; the check is one load per block.
_produce_hook = None

#: Byte-accounting hook: when set, called with the byte size of every
#: bytes-like item a prefetched() worker produces (0 for non-bytes
#: items, which carry their own accounting). The memory auditor
#: (analysis/mem.py) installs a recorder here to prove the footprint
#: model's block-size term against the blocks that actually flowed —
#: the stream layer's half of the RSS oracle. Production leaves it
#: None; the check is one load per block.
_bytes_hook = None


def _item_nbytes(item) -> int:
    """Accountable byte size of a produced item: RAW byte blocks only —
    bare, or (offset, block) pairs from iter_byte_blocks' with_offsets
    mode (the delta-scan feeds). Parsed/encoded items (Datasets, padded
    pages, packed bitsets) are priced by the footprint model's own
    per-job terms, so counting them here would double-book them against
    the raw-block term."""
    if isinstance(item, (bytes, bytearray, memoryview)):
        return len(item)
    if isinstance(item, tuple) and len(item) == 2 \
            and isinstance(item[1], (bytes, bytearray, memoryview)):
        return len(item[1])
    return 0

#: consumer-side poll granularity: bounds how long a pull can block
#: before re-checking that the worker is still alive (a dead worker with
#: an empty queue would otherwise hang the consumer forever)
_GET_POLL_SECS = 0.5
#: close() bound on joining the worker; a worker alive past this is
#: wedged in `items` (e.g. blocking IO) and is reported, not ignored
_JOIN_SECS = 10.0


def _prefetch_worker(items: Iterable, q: "queue.Queue",
                     cancel: threading.Event, error_cell: list) -> None:
    """Producer body. Deliberately a MODULE function taking its state as
    arguments: a bound-method target would make the worker thread keep
    its own _Prefetcher alive, so an abandoned iterator could never be
    garbage-collected (and its worker never cancelled) while the worker
    ran — the leak the join contract exists to prevent."""

    def put(item) -> bool:
        # producer-stall attribution: time blocked on a FULL queue means
        # the CONSUMER (device fold / downstream parse) is the
        # bottleneck for this item — the dual of the consumer-stall
        # span in _Prefetcher.__next__
        t0 = _obs.now()
        while not cancel.is_set():
            try:
                q.put(item, timeout=0.1)
                _obs.record_min("stream.stall.producer", t0,
                                nbytes=_item_nbytes(item))
                return True
            except queue.Full:
                continue
        return False

    it = iter(items)
    try:
        for item in it:
            hook = _produce_hook
            if hook is not None:
                hook()
            bhook = _bytes_hook
            if bhook is not None:
                bhook(_item_nbytes(item))
            if not put(item):
                break
        else:
            put(_DONE)
    except BaseException as exc:  # re-raised on the consumer side
        error_cell[0] = exc       # kept even if the queue put loses a
        put(exc)                  # race with close(): never dropped
    finally:
        close = getattr(it, "close", None)
        if close is not None:
            close()


class _Prefetcher(Iterator[T]):
    """Iterator over `items` produced by a background worker thread.

    The consumer contract prefetched() documents lives here: order
    preserved, worker exceptions re-raise at the consumer's next pull,
    and close() — called explicitly, by `yield from` delegation, on
    exhaustion, or at GC — cancels AND JOINS the worker so its thread
    and any file handle inside `items` never outlive the consumer. A
    worker exception that the consumer has not yet pulled re-raises from
    an explicit close() instead of being dropped."""

    def __init__(self, items: Iterable[T], depth: int):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._cancel = threading.Event()
        self._error_cell: list = [None]
        self._thread: Optional[threading.Thread] = threading.Thread(
            target=_prefetch_worker,
            args=(items, self._q, self._cancel, self._error_cell),
            daemon=True)
        self._thread.start()

    def __iter__(self) -> "_Prefetcher":
        return self

    def __next__(self) -> T:
        if self._thread is None:
            raise StopIteration
        # consumer-stall attribution: time blocked on an EMPTY queue
        # means the PRODUCER (disk read / parse worker) is the
        # bottleneck for this pull
        t0 = _obs.now()
        while True:
            try:
                item = self._q.get(timeout=_GET_POLL_SECS)
            except queue.Empty:
                if not self._thread.is_alive() and self._q.empty():
                    # every worker exit path posts _DONE or an exception;
                    # an empty queue with a dead worker means the process
                    # is tearing down — fail crisply instead of hanging
                    self.close()
                    raise RuntimeError(
                        "prefetch worker exited without a result")
                continue
            if item is _DONE:
                self.close()
                raise StopIteration
            if isinstance(item, BaseException):
                self._error_cell[0] = None   # delivered: close() must
                self.close(_suppress=True)   # not re-raise it
                raise item
            _obs.record_min("stream.stall.consumer", t0,
                            nbytes=_item_nbytes(item))
            return item

    def close(self, _suppress: bool = False) -> None:
        """Cancel the worker, join it, and re-raise any worker exception
        the consumer never pulled (unless `_suppress`, used on the paths
        where the exception is already propagating)."""
        thread, self._thread = self._thread, None
        if thread is None:
            return
        self._cancel.set()
        # drain so a worker blocked on a full queue sees the cancel fast
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        thread.join(_JOIN_SECS)
        if thread.is_alive():
            raise RuntimeError(
                f"prefetch worker failed to stop within {_JOIN_SECS}s "
                f"(wedged inside its source iterable?)")
        pending, self._error_cell[0] = self._error_cell[0], None
        if pending is not None and not _suppress:
            raise pending

    def __del__(self):
        try:
            self.close(_suppress=True)   # GC close never raises
        except Exception:
            pass


def prefetched(items: Iterable[T], depth: int = 2) -> Iterator[T]:
    """Run `items` in a background worker thread, keeping up to `depth`
    results queued ahead of the consumer. Exceptions re-raise at the
    consumer's next pull; order is preserved. The returned iterator's
    close() (also invoked by abandonment/GC) cancels AND joins the worker
    — so its thread and any file handle inside `items` don't outlive the
    consumer — and propagates a worker exception the consumer never saw."""
    return _Prefetcher(items, depth)


def double_buffered(items: Iterable[T]) -> Iterator[T]:
    """Depth-1 prefetch: host production of block k+1 overlaps consumption
    (device counting) of block k, and at most ONE finished block waits in
    the queue — the bounded-RSS flavor of prefetched() the multi-pass
    miners put between chunk encode/pack and the device support fold.
    Stacks safely on the inner byte-block prefetch: the pipeline then
    holds one block being read, one being encoded, one being counted."""
    return prefetched(items, depth=1)


class SharedScan:
    """ONE disk read + ONE parse per chunk, fanned out to N fold sinks.

    The scan-sharing executor: every streamed job used to make its own
    full pass over the same corpus (nb + mi + discriminant each re-read
    and re-parsed the multi-GB churn CSV), so ingest cost — the measured
    limiter once folds are vectorized — multiplied with the job count.
    Here the chunk iterator (typically a prefetched() CSV/byte-block
    reader) runs ONCE and each produced chunk is handed to every
    registered sink in registration order, sequentially — fold order per
    sink is exactly the order the one-job-one-scan path would see, which
    is what makes shared-scan outputs byte-identical to per-job scans
    (asserted by the chunk-invariance auditor's fused entries).

    Error contract: a sink raising mid-scan closes the underlying
    iterator before the exception propagates — for a prefetched() feed
    that cancels AND joins the worker thread (the PR-4 _Prefetcher join
    guarantee), so a failing consumer never wedges or leaks the
    producer. Generator feeds built on ``yield from prefetched(...)``
    (stream_job_inputs and friends) delegate close() the same way."""

    def __init__(self, chunks: Iterable):
        self._chunks = chunks
        self._sinks: list = []

    def add_sink(self, sink, label: Optional[str] = None) -> None:
        """Register a per-chunk consumer: any callable taking one chunk
        (or an object with a ``consume`` method). `label` names the
        sink in its per-chunk ``stream.fold`` spans (default: the
        sink's class/function name)."""
        fn = getattr(sink, "consume", sink)
        if label is None:
            label = (type(sink).__name__ if hasattr(sink, "consume")
                     else getattr(sink, "__name__", "sink"))
        self._sinks.append((fn, label))

    def run(self) -> int:
        """Drive the scan: one pull per chunk, every sink sees it.
        Returns the number of chunks scanned. Each sink call records a
        ``stream.fold`` span and every chunk's full fan-out feeds the
        process-global ``chunk_latency_ms`` histogram — the per-chunk
        telemetry the obs tripwire proves is <=3% overhead."""
        n = 0
        it = iter(self._chunks)
        try:
            for chunk in it:
                t_chunk = _obs.now()
                for sink, label in self._sinks:
                    t0 = _obs.now()
                    sink(chunk)
                    _obs.record("stream.fold", t0, sink=label, chunk=n)
                _obs.observe("chunk_latency_ms",
                             (_obs.now() - t_chunk) * 1e3)
                n += 1
        except BaseException:
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()          # join the worker; the sink's (or
                except Exception:    # producer's) exception is already
                    pass             # propagating — don't mask it
            raise
        else:
            close = getattr(it, "close", None)
            if close is not None:
                close()
        return n


def prefetch_depth(cfg) -> int:
    """The `stream.prefetch.depth` conf key (default 2, floor 1): how
    many produced items may queue ahead of the consumer in the outer
    job feeds below. Deeper absorbs producer burstiness when the
    consumer measurably waits (the autotuner's signal); every queued
    item is a resident parsed chunk / raw block, which is why the
    footprint model's in-flight terms scale with this same number."""
    return max(int(cfg.get_float("stream.prefetch.depth",
                                 float(DEFAULT_PREFETCH_DEPTH))), 1)


def _sidecar_payloads(feed) -> Iterator:
    """Drop the (offset, length, hash) bookkeeping of a sidecar feed and
    the blank-block placeholders — what job consumers fold."""
    for _off, _length, _hash, payload in feed:
        if payload is not None:
            yield payload


def stream_job_inputs(cfg, inputs: Iterable[str], schema: FeatureSchema,
                      keep_raw: bool = False) -> Iterator[Dataset]:
    """Per-job streaming input helper: prefetched block chunks of every
    input path, sized by the `stream.block.size.mb` config key (default
    64) and queued `stream.prefetch.depth` deep. The one way runner
    jobs consume CSV inputs at unbounded size.

    When the columnar sidecar can engage (native parse path, single-byte
    delimiter, `stream.sidecar` not disabled), each path streams through
    native.sidecar.dataset_blocks instead: a verified repeat scan
    replays packed binary columns parse-free, a cold scan parses AND
    packs, and any doubt — absent manifest, content drift, torn write —
    falls back to the cold chunks below, byte-identically."""
    block = int(cfg.get_float("stream.block.size.mb", 64.0) * (1 << 20))
    depth = prefetch_depth(cfg)
    sc = sc_opts = None
    if not keep_raw:
        try:
            from avenir_tpu.native import sidecar as sc

            sc_opts = sc.opts_from_cfg(cfg)
        except Exception:
            sc_opts = None
    for path in inputs:
        feed = None
        if sc_opts is not None:
            feed = sc.dataset_blocks(sc_opts, path, schema,
                                     cfg.field_delim_regex, block)
        if feed is not None:
            yield from prefetched(_sidecar_payloads(feed), depth=depth)
        else:
            yield from prefetched(iter_csv_chunks(
                path, schema, cfg.field_delim_regex, block,
                keep_raw=keep_raw), depth=depth)


def iter_byte_blocks(path: str,
                     block_bytes: int = DEFAULT_BLOCK_BYTES,
                     byte_range: Optional[Tuple[int, int]] = None,
                     with_offsets: bool = False) -> Iterator:
    """Yield ~block_bytes raw byte blocks cut at line boundaries — the
    zero-copy feed for native block consumers (seq_encode): no decode,
    no per-line Python strings.

    byte_range=(start, end) restricts to one INPUT SPLIT with the same
    Hadoop LineRecordReader boundary contract as CsvBlockReader: a split
    starting mid-line skips past its first newline (the previous split
    owns that line) and owns every line that STARTS before `end`, so
    disjoint ranges covering [0, size) yield every line exactly once —
    multi-host ingest for the sequence jobs.

    with_offsets=True yields (offset, block) pairs instead, where
    `offset` is the ABSOLUTE file offset of the block's first byte, and
    whitespace-only blocks are yielded too so consecutive blocks tile
    the covered range gap-free — the delta-scan drivers (the incremental
    runner, the encoded-block cache's per-block fingerprints) account
    for every covered byte; consumers skip folding blank blocks
    themselves (folds treat them as zero rows anyway). The default mode
    keeps the historical contract: bare blocks, blanks dropped."""
    blocks = _offset_byte_blocks(path, block_bytes, byte_range)
    if with_offsets:
        return blocks
    return _blank_filtered(blocks)


def _blank_filtered(blocks: Iterator[Tuple[int, bytes]]) -> Iterator[bytes]:
    nonblank = _NONWS.search   # no-copy emptiness check (strip() copies)
    try:
        for _off, blk in blocks:
            if nonblank(blk):
                yield blk
    finally:
        blocks.close()          # abandonment closes the file promptly


def _offset_byte_blocks(path: str, block_bytes: int,
                        byte_range: Optional[Tuple[int, int]]
                        ) -> Iterator[Tuple[int, bytes]]:
    """(absolute offset, block) pairs tiling the byte range gap-free —
    the one copy of the split-boundary block cutter behind both
    iter_byte_blocks modes."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"no such input file: {path!r}")
    if block_bytes < 1:
        raise ValueError(f"block_bytes must be positive, got {block_bytes}")
    if byte_range is not None:
        s, e = byte_range
        if s < 0 or e < s:
            raise ValueError(f"invalid byte_range {byte_range}")
    size = os.path.getsize(path)
    start, end = byte_range if byte_range else (0, size)
    end = min(end, size)
    with open(path, "rb") as fh:
        if start > 0:
            fh.seek(start - 1)
            if fh.read(1) != b"\n":
                fh.readline()
        pos = fh.tell()
        emit = pos               # offset of the next unemitted byte
        carry = b""
        # per-block read spans: t_blk opens when assembly of the next
        # emitted block starts (reset after every yield, so consumer
        # time between pulls is never billed to the read)
        t_blk = _obs.now()
        while pos < end:
            block = fh.read(block_bytes)
            if not block:
                break
            pos += len(block)
            if pos >= end:
                # finish the line containing byte end-1 (we own every
                # line starting before `end`), reading past end if its
                # newline isn't buffered yet
                data = carry + block if carry else block
                carry = b""
                b = len(data) - (pos - end)
                if b > 0 and data[b - 1:b] == b"\n":
                    cut = b
                else:
                    nl = data.find(b"\n", b)
                    while nl < 0:
                        extra = fh.read(block_bytes)
                        if not extra:
                            break
                        off = len(data)
                        data += extra
                        nl = data.find(b"\n", off)
                    cut = (nl + 1) if nl >= 0 else len(data)
                _obs.record("stream.read", t_blk, path=path, offset=emit,
                            nbytes=cut)
                yield emit, data[:cut]
                return
            # carry never contains a newline, so the cut within `block`
            # is the cut within carry+block — splice with ONE copy
            # (join reads the memoryview; no intermediate slice bytes)
            cut = block.rfind(b"\n")
            if cut < 0:
                carry += block
                continue
            out = (b"".join((carry, memoryview(block)[:cut + 1]))
                   if carry else block[:cut + 1])
            carry = block[cut + 1:]
            _obs.record("stream.read", t_blk, path=path, offset=emit,
                        nbytes=len(out))
            yield emit, out
            emit += len(out)
            t_blk = _obs.now()
        if carry:
            _obs.record("stream.read", t_blk, path=path, offset=emit,
                        nbytes=len(carry))
            yield emit, carry


def split_byte_ranges(total: int, n: int) -> list:
    """`n` contiguous [lo, hi) ranges tiling ``[0, total)`` gap-free —
    the ONE copy of the input-split arithmetic behind every multi-process
    ingest surface (``parallel.multihost.host_shard_bounds``, the shard
    planner's nominal block bounds). Ceil-division sizing, so a total
    smaller than the split count yields trailing EMPTY ranges that still
    tile (``(total, total)``) — consumers built on the LineRecordReader
    boundary contract (``iter_byte_blocks``/``CsvBlockReader`` with
    ``byte_range=``) then see zero lines for those, never a duplicated
    or dropped boundary line. Pinned by the edge regression tests in
    tests/test_stream.py (no trailing newline, single-line corpus,
    corpus smaller than the split count)."""
    if n < 1:
        raise ValueError(f"split count must be positive, got {n}")
    if total < 0:
        raise ValueError(f"total must be non-negative, got {total}")
    per = (total + n - 1) // n
    ranges = []
    for i in range(n):
        lo = min(i * per, total)
        ranges.append((lo, min(lo + per, total)))
    return ranges


def is_blank_block(data: bytes) -> bool:
    """True when a raw byte block holds no non-whitespace byte — the
    no-copy check delta-scan drivers use to skip folding the blank
    blocks that with_offsets mode must still account for."""
    return _NONWS.search(data) is None


def iter_line_blocks(path: str,
                     block_bytes: int = DEFAULT_BLOCK_BYTES
                     ) -> Iterator[list]:
    """Yield lists of non-empty text lines, ~block_bytes of file each.

    The untyped-row analog of CsvBlockReader for jobs whose input is not
    schema-typed CSV (sequence files, transaction lists, free text): the
    reference streams those one line at a time through the same mapper
    contract (e.g. markov/MarkovStateTransitionModel.java:116-133,
    association/FrequentItemsApriori.java:138-150); here the unit is a
    block of lines, so host RSS stays O(block) however large the file."""
    for blk in iter_byte_blocks(path, block_bytes):
        lines = [ln.rstrip("\r")
                 for ln in blk.decode("utf-8", "replace").split("\n")
                 if ln.strip()]
        if lines:
            yield lines


def stream_job_lines(cfg, inputs: Iterable[str]) -> Iterator[list]:
    """Prefetched line blocks of every input path, sized by the same
    `stream.block.size.mb` key (and queued `stream.prefetch.depth`
    deep) as stream_job_inputs."""
    block = int(cfg.get_float("stream.block.size.mb", 64.0) * (1 << 20))
    depth = prefetch_depth(cfg)
    for path in inputs:
        yield from prefetched(iter_line_blocks(path, block), depth=depth)


def stream_job_byte_blocks(cfg, inputs: Iterable[str],
                           sidecar_skip: Optional[int] = None
                           ) -> Iterator[bytes]:
    """Prefetched raw byte blocks of every input path (the native
    seq_encode feed), sized by the same `stream.block.size.mb` key and
    queued `stream.prefetch.depth` deep.

    `sidecar_skip` OPTS IN to the bytes-kind columnar sidecar: callers
    whose consumers dispatch on native.sidecar.SidecarBytesBlock (the
    CSR folds — markov fit_csr, the miner scan sinks) pass their meta-
    column skip count, and verified repeat scans then replay packed
    codes instead of raw text. Callers that fold raw bytes directly
    leave it None and keep the historical feed."""
    block = int(cfg.get_float("stream.block.size.mb", 64.0) * (1 << 20))
    depth = prefetch_depth(cfg)
    sc = sc_opts = None
    if sidecar_skip is not None:
        try:
            from avenir_tpu.native import sidecar as sc

            sc_opts = sc.opts_from_cfg(cfg)
        except Exception:
            sc_opts = None
    for path in inputs:
        feed = None
        if sc_opts is not None:
            feed = sc.byte_blocks(sc_opts, path, cfg.field_delim_regex,
                                  int(sidecar_skip), block)
        if feed is not None:
            yield from prefetched(_sidecar_payloads(feed), depth=depth)
        else:
            yield from prefetched(iter_byte_blocks(path, block),
                                  depth=depth)
