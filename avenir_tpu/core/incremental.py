"""Incremental delta-scan state: content block fingerprints + atomic
fold-state checkpoints.

A production corpus is append-mostly, but every streamed job used to
re-scan from byte 0 — re-ingesting 100M unchanged rows IS the cost once
folds are vectorized (the framework-overhead thesis of arXiv:1811.04875
/ arXiv:1309.0215). The fold-state merge algebra is proven
(``merge(fold(A), fold(B)) == fold(A++B)`` byte-identically, graftlint
--merge, 8/8 per round), so an append-refresh only needs driver state:

- **Block fingerprints** — every byte block a scan folds is recorded as
  ``(offset, length, content hash)``. Two files agreeing on a
  fingerprint PREFIX agree byte-for-byte on the covered range, so an
  appended CSV invalidates nothing and an in-place edit invalidates
  exactly the blocks from the edit on. This replaces whole-file
  ``size+mtime_ns`` validity wherever a delta matters (the incremental
  runner here; the encoded-block cache's per-source segments in
  native/ingest.py).
- **Checkpoints** — a scan's carry (``StreamFoldOps.serialize_state``
  npz bytes) plus a JSON manifest naming the covered watermark and the
  fingerprints behind it, written atomically so a torn checkpoint can
  never commit. ``runner.run_incremental`` restores the newest
  checkpoint, folds only the blocks past the watermark, and re-emits
  the artifact — the same mechanism serves both the append-refresh
  (watermark = end of the previous corpus) and mid-corpus crash resume
  (watermark = the last periodic checkpoint before the kill).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Optional, Sequence, Tuple

from avenir_tpu.core.atomic import (publish_bytes, sched_point,
                                    sweep_stale_tmps)

#: fingerprint hash: sha1. Chosen by MEASURED throughput — the hash is
#: the incremental driver's per-refresh floor (the whole unchanged
#: prefix re-hashes before a carry restores), and on this host sha1
#: streams ~2.5x faster than blake2b (~1.2GB/s vs ~0.5GB/s) while crc32
#: is both slower and 32-bit. 160 bits is collision-safe at any corpus
#: size this repo targets; the table stays ~60 bytes per 64MB block.
_HASH = hashlib.sha1

#: Audit/test hook: when set, called with the checkpoint meta dict right
#: after every COMMITTED checkpoint write (the core.stream._produce_hook
#: pattern). The merge auditor's crash-resume leg installs an
#: interrupter here to abort a scan right after a mid-scan checkpoint;
#: the crash tests install an os._exit to simulate a hard kill.
#: Production leaves it None.
_checkpoint_hook = None


def block_hash(data: bytes) -> str:
    return _HASH(data).hexdigest()


def block_fingerprint(offset: int, data: bytes) -> Dict[str, object]:
    """The per-block validity unit of every delta scan: absolute file
    offset, byte length and content hash of one line-aligned block."""
    return {"offset": int(offset), "length": len(data),
            "hash": block_hash(data)}


def _fp_reads(path: str, fps: Sequence[dict]):
    """Sequential reads of the recorded block lengths — the producer
    half of verified_prefix, run in a prefetch thread so disk read and
    hashing overlap (hashlib releases the GIL for large buffers; the
    hash is the incremental driver's per-refresh floor, so halving its
    wall time is a direct speedup of every append-refresh)."""
    with open(path, "rb") as fh:
        fh.seek(int(fps[0]["offset"]))
        for fp in fps:
            yield fh.read(int(fp["length"]))


def verified_prefix(path: str, fps: Sequence[dict]) -> Tuple[int, int]:
    """(n_blocks, covered_end_offset) of the longest recorded-fingerprint
    prefix that still content-matches `path`.

    Offsets must tile gap-free from the first recorded offset and every
    block's bytes must re-hash to the recorded value — one sequential
    read of the covered range (prefetched, so IO overlaps the hash), no
    parse. Verification stops at the first mismatch: an in-place edit
    invalidates everything from the edited block on, while a pure
    append invalidates nothing (appended bytes sit past the last
    recorded block's end)."""
    from avenir_tpu.core.stream import prefetched

    n = 0
    covered = 0
    if not fps:
        return 0, 0
    try:
        size = os.path.getsize(path)
        expect = int(fps[0]["offset"])
        feed = prefetched(_fp_reads(path, fps), depth=2)
        try:
            for fp, data in zip(fps, feed):
                off, length = int(fp["offset"]), int(fp["length"])
                # geometry first: the reader streams assuming contiguity,
                # so a gap means the bytes it handed over are untrusted
                if off != expect or off + length > size:
                    break
                if len(data) != length or block_hash(data) != fp["hash"]:
                    break
                expect = off + length
                n += 1
                covered = expect
        finally:
            feed.close()
    except OSError:
        return 0, 0
    return n, covered


def ends_at_newline(path: str, offset: int) -> bool:
    """True when a watermark at `offset` sits on a line boundary (the
    byte before it is ``\\n``, or it is the start of the file). A
    recorded coverage whose final block does NOT end at a newline came
    from a corpus whose last line had no terminator — the already-folded
    row and any appended bytes form ONE line, so resuming past the
    watermark would silently skip the row's continuation. Delta gates
    (run_incremental's restore plan, EncodedBlockCache.source_delta)
    treat a grown file behind a mid-line watermark as unusable: cold
    re-scan, never a spliced row."""
    if offset <= 0:
        return True
    try:
        with open(path, "rb") as fh:
            fh.seek(offset - 1)
            return fh.read(1) == b"\n"
    except OSError:
        return False


class CheckpointStore:
    """Atomic on-disk checkpoint of one incremental scan: a carry blob
    next to a JSON manifest, under a per-(job, corpus) state directory.

    Write protocol — a torn checkpoint must NEVER commit (the standing
    cache/checkpoint contract): the carry blob lands first under a
    unique name (write to ``.tmp``, rename), then the manifest — which
    records the carry's file name, byte length and content hash —
    replaces the previous one the same way. A killed process leaves
    either the previous consistent pair or the new one on disk, and
    ``load()`` re-verifies the referenced blob's length and hash,
    returning None for anything missing, truncated or unparsable — the
    driver then falls back to a cold scan instead of resuming from
    (and committing) a wrong carry. No fsync: the hash-verified load is
    what makes a torn pair a DETECTED cold-fallback rather than a wrong
    resume, so the only cost of an unflushed page at power loss is a
    re-scan — while an fsync per checkpoint was measured at ~0.2s, a
    per-refresh floor the delta-scan driver cannot afford. Superseded
    carry files are removed only after the new manifest is in place.

    Single-writer: one incremental scan owns a state dir (the dir is
    keyed per (job, corpus)); concurrent SAVERS are out of contract.
    Concurrent READERS are in contract — the hash-verified load plus
    content-addressed carry names make every interleaving of save()
    and load() a consistent pair or a detected cold fallback
    (graftlint --race, checkpoint.save site)."""

    MANIFEST = "MANIFEST.json"
    #: manifest layout version; a manifest stamped with a DIFFERENT
    #: version refuses to load (cold start) — old readers must never
    #: silently parse a newer layout. A MISSING stamp is a
    #: pre-versioning checkpoint and still loads.
    FORMAT_VERSION = 1

    def __init__(self, state_dir: str):
        self.dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        # startup GC: tmp files a hard-killed writer left behind (the
        # age gate keeps a concurrent writer's live tmp safe)
        sweep_stale_tmps(state_dir)

    def _write_atomic(self, path: str, payload: bytes,
                      site: Optional[str] = None) -> None:
        publish_bytes(payload, path, site=site)

    def save(self, meta: dict, blob: bytes) -> dict:
        """Commit one checkpoint; returns the manifest actually written
        (meta plus the carry bookkeeping fields)."""
        token = f"{int(meta.get('seq', 0)):06d}_{block_hash(blob)[:8]}"
        carry = f"carry_{token}.npz"
        meta = dict(meta, carry_file=carry, carry_bytes=len(blob),
                    carry_hash=block_hash(blob))
        meta.setdefault("format_version", self.FORMAT_VERSION)
        sched_point("checkpoint.save")
        self._write_atomic(os.path.join(self.dir, carry), blob)
        # the manifest replace IS the commit point — the carry above is
        # invisible until the manifest references it
        sched_point("checkpoint.save")
        self._write_atomic(os.path.join(self.dir, self.MANIFEST),
                           json.dumps(meta, indent=1).encode(),
                           site="checkpoint.save")
        # superseded-carry GC races a concurrent load() holding the OLD
        # manifest: the loader finds its carry gone and reports None —
        # the cold-fallback contract, never a wrong resume
        sched_point("checkpoint.save")
        for name in os.listdir(self.dir):
            if (name.startswith("carry_") and name != carry) \
                    or name.endswith(".tmp"):
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass
        return meta

    def load(self) -> Optional[Tuple[dict, bytes]]:
        """(manifest, carry blob) of the newest committed checkpoint, or
        None when there is none — or when what is on disk is torn
        (missing/short/corrupt carry, unparsable manifest). A None here
        is the cold-scan fallback signal, never an error."""
        try:
            sched_point("checkpoint.load")
            with open(os.path.join(self.dir, self.MANIFEST), "rb") as fh:
                meta = json.loads(fh.read().decode())
            sched_point("checkpoint.load")
            with open(os.path.join(self.dir, str(meta["carry_file"])),
                      "rb") as fh:
                blob = fh.read()
            if meta.get("format_version",
                        self.FORMAT_VERSION) != self.FORMAT_VERSION:
                return None           # version skew: refuse, go cold
            if len(blob) != int(meta["carry_bytes"]) \
                    or block_hash(blob) != meta["carry_hash"]:
                return None
            return meta, blob
        except (OSError, ValueError, KeyError):
            return None

    def clear(self) -> None:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in names:
            if name == self.MANIFEST or name.startswith("carry_") \
                    or name.endswith(".tmp"):
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass
