"""Columnar dataset: CSV rows -> device-friendly arrays.

The reference's unit of data is a delimited text line on HDFS whose fields
get meaning from the FeatureSchema JSON (every mapper re-splits the line,
e.g. bayesian/BayesianDistribution.java:137-178). The TPU-native equivalent
is columnar: parse once on the host, dictionary-encode categoricals against
the schema's declared cardinality, bucketize binned numerics, and hand the
algorithms dense int32/float32 matrices that vmap/segment_sum can chew on.

Three views cover every algorithm family:
- `feature_codes()`  int32 [n, F]: dense per-feature states (categorical code
  or numeric bucket) — count-based algorithms (NB, MI, correlations, tree
  categorical splits, Apriori-style contingency work).
- `feature_matrix()` float32 [n, D]: numeric values (raw numerics; categorical
  columns excluded) — distance/gradient algorithms (KNN, LR, Fisher).
- `labels()`         int32 [n]: encoded class attribute.

Row identity (the `id` field) stays host-side as numpy object/str arrays —
ids never need to touch the device.
"""

from __future__ import annotations

import io
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from avenir_tpu.core.schema import FeatureField, FeatureSchema


class Dataset:
    """Columnar view of one CSV input split against a FeatureSchema."""

    def __init__(
        self,
        schema: FeatureSchema,
        columns: Dict[int, np.ndarray],
        n_rows: int,
        raw_rows: Optional[List[List[str]]] = None,
        lazy: Optional[Dict[int, object]] = None,
    ):
        self.schema = schema
        self.columns = columns          # ordinal -> np array (codes / floats / object)
        self.n_rows = n_rows
        self.raw_rows = raw_rows        # kept when passthrough output is needed
        # string/id columns parse lazily (thunks): most algorithms never
        # touch ids, and materializing millions of python strings halves
        # the native ingest rate (the 1B-row streaming path skips it)
        self._lazy = dict(lazy) if lazy else {}
        # feature_codes memo: a shared scan hands one chunk to several
        # consumers, each stacking the same [n, F] code matrix
        self._codes_cache: Dict[tuple, tuple] = {}

    # ------------------------------------------------------------------ load
    @classmethod
    def from_csv(
        cls,
        source: Union[str, bytes, Iterable[str]],
        schema: FeatureSchema,
        delim: str = ",",
        keep_raw: bool = False,
        engine: str = "auto",
    ) -> "Dataset":
        """Parse CSV lines (a path, a text blob, raw bytes, or an iterable
        of lines) into columns. Unknown categorical values raise — the
        schema declares the full cardinality, same contract as the
        reference. A string is treated as a file path if such a file
        exists, otherwise as content (content must contain a newline or the
        delimiter). Bytes are always content — the block-streaming reader
        (core/stream.py) hands file blocks here without a decode copy.

        engine: 'auto' uses the native C++ parser (avenir_tpu/native) when
        built and applicable (path/blob/bytes source, single-char delimiter,
        no keep_raw), 'native' requires it, 'python' forces the row parser."""
        if engine not in ("auto", "native", "python"):
            raise ValueError(f"unknown CSV engine {engine!r} "
                             "(want auto, native, or python)")
        if isinstance(source, (bytes, bytearray)):
            native_ok = not keep_raw and len(delim.encode()) == 1
            if engine in ("auto", "native") and native_ok:
                ds = cls._from_native_data(bytes(source), schema, delim,
                                           required=engine == "native")
                if ds is not None:
                    return ds
            if engine == "native":
                raise ValueError(
                    "engine='native' requires a single-byte delimiter and "
                    "keep_raw=False")
            source = io.StringIO(bytes(source).decode())
        native_ok = (not keep_raw and isinstance(source, str)
                     and len(delim.encode()) == 1)
        if engine == "native" and not native_ok:
            raise ValueError(
                "engine='native' requires a path/blob source, a single-byte "
                "delimiter, and keep_raw=False")
        if engine in ("auto", "native") and native_ok:
            ds = cls._from_csv_native(source, schema, delim,
                                      required=engine == "native")
            if ds is not None:
                return ds
        if isinstance(source, str):
            if os.path.exists(source):
                lines: Iterable[str] = open(source, "r")
            elif "\n" in source or delim in source:
                lines = io.StringIO(source)
            elif source == "":
                lines = io.StringIO("")
            else:
                raise FileNotFoundError(f"no such CSV file: {source!r}")
        else:
            lines = source

        rows: List[List[str]] = []
        for line in lines:
            line = line.rstrip("\n").rstrip("\r")
            if not line.strip():
                continue
            rows.append([tok.strip() for tok in line.split(delim)])
        if hasattr(lines, "close") and lines is not source:
            lines.close()
        return cls.from_rows(rows, schema, keep_raw=keep_raw)

    @classmethod
    def _from_csv_native(cls, source: str, schema: FeatureSchema,
                         delim: str, required: bool) -> Optional["Dataset"]:
        """Native one-pass columnar parse of a path/blob source; None when
        unavailable (caller falls through to the Python parser)."""
        if os.path.exists(source):
            with open(source, "rb") as fh:
                data = fh.read()
        elif "\n" in source or delim in source or source == "":
            data = source.encode()
        else:
            raise FileNotFoundError(f"no such CSV file: {source!r}")
        return cls._from_native_data(data, schema, delim, required)

    @classmethod
    def _from_native_data(cls, data: bytes, schema: FeatureSchema,
                          delim: str, required: bool) -> Optional["Dataset"]:
        from avenir_tpu.native.ingest import native_available, parse_csv_native

        if not native_available():
            if required:
                raise RuntimeError("native CSV ingest unavailable")
            return None
        numeric = [f.ordinal for f in schema.fields if f.is_numeric]
        # categoricals with a fixed declared vocabulary encode in C; those
        # with an undeclared (data-discovered, growable) vocabulary come
        # back as tokens and encode below
        declared = [f for f in schema.fields if f.is_categorical
                    and f.cardinality and not f.discovered_cardinality]
        undeclared = [f for f in schema.fields if f.is_categorical
                      and (not f.cardinality or f.discovered_cardinality)]
        categorical = [(f.ordinal, f.cardinality) for f in declared]
        strings = [f.ordinal for f in schema.fields
                   if not f.is_numeric and not f.is_categorical]
        strings += [f.ordinal for f in undeclared]
        try:
            n, columns, lazy = parse_csv_native(data, delim, numeric,
                                                categorical, strings,
                                                lazy_strings=True)
            for fld in undeclared:
                # discovery needs the tokens now; materialize eagerly
                toks = lazy.pop(fld.ordinal)()
                _discover_cardinality(fld, toks.tolist())
                index = fld.cardinality_index()
                columns[fld.ordinal] = np.array(
                    [index[t] for t in toks], dtype=np.int32)
        except ValueError as e:
            # align cardinality errors with the Python parser (field name);
            # other ValueErrors (e.g. invalid numerics) pass through as-is
            msg = str(e)
            if " not in declared cardinality" in msg:
                for fld in schema.fields:
                    if msg.endswith(f"ordinal {fld.ordinal}") or \
                            f"ordinal {fld.ordinal} " in msg:
                        raise ValueError(
                            msg.split(" not in ")[0]
                            + f" not in declared cardinality of field "
                            f"{fld.name!r}") from None
            raise
        for fld in schema.fields:
            if fld.is_numeric and fld.ordinal in columns:
                _discover_numeric_range(fld, columns[fld.ordinal])
        return cls(schema, columns, n, lazy=lazy)

    @classmethod
    def from_rows(
        cls,
        rows: List[List[str]],
        schema: FeatureSchema,
        keep_raw: bool = False,
    ) -> "Dataset":
        n = len(rows)
        columns: Dict[int, np.ndarray] = {}
        for fld in schema.fields:
            o = fld.ordinal
            toks = [r[o] if o < len(r) else "" for r in rows]
            if fld.is_categorical:
                _discover_cardinality(fld, toks)
                index = fld.cardinality_index()
                try:
                    columns[o] = np.array([index[t] for t in toks], dtype=np.int32)
                except KeyError as e:
                    raise ValueError(
                        f"value {e.args[0]!r} not in declared cardinality of "
                        f"field {fld.name!r}"
                    ) from None
            elif fld.is_numeric:
                dt = np.float32
                columns[o] = np.array(
                    [float(t) if t != "" else np.nan for t in toks], dtype=dt
                )
                _discover_numeric_range(fld, columns[o])
            else:  # string / text / id: host-side object column
                columns[o] = np.array(toks, dtype=object)
        return cls(schema, columns, n, raw_rows=rows if keep_raw else None)

    # ----------------------------------------------------------------- views
    def column(self, ordinal: int) -> np.ndarray:
        if ordinal not in self.columns and ordinal in self._lazy:
            self.columns[ordinal] = self._lazy.pop(ordinal)()
        return self.columns[ordinal]

    def ids(self) -> np.ndarray:
        idf = self.schema.id_field
        if idf is None:
            return np.array([str(i) for i in range(self.n_rows)], dtype=object)
        return self.column(idf.ordinal)

    def labels(self) -> np.ndarray:
        """Encoded class attribute codes, int32 [n]."""
        cf = self.schema.class_field
        if cf is None:
            raise ValueError("schema has no class attribute")
        col = self.column(cf.ordinal)
        if col.dtype == object:  # class field declared as plain string
            index = cf.cardinality_index()
            return np.array([index[v] for v in col], dtype=np.int32)
        return col.astype(np.int32)

    def feature_codes(
        self, fields: Optional[Sequence[FeatureField]] = None
    ) -> Tuple[np.ndarray, List[int]]:
        """Dense per-feature states.

        Returns (codes int32 [n, F], bins list[F]) over the dense-encodable
        feature fields (categoricals + bucketized numerics), in ordinal order.
        Numeric features without bucketWidth are skipped (they have no dense
        state; the Gaussian path of NB handles them from feature_matrix()).
        """
        if fields is None:
            fields = [f for f in self.schema.feature_fields if f.num_bins() > 0]
        # keyed on (ordinal, bins) so a vocabulary discovered AFTER a
        # cached call (growing num_bins) misses instead of serving codes
        # stacked against the stale bin count
        memo_key = tuple((f.ordinal, f.num_bins()) for f in fields)
        hit = self._codes_cache.get(memo_key)
        if hit is not None:
            return hit[0], list(hit[1])
        cols = []
        bins = []
        for fld in fields:
            nb = fld.num_bins()
            if nb <= 0:
                continue
            col = self.column(fld.ordinal)
            if fld.is_categorical:
                # copy=False: the stack below copies; an int32 column
                # (the native parse and replay norm) need not copy twice
                cols.append(col.astype(np.int32, copy=False))
            else:
                if np.isnan(col).any():
                    raise ValueError(
                        f"missing value in bucketized numeric field {fld.name!r} "
                        "(empty tokens cannot be dense-encoded)"
                    )
                lo = fld.min if fld.min is not None else 0.0
                code = np.floor((col - lo) / fld.bucket_width).astype(np.int32)
                cols.append(np.clip(code, 0, nb - 1))
            bins.append(nb)
        codes = (np.stack(cols, axis=1) if cols
                 else np.zeros((self.n_rows, 0), dtype=np.int32))
        # the cached matrix is SHARED across callers (a SharedScan chunk
        # feeds several consumers): freeze it so an in-place write in
        # one fused job raises instead of corrupting every other's codes
        codes.setflags(write=False)
        self._codes_cache[memo_key] = (codes, tuple(bins))
        return codes, bins

    def feature_matrix(
        self, fields: Optional[Sequence[FeatureField]] = None
    ) -> np.ndarray:
        """float32 [n, D] of numeric feature values (raw, unbinned)."""
        if fields is None:
            fields = [f for f in self.schema.feature_fields if f.is_numeric]
        cols = [self.column(f.ordinal).astype(np.float32, copy=False)
                for f in fields]
        if not cols:
            return np.zeros((self.n_rows, 0), dtype=np.float32)
        return np.stack(cols, axis=1)

    def numeric_feature_fields(self) -> List[FeatureField]:
        return [f for f in self.schema.feature_fields if f.is_numeric]

    def encodable_feature_fields(self) -> List[FeatureField]:
        return [f for f in self.schema.feature_fields if f.num_bins() > 0]

    # ------------------------------------------------------------- utilities
    def to_csv(self, delim: str = ",") -> str:
        """Render rows back to reference-style CSV text (categorical codes
        decoded to their cardinality values). Uses raw rows when kept."""
        if self.raw_rows is not None:
            return "\n".join(delim.join(r) for r in self.raw_rows) + "\n"
        # tokens land at their declared ordinals; gaps (fields present in
        # the file but undeclared in the schema, e.g. call_hangup's area
        # code) become empty tokens so the row re-parses against the schema
        width = max(f.ordinal for f in self.schema.fields) + 1
        lines = []
        for i in range(self.n_rows):
            toks = [""] * width
            for fld in self.schema.fields:
                col = self.column(fld.ordinal)
                if fld.is_categorical:
                    tok = fld.decode_value(int(col[i]))
                elif fld.is_numeric:
                    v = float(col[i])
                    # NaN is the documented missing-value sentinel from both
                    # parsers; render it (and inf) back as an empty token
                    tok = ("" if not np.isfinite(v)
                           else str(int(v)) if v == int(v) else f"{v:.6g}")
                else:
                    tok = str(col[i])
                toks[fld.ordinal] = tok
            lines.append(delim.join(toks))
        return "\n".join(lines) + "\n"

    def take(self, idx: np.ndarray) -> "Dataset":
        """Row subset (numpy fancy index) — used by samplers and CV splits."""
        # lazy columns stay lazy: compose the subset onto the thunk so a
        # sampler over an id-bearing dataset still never materializes ids
        # unless someone reads them
        sub_idx = np.asarray(idx)
        lazy = {o: (lambda o=o: self.column(o)[sub_idx])
                for o in self._lazy}
        cols = {o: c[idx] for o, c in self.columns.items()}
        raw = [self.raw_rows[i] for i in idx] if self.raw_rows is not None else None
        return Dataset(self.schema, cols, int(sub_idx.shape[0]), raw,
                       lazy=lazy)

    def __len__(self) -> int:
        return self.n_rows

    def __repr__(self) -> str:
        return f"Dataset(n={self.n_rows}, fields={len(self.schema)})"


def _discover_cardinality(fld, tokens) -> None:
    """Categorical fields may ship without a declared cardinality (e.g.
    `status` in the reference's elearnActivity.json rich schema) — the
    value set is then discovered from the data, sorted for determinism,
    and recorded on the (shared) schema field so later splits parsed
    against the same schema object encode consistently; unseen values in
    later splits extend the vocabulary instead of raising."""
    if fld.cardinality:
        if fld.discovered_cardinality:
            known = set(fld.cardinality)
            new = sorted({t for t in tokens} - known)
            if new:
                fld.cardinality.extend(new)
        return
    fld.cardinality = sorted({t for t in tokens})
    fld.discovered_cardinality = True


def _discover_numeric_range(fld, col: np.ndarray) -> None:
    """Numeric fields with bucketWidth but no declared max (the
    reference's hosp_readmit.json style — the Java jobs bin by
    floor(value/width) with data-determined extent): record the observed
    max on the (shared) schema field so num_bins() covers every seen
    code. The max only grows across chunks/splits, so earlier codes stay
    valid and streaming count accumulators just pad the bin axis."""
    if not fld.bucket_width or (fld.max is not None
                                and not fld.discovered_range):
        return
    finite = col[np.isfinite(col)]
    if finite.size == 0:
        return
    hi = float(finite.max())
    fld.max = hi if fld.max is None else max(fld.max, hi)
    fld.discovered_range = True


def pad_rows(n: int, multiple: int) -> int:
    """Rows padded up to a multiple (device shard divisibility)."""
    return ((n + multiple - 1) // multiple) * multiple


def extract_mixed_features(ds: "Dataset"):
    """Split a dataset into distance-ready arrays: (x_num float32 [n, Dn],
    ranges float32 [Dn], x_cat int32 [n, Dc] | None, cat_bins tuple | None).

    Ranges come from the schema's declared min/max (1.0 fallback) — the
    normalization the mixed-attribute distance metric uses. Shared by KNN
    and clustering. (Relief normalizes per-feature diffs itself with a
    data-derived range fallback — explore.relief_relevance.)"""
    num_fields = [f for f in ds.schema.feature_fields if f.is_numeric]
    cat_fields = [f for f in ds.schema.feature_fields if f.is_categorical]
    x_num = ds.feature_matrix(num_fields)
    ranges = np.array(
        [
            (f.max - f.min) if (f.max is not None and f.min is not None) else 1.0
            for f in num_fields
        ],
        dtype=np.float32,
    )
    if cat_fields:
        x_cat = np.stack(
            [ds.column(f.ordinal).astype(np.int32) for f in cat_fields], axis=1
        )
        bins = tuple(len(f.cardinality) for f in cat_fields)
    else:
        x_cat, bins = None, None
    return x_num, ranges, x_cat, bins
