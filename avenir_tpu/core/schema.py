"""FeatureSchema: dataset metadata compatible with the reference JSON format.

The reference consumes per-dataset JSON schemas (e.g. resource/churn.json,
resource/call_hangup.json) through chombo's FeatureSchema/FeatureField; every
job resolves column ordinals, types, roles (id / feature / class attribute),
categorical cardinalities and numeric binning hints from it (reference:
bayesian/BayesianDistribution.java:117-123, tree/SplitManager.java:284-291).

This module parses the *same* JSON files unchanged, and adds what a TPU
pipeline needs on top: stable integer encodings for categorical values
(value -> index within the declared cardinality), bucketizers for numeric
fields, and flat views (feature ordinals, class ordinal) used by the
columnar ingest in avenir_tpu.core.dataset.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence


DATA_TYPE_STRING = "string"
DATA_TYPE_CATEGORICAL = "categorical"
DATA_TYPE_INT = "int"
DATA_TYPE_DOUBLE = "double"
DATA_TYPE_TEXT = "text"

NUMERIC_TYPES = (DATA_TYPE_INT, DATA_TYPE_DOUBLE)


@dataclass
class FeatureField:
    """One column of the dataset.

    Mirrors the attributes of the reference schema JSON: name, ordinal,
    dataType, and the role flags / hints used by the jobs.
    """

    name: str
    ordinal: int
    data_type: str = DATA_TYPE_STRING
    # role flags
    id_field: bool = False
    feature: bool = False
    class_attr: bool = False
    # categorical metadata; discovered_cardinality marks a vocabulary that
    # was inferred from data (undeclared in the schema file) and may grow
    cardinality: List[str] = field(default_factory=list)
    discovered_cardinality: bool = False
    # numeric metadata (binning / split hints); discovered_range marks a
    # max that was inferred from data (undeclared bucketWidth extent, the
    # reference's hosp_readmit.json style) and may grow
    min: Optional[float] = None
    max: Optional[float] = None
    discovered_range: bool = False
    bucket_width: Optional[float] = None
    max_split: Optional[int] = None
    split_scan_interval: Optional[float] = None
    # misc passthrough of unrecognized keys (kept for round-tripping)
    extra: Dict[str, Any] = field(default_factory=dict)

    # ------------------------------------------------------------------ roles
    @property
    def is_categorical(self) -> bool:
        return self.data_type == DATA_TYPE_CATEGORICAL

    @property
    def is_numeric(self) -> bool:
        return self.data_type in NUMERIC_TYPES

    @property
    def is_text(self) -> bool:
        return self.data_type == DATA_TYPE_TEXT

    # --------------------------------------------------------------- encoding
    def cardinality_index(self) -> Dict[str, int]:
        """Stable mapping categorical value -> int code (order of declaration)."""
        return {v: i for i, v in enumerate(self.cardinality)}

    def num_bins(self) -> int:
        """Number of discrete states this field takes after encoding.

        Categorical: declared cardinality. Numeric with bucketWidth: number of
        buckets across [min, max] (the reference bins continuous features the
        same way when building count-based distributions). Other: 0 (not
        encodable to a dense state).
        """
        if self.is_categorical:
            return len(self.cardinality)
        if self.is_numeric and self.bucket_width:
            lo = self.min if self.min is not None else 0.0
            hi = self.max
            if hi is None:
                raise ValueError(
                    f"field {self.name!r}: bucketWidth set but no max bound"
                )
            return int(math.floor((hi - lo) / self.bucket_width)) + 1
        return 0

    def encode_value(self, raw: str) -> int:
        """Encode one raw CSV token to its dense integer state."""
        if self.is_categorical:
            return self.cardinality_index()[raw]
        if self.is_numeric and self.bucket_width:
            lo = self.min if self.min is not None else 0.0
            return int((float(raw) - lo) // self.bucket_width)
        raise ValueError(f"field {self.name!r} is not dense-encodable")

    def decode_value(self, code: int) -> str:
        if self.is_categorical:
            return self.cardinality[code]
        raise ValueError(f"field {self.name!r} is not categorical")

    # ------------------------------------------------------------------- json
    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "FeatureField":
        known = {
            "name",
            "ordinal",
            "dataType",
            "id",
            "feature",
            "classAttribute",
            "cardinality",
            "min",
            "max",
            "bucketWidth",
            "maxSplit",
            "splitScanInterval",
            "discoveredCardinality",
            "discoveredRange",
        }
        return cls(
            name=obj.get("name", f"field{obj.get('ordinal')}"),
            ordinal=int(obj["ordinal"]),
            data_type=obj.get("dataType", DATA_TYPE_STRING),
            id_field=bool(obj.get("id", False)),
            feature=bool(obj.get("feature", False)),
            class_attr=bool(obj.get("classAttribute", False)),
            cardinality=[str(v) for v in obj.get("cardinality", [])],
            discovered_cardinality=bool(obj.get("discoveredCardinality",
                                                False)),
            min=obj.get("min"),
            max=obj.get("max"),
            discovered_range=bool(obj.get("discoveredRange", False)),
            bucket_width=obj.get("bucketWidth"),
            max_split=obj.get("maxSplit"),
            split_scan_interval=obj.get("splitScanInterval"),
            extra={k: v for k, v in obj.items() if k not in known},
        )

    def to_json(self) -> Dict[str, Any]:
        obj: Dict[str, Any] = {"name": self.name, "ordinal": self.ordinal}
        obj["dataType"] = self.data_type
        if self.id_field:
            obj["id"] = True
        if self.feature:
            obj["feature"] = True
        if self.class_attr:
            obj["classAttribute"] = True
        if self.cardinality:
            obj["cardinality"] = list(self.cardinality)
        if self.discovered_cardinality:
            # keeps a data-discovered vocabulary growable after reload
            obj["discoveredCardinality"] = True
        if self.discovered_range:
            # keeps a data-discovered numeric extent growable after reload
            obj["discoveredRange"] = True
        for key, val in (
            ("min", self.min),
            ("max", self.max),
            ("bucketWidth", self.bucket_width),
            ("maxSplit", self.max_split),
            ("splitScanInterval", self.split_scan_interval),
        ):
            if val is not None:
                obj[key] = val
        obj.update(self.extra)
        return obj


class FeatureSchema:
    """The full dataset schema: an ordered list of FeatureFields.

    Convention kept from the reference: when no field carries an explicit
    `classAttribute` flag, the *last* non-feature, non-id categorical field is
    the class attribute (this is how churn.json's `status` field is used by
    the Bayesian jobs even though it carries no explicit role flag).
    """

    def __init__(self, fields: Sequence[FeatureField],
                 dist_algorithm: Optional[str] = None,
                 entity_name: Optional[str] = None):
        self.fields: List[FeatureField] = sorted(fields, key=lambda f: f.ordinal)
        self._by_ordinal = {f.ordinal: f for f in self.fields}
        self._by_name = {f.name: f for f in self.fields}
        self.dist_algorithm = dist_algorithm
        self.entity_name = entity_name

    # --------------------------------------------------------------- loading
    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "FeatureSchema":
        """Accepts both the plain FeatureSchema layout ({"fields": [...]},
        resource/churn.json) and the sifarish rich-attribute wrapper
        ({"distAlgorithm", "entity": {"name", "fields"}},
        resource/elearnActivity.json consumed at knn.sh:46)."""
        if "fields" in obj:
            return cls([FeatureField.from_json(f) for f in obj["fields"]])
        if "entity" in obj:
            ent = obj["entity"]
            return cls([FeatureField.from_json(f) for f in ent["fields"]],
                       dist_algorithm=obj.get("distAlgorithm"),
                       entity_name=ent.get("name"))
        raise ValueError("schema JSON has neither 'fields' nor 'entity'")

    @classmethod
    def from_file(cls, path: str) -> "FeatureSchema":
        with open(path, "r") as fh:
            return cls.from_json(json.load(fh))

    @classmethod
    def from_string(cls, text: str) -> "FeatureSchema":
        return cls.from_json(json.loads(text))

    def to_json(self) -> Dict[str, Any]:
        return {"fields": [f.to_json() for f in self.fields]}

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_json(), fh, indent=2)

    # --------------------------------------------------------------- lookups
    def field_by_ordinal(self, ordinal: int) -> FeatureField:
        return self._by_ordinal[ordinal]

    def field_by_name(self, name: str) -> FeatureField:
        return self._by_name[name]

    @property
    def id_field(self) -> Optional[FeatureField]:
        for f in self.fields:
            if f.id_field:
                return f
        return None

    @property
    def feature_fields(self) -> List[FeatureField]:
        """Fields in the feature role. When no field carries an explicit
        `feature` flag (the sifarish rich schemas, e.g. elearnActivity.json,
        mark only id/class roles), every non-id, non-class field is
        implicitly a feature — the convention SameTypeSimilarity applies."""
        explicit = [f for f in self.fields if f.feature]
        if explicit:
            return explicit
        cf = self.class_field
        cls_ord = cf.ordinal if cf is not None else -1
        return [f for f in self.fields
                if not f.id_field and not f.class_attr
                and f.ordinal != cls_ord]

    @property
    def feature_ordinals(self) -> List[int]:
        return [f.ordinal for f in self.feature_fields]

    @property
    def class_field(self) -> Optional[FeatureField]:
        explicit = [f for f in self.fields if f.class_attr]
        if explicit:
            return explicit[-1]
        # reference convention: trailing categorical non-feature non-id field
        for f in reversed(self.fields):
            if f.is_categorical and not f.feature and not f.id_field:
                return f
        return None

    @property
    def class_ordinal(self) -> int:
        cf = self.class_field
        if cf is None:
            raise ValueError("schema has no class attribute")
        return cf.ordinal

    def num_classes(self) -> int:
        cf = self.class_field
        return len(cf.cardinality) if cf is not None else 0

    def class_values(self) -> List[str]:
        cf = self.class_field
        return list(cf.cardinality) if cf is not None else []

    # per-feature dense state counts (0 for non-encodable e.g. unbinned double)
    def feature_bins(self) -> List[int]:
        return [f.num_bins() for f in self.feature_fields]

    def max_ordinal(self) -> int:
        return self.fields[-1].ordinal if self.fields else -1

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __repr__(self) -> str:
        return f"FeatureSchema({[f.name for f in self.fields]})"
