"""The knob registry: every conf key the autotuner may move, with its
safe range and the telemetry signal that drives it.

The registry is the tuner's whole authority surface — a knob not listed
here can never be written by a policy, and a tuned profile naming an
unknown or out-of-range key fails LOUDLY at load (:func:`validate_knobs`
raises :class:`KnobError`) instead of silently running defaults. That is
the conf-key guard the streaming layer never needed while every key was
hand-typed next to its reader: a tuner writes keys nobody proofreads,
so the registry is where a typo'd ``stream.blokc.size.mb`` dies.

Ranges are SAFETY ranges, not search ranges: chunk invariance (graftlint
--flow, 8/8 byte-identity under adversarial chunkings) proves any value
in range changes only speed, never bytes — which is what lets the
policies be aggressive. The clamp exists so a pathological signal (a
stall storm, a mis-read histogram) can at worst pick a slow
configuration, never an inadmissible one.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Mapping, Union

Number = Union[int, float]


class KnobError(ValueError):
    """A tuned profile (or autotune conf) names an unknown knob key or
    an out-of-range/uncoercible value. Deliberately loud: the silent
    alternative is a typo'd key that "tunes" nothing while the operator
    believes it does."""


@dataclass(frozen=True)
class Knob:
    """One tunable conf key: its type, default, safe range, and the
    telemetry signal the policy engine derives its moves from."""

    key: str
    kind: str                 # "int" | "float"
    default: float
    lo: float
    hi: float
    signal: str               # the driving telemetry, for explain/docs
    description: str

    def coerce(self, value) -> Number:
        """`value` as this knob's type, clamped INTO [lo, hi] is NOT
        done here — validation rejects out-of-range instead (a profile
        holding an out-of-range value was written by a buggy policy or
        by hand; clamping would hide that)."""
        try:
            out = float(value)
        except (TypeError, ValueError) as e:
            raise KnobError(
                f"knob {self.key!r}: value {value!r} is not numeric") from e
        if not self.lo <= out <= self.hi:
            raise KnobError(
                f"knob {self.key!r}: value {out!r} outside the safe "
                f"range [{self.lo:g}, {self.hi:g}]")
        return int(out) if self.kind == "int" else out

    def clamp(self, value: float) -> Number:
        """`value` clamped into the safe range (the POLICY side: every
        chosen move passes through here, so a policy bug can at worst
        pick a slow value, never an invalid one)."""
        out = min(max(float(value), self.lo), self.hi)
        return int(out) if self.kind == "int" else out


#: every key the autotuner may write, by conf key
KNOBS: Dict[str, Knob] = {k.key: k for k in (
    Knob("stream.block.size.mb", "float", 64.0, 1.0, 256.0,
         "stream.read/stream.parse vs per-sink stream.fold span balance, "
         "chunk count, chunk_latency_ms",
         "byte-block size of every streamed scan: larger amortizes "
         "read/parse overhead, smaller gives the producer/consumer "
         "pipeline finer overlap"),
    Knob("stream.prefetch.depth", "int", 2.0, 1.0, 8.0,
         "producer-bound stall share (stream.stall.consumer spans: the "
         "consumer waited on an empty queue)",
         "how many produced chunks may queue ahead of the consumer in "
         "every prefetched() feed"),
    Knob("stream.checkpoint.interval.mb", "float", 256.0, 32.0, 4096.0,
         "job.checkpoint span share of wall clock",
         "bytes folded between incremental fold-state checkpoints: "
         "longer intervals spend less wall on serialization, shorter "
         "ones replay less after a kill"),
    Knob("stream.encoded.cache.budget.mb", "float", 1024.0, 64.0, 8192.0,
         "Cache:EvictedBytes / Cache:SpillBytes counters",
         "byte budget of the miners' encoded-block spill cache: big "
         "enough that per-k replays never re-parse, small enough that "
         "a tenant's spill stays bounded"),
)}

#: autotune CONTROL keys (valid conf surface, never themselves tuned)
CONTROL_KEYS = frozenset({
    "stream.autotune",                      # bool: enable the loop
    "stream.autotune.dir",                  # profile-store directory
    "stream.autotune.batch.balance.ratio",  # server batch-balance band
})


def knob_keys() -> list:
    return sorted(KNOBS)


def knob_defaults() -> Dict[str, Number]:
    return {k.key: (int(k.default) if k.kind == "int" else k.default)
            for k in KNOBS.values()}


def validate_knobs(mapping: Mapping[str, object],
                   source: str = "profile") -> Dict[str, Number]:
    """Validate a {conf key: value} mapping against the registry:
    unknown keys and out-of-range/uncoercible values raise
    :class:`KnobError` naming `source` (the profile path, usually).
    Returns the coerced mapping."""
    out: Dict[str, Number] = {}
    for key in sorted(mapping):
        knob = KNOBS.get(key)
        if knob is None:
            raise KnobError(
                f"{source}: unknown knob key {key!r}; tunable keys: "
                f"{', '.join(knob_keys())}")
        out[key] = knob.coerce(mapping[key])
    return out


def format_value(key: str, value: Number) -> str:
    """The .properties string form of a knob value (what the runner
    splices into a JobConfig): ints bare, floats via %g so a tuned
    profile round-trips through the flat string props unchanged."""
    knob = KNOBS[key]
    return str(int(value)) if knob.kind == "int" else f"{float(value):g}"
