"""`python -m avenir_tpu tune <dir>` — inspect and explain autotune
decisions.

Renders every profile under an autotune directory (the
``.avenir_tune/`` next to a corpus, or a ``stream.autotune.dir``): the
chosen knobs with the policy reasons that picked them, the latest run's
signal balance, the fold-cost mean the server's batch balancer reads,
and the residual-correction factor admission would apply — so an
operator can see WHY the tuner moved a knob without re-deriving it
from raw traces.
"""

from __future__ import annotations

import json
from typing import Dict, List

from avenir_tpu.tune.knobs import KNOBS
from avenir_tpu.tune.policy import residual_factor
from avenir_tpu.tune.store import ProfileStore


def profile_row(prof: Dict) -> Dict:
    """One profile's JSON summary row (pure function of the dict, so
    tests pin the rendering without a filesystem)."""
    runs = prof.get("runs") or []
    latest = runs[-1] if runs else {}
    sig = latest.get("signals") or {}
    residuals = prof.get("residuals") or []
    knobs = dict(prof.get("knobs") or {})
    return {
        "job": prof.get("job"),
        "corpus_digest": prof.get("corpus_digest"),
        "runs": len(runs),
        "knobs": knobs,
        "defaults_moved": sorted(
            k for k, v in knobs.items()
            if float(v) != float(KNOBS[k].default)),
        "reasons": list(prof.get("reasons") or []),
        "fold_cost_ms": prof.get("fold_cost_ms"),
        "residual_records": len(residuals),
        "residual_factor": round(residual_factor(residuals), 3),
        "latest_wall_s": latest.get("wall_s"),
        "latest_signals": sig,
    }


def render_profiles(rows: List[Dict]) -> str:
    lines: List[str] = []
    if not rows:
        return "no autotune profiles found"
    for row in rows:
        lines.append(f"{row['job']}  corpus={row['corpus_digest']}  "
                     f"runs={row['runs']}  "
                     f"residual_factor={row['residual_factor']}"
                     + (f"  fold_cost_ms={row['fold_cost_ms']}"
                        if row.get("fold_cost_ms") else ""))
        if row["knobs"]:
            lines.append("  knobs: " + ", ".join(
                f"{k}={v:g}" for k, v in sorted(row["knobs"].items())))
        else:
            lines.append("  knobs: (defaults)")
        for reason in row["reasons"]:
            lines.append(f"    - {reason}")
        sig = row.get("latest_signals") or {}
        if sig:
            lines.append(
                f"  last run: wall={sig.get('wall_s', 0)}s "
                f"read={sig.get('read_s', 0)}s "
                f"parse={sig.get('parse_s', 0)}s "
                f"fold={sig.get('fold_s', 0)}s "
                f"chunks={sig.get('chunks', 0)} "
                f"producer_bound={sig.get('producer_bound_s', 0)}s "
                f"consumer_bound={sig.get('consumer_bound_s', 0)}s")
    return "\n".join(lines)


def tune_main(argv) -> int:
    """CLI body for ``python -m avenir_tpu tune <dir-or-profile>``."""
    import argparse

    ap = argparse.ArgumentParser(prog="avenir_tpu tune")
    ap.add_argument("path", help="autotune directory (.avenir_tune or "
                                 "a stream.autotune.dir)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw profile rows instead of the table")
    args = ap.parse_args(argv)
    try:
        profiles = ProfileStore(args.path).profiles()
    except Exception as e:                          # incl. KnobError: a
        print(f"cannot load autotune profiles from {args.path!r}: {e}")
        return 2                                    # bad profile is loud
    rows = [profile_row(p) for p in profiles]
    print(json.dumps(rows, indent=1) if args.json
          else render_profiles(rows))
    return 0
