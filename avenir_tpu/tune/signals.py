"""Signal extraction: from a window of captured spans to the numbers
the policy engine moves knobs on.

The PR-10 instrumentation already records everything a tuner needs —
per-block ``stream.read``, per-chunk ``stream.parse``, per-sink
``stream.fold``, producer/consumer stall attribution, and the
incremental driver's ``job.checkpoint`` spans. This module is the
read side: given the spans one run emitted (the runner filters the
process-global ring by the run's start time), aggregate them into a
:class:`RunSignals` row — totals, shares and per-sink fold means — that
is JSON-serializable into the profile store, so every policy decision
can be explained later from the recorded inputs.

Stall naming: a ``stream.stall.consumer`` span is recorded when the
CONSUMER waited on an empty queue, i.e. the PRODUCER (disk read /
parse) was the bottleneck — here that time is ``producer_bound_s``.
Dually ``stream.stall.producer`` (producer blocked on a full queue:
the fold side was the bottleneck) becomes ``consumer_bound_s``. The
signals carry the attribution, not the span spelling.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional


@dataclass
class RunSignals:
    """One run's aggregated telemetry (all times in seconds; the
    per-sink fold means in milliseconds per chunk)."""

    wall_s: float = 0.0
    read_s: float = 0.0
    parse_s: float = 0.0
    fold_s: float = 0.0
    producer_bound_s: float = 0.0      # consumer waited on producer
    consumer_bound_s: float = 0.0      # producer waited on consumer
    checkpoint_s: float = 0.0
    chunks: int = 0                    # ingest blocks (read or replayed)
    bytes_read: int = 0
    fold_ms_by_sink: Dict[str, float] = field(default_factory=dict)

    # ------------------------------------------------------------ shares
    @property
    def ingest_s(self) -> float:
        """Producer-side work: disk read + parse."""
        return self.read_s + self.parse_s

    def _share(self, x: float) -> float:
        return x / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def producer_bound_share(self) -> float:
        return self._share(self.producer_bound_s)

    @property
    def consumer_bound_share(self) -> float:
        return self._share(self.consumer_bound_s)

    @property
    def checkpoint_share(self) -> float:
        return self._share(self.checkpoint_s)

    def to_json(self) -> Dict:
        return {"wall_s": round(self.wall_s, 4),
                "read_s": round(self.read_s, 4),
                "parse_s": round(self.parse_s, 4),
                "fold_s": round(self.fold_s, 4),
                "producer_bound_s": round(self.producer_bound_s, 4),
                "consumer_bound_s": round(self.consumer_bound_s, 4),
                "checkpoint_s": round(self.checkpoint_s, 4),
                "chunks": int(self.chunks),
                "bytes_read": int(self.bytes_read),
                "fold_ms_by_sink": {k: round(v, 3) for k, v
                                    in sorted(self.fold_ms_by_sink.items())}}

    @classmethod
    def from_json(cls, d: Dict) -> "RunSignals":
        return cls(wall_s=float(d.get("wall_s", 0.0)),
                   read_s=float(d.get("read_s", 0.0)),
                   parse_s=float(d.get("parse_s", 0.0)),
                   fold_s=float(d.get("fold_s", 0.0)),
                   producer_bound_s=float(d.get("producer_bound_s", 0.0)),
                   consumer_bound_s=float(d.get("consumer_bound_s", 0.0)),
                   checkpoint_s=float(d.get("checkpoint_s", 0.0)),
                   chunks=int(d.get("chunks", 0)),
                   bytes_read=int(d.get("bytes_read", 0)),
                   fold_ms_by_sink={str(k): float(v) for k, v in
                                    dict(d.get("fold_ms_by_sink",
                                               {})).items()})


def extract_signals(spans: Iterable,
                    wall_s: Optional[float] = None) -> RunSignals:
    """Aggregate a window of :class:`~avenir_tpu.obs.trace.Span` events
    into a :class:`RunSignals` row. `wall_s` is the run's wall clock as
    the caller measured it (the spans alone cannot give it — they may
    overlap across threads); when None it falls back to the span
    extent. Works on whatever subset of spans survived the ring — the
    signals are aggregates, so a truncated window degrades gracefully
    instead of failing."""
    sig = RunSignals()
    fold_n: Dict[str, int] = {}
    fold_t: Dict[str, float] = {}
    t_lo, t_hi = None, None
    for sp in spans:
        if t_lo is None or sp.t0 < t_lo:
            t_lo = sp.t0
        end = sp.t0 + sp.dur
        if t_hi is None or end > t_hi:
            t_hi = end
        if sp.name == "stream.read":
            sig.read_s += sp.dur
            sig.chunks += 1
            if sp.attrs:
                sig.bytes_read += int(sp.attrs.get("nbytes", 0))
        elif sp.name == "stream.parse":
            sig.parse_s += sp.dur
        elif sp.name == "stream.sidecar.replay":
            # a parse-free sidecar replay IS the run's ingest: chunks
            # and ingest seconds must stay visible to the block and
            # prefetch policies on warm scans, or a packed corpus
            # records a signal-less profile and the tuner goes inert
            sig.read_s += sp.dur
            sig.chunks += 1
            if sp.attrs:
                sig.bytes_read += int(sp.attrs.get("nbytes", 0))
        elif sp.name == "stream.fold":
            sig.fold_s += sp.dur
            sink = (sp.attrs or {}).get("sink", "sink")
            fold_n[sink] = fold_n.get(sink, 0) + 1
            fold_t[sink] = fold_t.get(sink, 0.0) + sp.dur
        elif sp.name == "stream.stall.consumer":
            sig.producer_bound_s += sp.dur     # consumer waited: producer slow
        elif sp.name == "stream.stall.producer":
            sig.consumer_bound_s += sp.dur     # producer waited: consumer slow
        elif sp.name == "job.checkpoint":
            sig.checkpoint_s += sp.dur
    sig.fold_ms_by_sink = {sink: 1e3 * fold_t[sink] / fold_n[sink]
                           for sink in fold_t}
    if wall_s is not None:
        sig.wall_s = float(wall_s)
    elif t_lo is not None and t_hi is not None:
        sig.wall_s = max(t_hi - t_lo, 0.0)
    return sig
