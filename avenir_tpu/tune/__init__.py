"""avenir-autotune: close the loop from trace telemetry to streaming
knobs.

PR 10 made the stack measure everything — per-chunk read/parse/fold
spans, producer/consumer stall attribution, queue-wait and
admission-hold histograms, predicted-vs-measured RSS on every streamed
JobResult — and this package is the actuator that reads those signals
and moves the knobs they implicate. Chunk invariance (graftlint --flow,
8/8 byte-identity under adversarial chunkings) means a tuner can NEVER
change results, only speed, so the policies are aggressive by design;
``bench_scaling.autotune_tripwire`` re-proves both halves (tuned beats
static, artifacts byte-identical) every full round.

Four pieces:

- **knob registry** (:mod:`~avenir_tpu.tune.knobs`): every tunable conf
  key with its safe range and driving signal; unknown/out-of-range keys
  in a tuned profile fail LOUDLY (:class:`KnobError`).
- **signal extraction** (:mod:`~avenir_tpu.tune.signals`): captured
  spans -> read/parse/fold totals, stall attribution shares, per-sink
  fold-cost means.
- **policy engine** (:mod:`~avenir_tpu.tune.policy`): deterministic
  signal -> knob-move rules, clamped to the registry ranges; plus the
  residual-corrected admission factor (clamped >= 1.0 so the learned
  correction can never price a request UNDER the validated model) and
  the server's fold-cost batch-balance predicate.
- **profile store** (:mod:`~avenir_tpu.tune.store`): atomic per-(job,
  corpus) JSON profiles — run signals, residual history, fold costs,
  chosen knobs + reasons — consulted by ``runner.run_job``/``run_shared``
  behind the ``stream.autotune`` conf/CLI flag and by the JobServer's
  scheduler/pricer via ``JobServer(autotune_dir=...)``. ``python -m
  avenir_tpu tune <dir>`` renders and explains the decisions.

This module adds the runner-facing glue: :func:`begin_run` (overlay the
stored knobs onto the job configs, hand back a session that records the
run's telemetry and chooses the next knobs) and
:func:`make_tuned_pricer` (the residual-corrected admission oracle).
Everything here is host-side stdlib + obs — no jax at module scope.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Sequence

from avenir_tpu import obs as _obs
from avenir_tpu.tune.knobs import (CONTROL_KEYS, KNOBS, Knob, KnobError,
                                   format_value, knob_defaults, knob_keys,
                                   validate_knobs)
from avenir_tpu.tune.policy import (BATCH_BALANCE_RATIO,
                                    RESIDUAL_FACTOR_CAP, batch_balanced,
                                    choose_knobs, residual_factor)
from avenir_tpu.tune.signals import RunSignals, extract_signals
from avenir_tpu.tune.store import ProfileStore, corpus_digest, resolve_dir

__all__ = [
    "KNOBS", "Knob", "KnobError", "CONTROL_KEYS",
    "knob_keys", "knob_defaults", "validate_knobs", "format_value",
    "RunSignals", "extract_signals",
    "choose_knobs", "residual_factor", "batch_balanced",
    "BATCH_BALANCE_RATIO", "RESIDUAL_FACTOR_CAP",
    "ProfileStore", "corpus_digest", "resolve_dir",
    "begin_run", "record_residual", "make_tuned_pricer",
    "placement_cost_ms",
]


def _effective_knobs(cfg) -> Dict[str, object]:
    """The knob values a run will actually use, read back through the
    config AFTER any overlay — so the recorded ``knobs_used`` reflects
    tuned values, explicit conf keys and defaults alike."""
    out: Dict[str, object] = {}
    for key, knob in KNOBS.items():
        if knob.kind == "int":
            out[key] = int(cfg.get_float(key, knob.default))
        else:
            out[key] = float(cfg.get_float(key, knob.default))
    return out


#: sessions currently between begin_run and finish — when two overlap,
#: the process-global span ring holds BOTH runs' spans, so neither
#: window can be attributed to one corpus; every overlapping session is
#: marked contaminated and skips its signal/knob recording (the run
#: itself, the overlay it already applied, and the residual history are
#: unaffected)
_session_lock = threading.Lock()
_active_sessions: set = set()


class RunSession:
    """One autotuned run: constructed by :func:`begin_run` (which has
    already overlaid the stored knobs onto the configs); ``finish()``
    extracts the run's spans from the process-global recorder, records
    the signal row, and commits the next run's knobs."""

    def __init__(self, store: ProfileStore, profile_job: str, digest: str,
                 canonicals: Sequence[str], knobs_used: Dict,
                 knobs_applied: Dict):
        self.store = store
        self.profile_job = profile_job
        self.digest = digest
        self.canonicals = list(canonicals)
        self.knobs_used = dict(knobs_used)
        self.knobs_applied = dict(knobs_applied)
        self.contaminated = False
        with _session_lock:
            if _active_sessions:
                self.contaminated = True
                for other in _active_sessions:
                    other.contaminated = True
            _active_sessions.add(self)
        self.t0 = _obs.now()

    def close(self) -> None:
        """Abandon the session without recording anything — the
        runner's failure path. MUST be called when the run raises, or
        this session would sit in ``_active_sessions`` forever and mark
        every later session in the process contaminated."""
        with _session_lock:
            _active_sessions.discard(self)

    def finish(self, results: Dict) -> Optional[Dict]:
        """Record the run and choose the next knobs. Advisory end to
        end: any failure here must never fail a job that already ran,
        so errors are swallowed. The knobs committed forward are the
        profile values this run APPLIED plus this round's clamped
        moves — an operator's explicit conf value is never adopted as
        a tuned knob, so set_knobs' validation cannot trip on legal
        conf outside the registry range. Returns the committed knob
        dict, or None when this session was skipped (concurrent
        session contamination) or recording failed."""
        with _session_lock:
            _active_sessions.discard(self)
        if self.contaminated:
            return None
        try:
            wall_s = _obs.now() - self.t0
            spans = [sp for sp in _obs.recorder().spans()
                     if sp.t0 >= self.t0]
            # the session guard only sees other AUTOTUNED sessions; a
            # concurrent UNTUNED streamed job (another server worker)
            # shares the same span ring too. Its fold spans carry its
            # canonical job name as the sink label — any registered
            # stream job folding in this window that is not ours means
            # the window cannot be attributed to this run: skip.
            from avenir_tpu.runner import stream_fold_names

            sinks = {(sp.attrs or {}).get("sink") for sp in spans
                     if sp.name == "stream.fold"}
            if (sinks & set(stream_fold_names())) - set(self.canonicals):
                return None
            sig = extract_signals(spans, wall_s=wall_s)
            counters: Dict[str, float] = {}
            for res in results.values():
                for key, val in getattr(res, "counters", {}).items():
                    counters[key] = max(counters.get(key, 0.0),
                                        float(val))
            moves, reasons = choose_knobs(sig, counters, self.knobs_used)
            chosen = dict(self.knobs_applied)
            chosen.update(moves)
            self.store.record_run(self.profile_job, self.digest,
                                  sig.to_json(), self.knobs_used, wall_s)
            self.store.set_knobs(self.profile_job, self.digest, chosen,
                                 reasons)
            # a fused run's per-sink fold means feed each member job's
            # own profile — the numbers the server's batch balancer
            # compares when composing future batches
            if len(self.canonicals) > 1:
                for canonical in self.canonicals:
                    cost = sig.fold_ms_by_sink.get(canonical)
                    if cost:
                        self.store.note_fold_cost(canonical, self.digest,
                                                  cost)
            _obs.record("tune.decide", _obs.now(), job=self.profile_job,
                        moves=len(reasons))
            return chosen
        except Exception:
            return None


def begin_run(canonicals: Sequence[str], cfgs: Sequence,
              inputs: Sequence[str]) -> RunSession:
    """Start one autotuned run: load the (job, corpus) profile, overlay
    its validated knobs onto EVERY config (fused jobs must agree on the
    scan-shaping keys, so one knob set serves the group), and return
    the session whose ``finish()`` closes the loop.

    Raises :class:`KnobError` when the stored profile names an unknown
    or out-of-range knob — the loud-guard contract; every other storage
    problem degrades to an untuned run."""
    cfg0 = cfgs[0]
    store = ProfileStore(resolve_dir(cfg0, inputs))
    profile_job = "+".join(sorted(canonicals))
    digest = corpus_digest(inputs)
    prof = store.load(profile_job, digest)       # may raise KnobError
    knobs = dict(prof.get("knobs") or {}) if prof else {}
    for cfg in cfgs:
        for key, value in knobs.items():
            pref = f"{cfg.prefix}.{key}" if cfg.prefix else key
            cfg.props[pref] = format_value(key, value)
    return RunSession(store, profile_job, digest, canonicals,
                      _effective_knobs(cfg0), knobs)


def record_residual(canonical: str, cfg, inputs: Sequence[str],
                    predicted: float, measured: float) -> None:
    """Persist one predicted-vs-measured RSS residual into the job's
    profile — called from ``runner._add_mem_counters`` on EVERY
    streamed result (not gated on the autotune flag), so the tuner's
    model-refinement leg has history from day one. Advisory: a store
    that cannot be written (read-only input dir, races) is silently
    skipped."""
    try:
        store = ProfileStore(resolve_dir(cfg, inputs))
        store.record_residual(canonical, corpus_digest(inputs),
                              predicted, measured)
    except Exception:
        return


def make_tuned_pricer(profile_dir: str,
                      base: Optional[Callable] = None) -> Callable:
    """The residual-corrected admission oracle: wraps the analytic
    pricer with the per-(job, corpus) learned correction factor
    (:func:`~avenir_tpu.tune.policy.residual_factor`, clamped into
    [1.0, cap]) — so the correction can RAISE a price whose job
    historically measured over its prediction, and can NEVER lower one
    below the uncorrected model's floor (pinned by a unit test)."""
    if base is None:
        from avenir_tpu.server.jobserver import price_request_bytes
        base = price_request_bytes

    store = ProfileStore(profile_dir)

    def pricer(requests, reserve_bytes: int) -> int:
        raw = base(requests, reserve_bytes)
        factor = 1.0
        try:
            from avenir_tpu.runner import _job_cfg

            for req in requests:
                canonical = _job_cfg(req.job, req.conf)[0]
                try:
                    prof = store.load(canonical,
                                      corpus_digest(req.inputs))
                except KnobError:
                    prof = None          # bad knob entry: the run will
                if prof is None:         # fail loudly on it, not pricing
                    continue
                factor = max(factor, residual_factor(
                    prof.get("residuals") or []))
        except Exception:
            factor = 1.0
        return int(raw * max(factor, 1.0))

    return pricer


def placement_cost_ms(profile_dir: Optional[str], job: str, conf,
                      inputs: Sequence[str]) -> Optional[float]:
    """The measured mean per-chunk fold cost (ms) of one (job, corpus)
    from a profile store — the fleet router's placement weight: a
    corpus whose folds are measured expensive counts for more pending
    load on its host than its bytes alone say. None (and never an
    exception) when there is no store, no profile, or no measurement —
    placement must degrade to bytes-only, not refuse to route."""
    if not profile_dir:
        return None
    try:
        from avenir_tpu.runner import _job_cfg

        canonical = _job_cfg(job, conf)[0]
    except Exception:  # noqa: BLE001 — unresolvable job: bytes-only
        canonical = job
    try:
        return ProfileStore(profile_dir).fold_cost_ms(
            canonical, corpus_digest(inputs))
    except Exception:  # noqa: BLE001 — unreadable store: bytes-only
        return None
