"""The profile store: per-(job, corpus) tuning state on disk.

One JSON file per (job, corpus digest) under the autotune directory
(``stream.autotune.dir`` when configured, else ``.avenir_tune/`` next
to the first input — the incremental driver's state-dir convention),
holding the last N runs' signals, the predicted-vs-measured RSS
residual history, the per-chunk fold-cost mean (the job server's batch
balancer reads it) and the currently chosen knobs with their reasons.

Write protocol is the CheckpointStore's: unique tmp file + ``os.replace``
— a killed writer leaves the previous consistent profile, never a torn
one. Concurrent writers (server workers finishing two requests over one
corpus) last-write-win a whole file; a lost run record costs one
history sample, never a wrong knob (knobs re-derive from whatever
history survives).

Loading VALIDATES the knob mapping against the registry and raises
:class:`~avenir_tpu.tune.knobs.KnobError` on an unknown key or an
out-of-range value — a typo'd key in a hand-edited (or version-skewed)
profile fails the run loudly instead of silently running defaults.
Everything else about a profile is advisory and tolerated loosely.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

from avenir_tpu.core.atomic import publish_json, sweep_stale_tmps
from avenir_tpu.core.keys import corpus_digest  # noqa: F401 — canonical
#                          recipe moved to core.keys; re-exported for
#                          this module's historical importers
from avenir_tpu.tune.knobs import validate_knobs

#: profile-file layout version; a profile stamped with a DIFFERENT
#: version refuses to load (cold start) — old readers must never
#: silently parse a newer layout
FORMAT_VERSION = 1

#: newest run-signal records a profile retains
MAX_RUNS = 16
#: newest residual records a profile retains
MAX_RESIDUALS = 32
#: EWMA blend of a new fold-cost sample into the stored mean
FOLD_COST_BLEND = 0.5

#: default store directory name (next to the first input, like the
#: incremental driver's .avenir_incremental)
DEFAULT_DIR_NAME = ".avenir_tune"


def resolve_dir(cfg, inputs: Sequence[str]) -> str:
    """Where the profile store lives for a job config + input set:
    the ``stream.autotune.dir`` key, else ``.avenir_tune/`` next to the
    first input."""
    explicit = cfg.get("stream.autotune.dir") if cfg is not None else None
    if explicit:
        return explicit
    base = os.path.dirname(os.path.abspath(inputs[0]))
    return os.path.join(base, DEFAULT_DIR_NAME)


def _fresh(job: str, digest: str) -> Dict:
    return {"format": 1, "format_version": FORMAT_VERSION,
            "job": job, "corpus_digest": digest,
            "knobs": {}, "reasons": [], "runs": [], "residuals": [],
            "fold_cost_ms": None}


class ProfileStore:
    """Load/update profiles under one autotune directory.

    Concurrency contract — last-write-wins: profiles are ADVISORY
    measurements (placement weights, knob priors), republished whole
    via atomic tmp+rename. Two hosts recording runs concurrently may
    drop one run's record; the cost is a slightly staler prior, never
    a wrong result, and serializing writers would put a lock on every
    scan's hot path for it."""

    def __init__(self, root: str):
        self.root = root
        # startup GC: tmp files a hard-killed writer left behind (the
        # age gate keeps a concurrent writer's live tmp safe; a root
        # that does not exist yet is a no-op)
        sweep_stale_tmps(root)

    def path(self, job: str, digest: str) -> str:
        return os.path.join(self.root, f"{job}_{digest}.json")

    # --------------------------------------------------------------- io
    def load(self, job: str, digest: str) -> Optional[Dict]:
        """The profile dict, or None when there is none (or what is on
        disk is unparsable — advisory state, cold start over). The knob
        mapping is validated: an unknown/out-of-range knob key raises
        KnobError — loudly, by contract."""
        path = self.path(job, digest)
        try:
            with open(path) as fh:
                prof = json.load(fh)
        except (OSError, ValueError):
            return None
        if not isinstance(prof, dict):
            return None
        if prof.get("format_version", FORMAT_VERSION) != FORMAT_VERSION:
            # version-skewed profile: refuse to serve, go cold (a
            # MISSING stamp is a pre-versioning profile and still
            # loads — upgrading never invalidates on-disk state)
            return None
        prof["knobs"] = validate_knobs(dict(prof.get("knobs") or {}),
                                       source=path)
        return prof

    def _save(self, prof: Dict) -> str:
        os.makedirs(self.root, exist_ok=True)
        path = self.path(prof["job"], prof["corpus_digest"])
        return publish_json(prof, path, site="profile.save", indent=1)

    def _load_or_fresh(self, job: str, digest: str) -> Dict:
        return self.load(job, digest) or _fresh(job, digest)

    # -------------------------------------------------------- mutation
    def record_run(self, job: str, digest: str, signals_json: Dict,
                   knobs_used: Dict, wall_s: float) -> Dict:
        """Append one run's signal record (window-bounded) and fold the
        run's total per-chunk fold cost into the stored mean."""
        prof = self._load_or_fresh(job, digest)
        runs = list(prof.get("runs") or [])
        runs.append({"wall_s": round(float(wall_s), 4),
                     "knobs_used": dict(knobs_used),
                     "signals": dict(signals_json)})
        prof["runs"] = runs[-MAX_RUNS:]
        fold_ms = signals_json.get("fold_ms_by_sink") or {}
        total_ms = sum(float(v) for v in fold_ms.values())
        if total_ms > 0:
            prev = prof.get("fold_cost_ms")
            prof["fold_cost_ms"] = round(
                total_ms if prev is None
                else FOLD_COST_BLEND * total_ms
                + (1.0 - FOLD_COST_BLEND) * float(prev), 3)
        self._save(prof)
        return prof

    def set_knobs(self, job: str, digest: str, knobs: Dict,
                  reasons: List[str]) -> Dict:
        """Commit the knob values the NEXT run over this (job, corpus)
        should use; values are registry-validated before the write so a
        buggy policy can never persist an invalid profile."""
        prof = self._load_or_fresh(job, digest)
        prof["knobs"] = validate_knobs(dict(knobs), source="set_knobs")
        if reasons:
            prof["reasons"] = list(reasons)
        self._save(prof)
        return prof

    def record_residual(self, job: str, digest: str,
                        predicted: float, measured: float) -> Dict:
        """Append one predicted-vs-measured RSS residual record — the
        model-refinement history :func:`~avenir_tpu.tune.policy.
        residual_factor` consumes."""
        prof = self._load_or_fresh(job, digest)
        residuals = list(prof.get("residuals") or [])
        residuals.append({"predicted": int(predicted),
                          "measured": int(measured)})
        prof["residuals"] = residuals[-MAX_RESIDUALS:]
        self._save(prof)
        return prof

    def note_fold_cost(self, job: str, digest: str, cost_ms: float) -> Dict:
        """Blend one per-chunk fold-cost sample into a (solo) job's
        profile — how a fused run's per-sink means reach the profiles
        the server's batch balancer reads."""
        prof = self._load_or_fresh(job, digest)
        prev = prof.get("fold_cost_ms")
        prof["fold_cost_ms"] = round(
            cost_ms if prev is None
            else FOLD_COST_BLEND * float(cost_ms)
            + (1.0 - FOLD_COST_BLEND) * float(prev), 3)
        self._save(prof)
        return prof

    # --------------------------------------------------------- queries
    def profiles(self) -> List[Dict]:
        """Every loadable profile under the root (sorted by file name);
        profiles with invalid knob mappings raise, per the loud-guard
        contract."""
        out: List[Dict] = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            job, _, rest = name[:-5].rpartition("_")
            if not job:
                continue
            prof = self.load(job, rest)
            if prof is not None:
                out.append(prof)
        return out

    def fold_cost_ms(self, job: str, digest: str) -> Optional[float]:
        """The stored mean per-chunk fold cost of one (job, corpus), or
        None when unmeasured. Swallows KnobError: the batch balancer
        must not refuse to schedule because an unrelated knob entry in
        the profile is bad — the run itself will fail loudly on it."""
        from avenir_tpu.tune.knobs import KnobError

        try:
            prof = self.load(job, digest)
        except KnobError:
            return None
        if prof is None:
            return None
        cost = prof.get("fold_cost_ms")
        return float(cost) if cost else None
