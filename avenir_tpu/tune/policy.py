"""The policy engine: deterministic signal -> knob-move rules.

Every rule is a pure function from a :class:`~avenir_tpu.tune.signals.
RunSignals` row (plus the result counters where the signal lives there)
to one knob's next value and a one-line reason. Rules only ever emit
values inside the registry's safe range (:meth:`Knob.clamp`), and chunk
invariance means any emitted value changes speed, never bytes — the
contract that lets these be simple and aggressive rather than hedged.

The rules, each grounded in a measured signal:

- **block size** — aim for enough chunks that the producer/consumer
  pipeline actually overlaps (``TARGET_CHUNKS`` per scan), then shift
  by the measured read-vs-fold balance: a producer-bound scan (ingest
  dominates the folds) wants bigger blocks to amortize per-block
  read/parse overhead; a consumer-bound one wants smaller blocks so the
  producer stays ahead at finer granularity. Snapped to powers of two
  so repeated tuning converges instead of dithering.
- **prefetch depth** — deepen when the producer-bound stall share
  (consumer waiting on an empty queue) dominates: more queued chunks
  absorb producer burstiness. Step back toward the default when stalls
  say the consumer is the bottleneck (queued chunks then only hold
  memory, bought for nothing).
- **checkpoint interval** — lengthen when ``job.checkpoint`` time
  exceeds its wall-clock budget share; the cost of a longer interval is
  replay after a kill, which is why it only ever doubles (never jumps).
- **encoded cache budget** — raise to cover the measured spill when the
  miners' cache evicted under pressure (an evicted source re-parses
  CSV on every later pass-k — the exact cost the cache exists to kill).
"""

from __future__ import annotations

import math
from typing import Dict, List, Mapping, Optional, Tuple

from avenir_tpu.tune.knobs import KNOBS, Number
from avenir_tpu.tune.signals import RunSignals

#: chunk-count ceiling per scan: few enough that per-chunk overhead is
#: noise, many enough that the depth-2 pipeline overlaps and the tail
#: (first/last chunk with no overlap partner) is a small fraction
TARGET_CHUNKS = 24
#: floor on the measured scan work one chunk should carry: cutting a
#: corpus finer than this buys no overlap (the per-chunk fold dispatch
#: and parse-call overhead is then comparable to the chunk's work), so
#: small corpora keep big blocks — the chunk target is
#: min(TARGET_CHUNKS, measured work / this)
MIN_CHUNK_WORK_SECS = 0.25
#: read-vs-fold imbalance ratio past which the block size shifts
BALANCE_RATIO = 1.5
#: stall share of wall clock past which prefetch depth moves
STALL_SHARE = 0.10
#: wall-clock share budget for checkpoint serialization
CHECKPOINT_BUDGET_SHARE = 0.05
#: headroom multiplier when re-sizing the cache budget over its spill
CACHE_HEADROOM = 1.5

Move = Tuple[Optional[Number], Optional[str]]


def _pow2_mb(mb: float) -> float:
    """Snap to the nearest power of two (in MB) so successive tuning
    rounds land on the same grid instead of dithering around it."""
    return float(2.0 ** round(math.log2(max(mb, 1e-6))))


def choose_block_mb(sig: RunSignals, current: float) -> Move:
    """(next stream.block.size.mb, reason) — None when the signals
    give no grounds to move."""
    knob = KNOBS["stream.block.size.mb"]
    if sig.bytes_read <= 0 or sig.chunks <= 0:
        return None, None
    # chunk target bounded by the MEASURED work: a scan worth 12s of
    # ingest+fold overlaps nicely at 24 chunks, but a 0.2s one pays
    # more per-chunk overhead than it could ever overlap away — small
    # corpora therefore converge to one whole-corpus block
    work_s = sig.ingest_s + sig.fold_s
    chunk_target = max(1, min(TARGET_CHUNKS,
                              int(work_s / MIN_CHUNK_WORK_SECS)))
    target = sig.bytes_read / float(chunk_target) / (1 << 20)
    why = (f"{sig.chunks} chunks over {sig.bytes_read >> 20}MB, "
           f"targeting {chunk_target}")
    if chunk_target >= 4:
        # the read-vs-fold balance shift only means something when the
        # scan is big enough to pipeline at all
        if sig.fold_s > 0 and sig.ingest_s > BALANCE_RATIO * sig.fold_s:
            target *= 2.0
            why += (f"; producer-bound (ingest {sig.ingest_s:.2f}s vs "
                    f"fold {sig.fold_s:.2f}s): bigger blocks amortize "
                    f"read/parse")
        elif sig.ingest_s > 0 and sig.fold_s > BALANCE_RATIO * sig.ingest_s:
            target *= 0.5
            why += (f"; consumer-bound (fold {sig.fold_s:.2f}s vs ingest "
                    f"{sig.ingest_s:.2f}s): smaller blocks overlap finer")
    chosen = knob.clamp(_pow2_mb(target))
    if chosen == float(current):
        return None, None
    return chosen, f"block {current:g}->{chosen:g}MB ({why})"


def choose_prefetch_depth(sig: RunSignals, current: int) -> Move:
    """(next stream.prefetch.depth, reason): deepen when the consumer
    measurably waited on the producer, shallow back toward the default
    when the producer waited on the consumer (queued depth then buys
    nothing but resident blocks)."""
    knob = KNOBS["stream.prefetch.depth"]
    cur = int(knob.clamp(current))
    if sig.producer_bound_share >= STALL_SHARE:
        chosen = int(knob.clamp(cur * 2))
        if chosen != cur:
            return chosen, (
                f"prefetch {cur}->{chosen}: producer-bound stalls were "
                f"{100 * sig.producer_bound_share:.0f}% of wall")
        return None, None
    if (sig.consumer_bound_share >= STALL_SHARE
            and cur > int(knob.default)):
        chosen = int(knob.clamp(max(cur // 2, int(knob.default))))
        return chosen, (
            f"prefetch {cur}->{chosen}: consumer-bound stalls were "
            f"{100 * sig.consumer_bound_share:.0f}% of wall — extra "
            f"depth only held memory")
    return None, None


def choose_checkpoint_interval_mb(sig: RunSignals, current: float) -> Move:
    """(next stream.checkpoint.interval.mb, reason): double the
    interval while serialization exceeds its wall share budget."""
    knob = KNOBS["stream.checkpoint.interval.mb"]
    if sig.checkpoint_share <= CHECKPOINT_BUDGET_SHARE:
        return None, None
    chosen = knob.clamp(float(current) * 2.0)
    if chosen <= float(current):
        return None, None
    return chosen, (
        f"checkpoint interval {current:g}->{chosen:g}MB: "
        f"serialization was {100 * sig.checkpoint_share:.0f}% of wall "
        f"(budget {100 * CHECKPOINT_BUDGET_SHARE:.0f}%)")


def choose_cache_budget_mb(counters: Mapping[str, float],
                           current: float) -> Move:
    """(next stream.encoded.cache.budget.mb, reason): grow the budget
    over the measured spill when the cache evicted under pressure."""
    knob = KNOBS["stream.encoded.cache.budget.mb"]
    evicted = float(counters.get("Cache:EvictedBytes", 0.0) or 0.0)
    spill = float(counters.get("Cache:SpillBytes", 0.0) or 0.0)
    if evicted <= 0 or spill <= 0:
        return None, None
    want = knob.clamp(_pow2_mb(CACHE_HEADROOM * spill / (1 << 20)))
    if want <= knob.clamp(current):
        return None, None
    return want, (
        f"cache budget {current:g}->{want:g}MB: "
        f"{int(evicted) >> 20}MB evicted under a {int(spill) >> 20}MB "
        f"spill — evicted sources re-parse CSV every pass-k")


def choose_knobs(sig: RunSignals, counters: Mapping[str, float],
                 current: Mapping[str, Number]
                 ) -> Tuple[Dict[str, Number], List[str]]:
    """Run every rule against one run's signals; returns ONLY this
    round's moves (each clamped into its registry range) and their
    human-readable reasons. `current` holds the values the run actually
    used — the rules' reference point, whether those came from the
    profile, an explicit conf key or the defaults. Carrying earlier
    rounds' knobs forward is the SESSION's job (it merges moves over
    the values it applied from the profile): adopting an arbitrary
    conf value here would persist operator conf as a \"tuned\" knob —
    including legal values outside the registry range, which the store
    would then loudly (and wrongly) refuse."""
    chosen: Dict[str, Number] = {}
    reasons: List[str] = []
    defaults = {k: v.default for k, v in KNOBS.items()}
    moves = (
        ("stream.block.size.mb",
         choose_block_mb(sig, float(current.get(
             "stream.block.size.mb", defaults["stream.block.size.mb"])))),
        ("stream.prefetch.depth",
         choose_prefetch_depth(sig, int(current.get(
             "stream.prefetch.depth",
             defaults["stream.prefetch.depth"])))),
        ("stream.checkpoint.interval.mb",
         choose_checkpoint_interval_mb(sig, float(current.get(
             "stream.checkpoint.interval.mb",
             defaults["stream.checkpoint.interval.mb"])))),
        ("stream.encoded.cache.budget.mb",
         choose_cache_budget_mb(counters, float(current.get(
             "stream.encoded.cache.budget.mb",
             defaults["stream.encoded.cache.budget.mb"])))),
    )
    for key, (value, reason) in moves:
        if value is not None:
            chosen[key] = value
            reasons.append(reason)
    return chosen, reasons


# --------------------------------------------------------------------------
# admission-model residual correction
# --------------------------------------------------------------------------
#: ceiling on the learned correction factor — matches the mem auditor's
#: non-vacuity bound (a model needing more than this is broken, and an
#: unbounded factor would let one wild RSS reading price everything out)
RESIDUAL_FACTOR_CAP = 8.0
#: how many newest residual records inform the factor
RESIDUAL_WINDOW = 8


def residual_factor(residuals, cap: float = RESIDUAL_FACTOR_CAP) -> float:
    """Learned per-job correction of the analytic footprint model from
    its recorded predicted-vs-measured residuals: the WORST (largest)
    measured/predicted ratio over the newest window, clamped into
    [1.0, cap].

    The 1.0 floor is the admission-safety clause: a job that measured
    UNDER its prediction never lowers its price below the uncorrected
    model — the validated model stays the admission floor, and the
    correction can only make admission more conservative (a unit test
    pins this). The cap keeps one pathological sample (a sticky-RSS
    reading in a long process) from pricing every future request out of
    the budget."""
    worst = 1.0
    recent = list(residuals)[-RESIDUAL_WINDOW:]
    for rec in recent:
        try:
            predicted = float(rec["predicted"])
            measured = float(rec["measured"])
        except (KeyError, TypeError, ValueError):
            continue
        if predicted > 0 and measured > 0:
            worst = max(worst, measured / predicted)
    return min(max(worst, 1.0), float(cap))


# --------------------------------------------------------------------------
# server batch composition
# --------------------------------------------------------------------------
#: default width of the fold-cost band one batch may span
BATCH_BALANCE_RATIO = 4.0


def batch_balanced(batch_costs_ms, candidate_cost_ms: Optional[float],
                   ratio: float = BATCH_BALANCE_RATIO) -> bool:
    """True when adding a sink with `candidate_cost_ms` mean per-chunk
    fold cost keeps the batch's costs within a `ratio` band (max/min).

    A shared scan's chunk latency is the SUM of its sinks' folds, so a
    batch mixing a microsecond fold with a second-long one makes the
    cheap job's chunks wait on the expensive one for no ingest saving
    it could notice — the scheduler stops the compatible prefix there
    instead. Unknown costs (no profile yet) always balance: the tuner
    must never make the server refuse work it simply hasn't measured."""
    if candidate_cost_ms is None:
        return True
    known = [c for c in batch_costs_ms if c is not None and c > 0]
    if not known or candidate_cost_ms <= 0:
        return True
    lo = min(known + [candidate_cost_ms])
    hi = max(known + [candidate_cost_ms])
    return hi <= ratio * lo
