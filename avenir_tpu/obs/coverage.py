"""Span-coverage auditor: instrumentation that can never silently rot.

Tracing is only trustworthy if every streamed job actually emits it —
an instrumentation point lost in a refactor fails no unit test (the
artifacts are unchanged) and quietly blinds the profiling the ROADMAP's
straggler/tuning work depends on. This auditor closes that hole the
same way the chunk-invariance and merge auditors close theirs: drive
every registered stream entry (analysis/manifest.stream_entries — the
REAL runner jobs over their real corpora) under a captured recorder and
assert the MANDATORY span set showed up:

- ``stream.read``  — a raw byte block left the disk (core.stream);
- ``stream.parse`` — a block became typed data (CSV chunk parse, native
  sequence/transaction encode);
- ``stream.fold``  — a sink/device fold consumed a chunk;
- ``job.finish``   — the job sealed its fold and wrote the artifact.

``bench_scaling.graftlint_tripwire`` gates this 8/8 every round next to
the invariance/footprint/merge legs; a deliberately de-instrumented
fold (tests/test_obs.py) must fail it.
"""

from __future__ import annotations

import shutil
import tempfile
from collections import Counter
from typing import List, Optional, Sequence

from avenir_tpu.obs import trace

#: the span names every stream entry must emit at least once
MANDATORY_SPANS = ("stream.read", "stream.parse", "stream.fold",
                   "job.finish")


class SpanCoverageError(RuntimeError):
    """A stream entry failed to RUN under the coverage auditor (distinct
    from running fine but emitting no spans, which is a finding row)."""


def audit_entry(spec, layout_mb: Optional[float] = None) -> dict:
    """Run one stream entry under a fresh captured recorder and report
    its mandatory-span coverage row."""
    workdir = tempfile.mkdtemp(prefix=f"obs_coverage_{spec.name}_")
    try:
        ctx = spec.prepare(workdir)
        if layout_mb is None:
            # a mid-sized layout: small enough to chunk the tiny audit
            # corpus (so per-chunk spans must repeat), big enough not to
            # crawl
            layout_mb = (spec.layouts[1] if len(spec.layouts) > 1
                         else spec.layouts[0])
        with trace.capture() as rec:
            spec.run(ctx, layout_mb)
        spans = rec.spans()
    except Exception as e:
        raise SpanCoverageError(
            f"{spec.name}: stream entry failed to run under the span "
            f"auditor: {e!r}") from e
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    names = Counter(sp.name for sp in spans)
    missing = [n for n in MANDATORY_SPANS if names.get(n, 0) < 1]
    return {"kernel": spec.name,
            "layout_mb": float(layout_mb),
            "span_counts": {n: names.get(n, 0) for n in MANDATORY_SPANS},
            "total_spans": len(spans),
            "missing": missing,
            "span_coverage_validated": not missing}


def audit_span_coverage(entries: Optional[Sequence] = None) -> List[dict]:
    """Coverage rows for every registered stream entry (or the given
    subset). Callers gate on ``span_coverage_validated`` per row."""
    if entries is None:
        from avenir_tpu.analysis.manifest import stream_entries

        entries = stream_entries()
    return [audit_entry(spec) for spec in entries]
