"""Span flight recorder: a bounded, thread-safe ring of timing events.

The recorder is process-global and always on (module docstring of
:mod:`avenir_tpu.obs` has the overhead contract). A span is a host-side
wall-clock interval: ``t0``/``dur`` are ``time.perf_counter`` seconds,
``tid`` the recording thread, ``attrs`` a small dict of primitives.
Device work dispatches asynchronously, so a span around a jitted fold
measures dispatch+host time, not device occupancy — the per-chunk
read/parse/fold attribution the streaming stack needs lives entirely on
the host timeline anyway.

Export is Chrome-trace JSON (the ``traceEvents`` complete-event form:
``ph:"X"`` with microsecond ``ts``/``dur``), loadable by Perfetto and
chrome://tracing; ``tools/trace_report.py`` rolls the same file into a
per-phase table.

Memory bound: the ring keeps the NEWEST ``capacity`` spans (overflow
drops the oldest and counts them in ``dropped``) — a resident server
can trace forever in O(capacity).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, Iterator, List, NamedTuple, Optional

#: default ring capacity (spans); ~100 bytes each -> a few MB bound
DEFAULT_CAPACITY = 65_536

#: shortest producer/consumer stall worth a span (seconds) — queue
#: handoffs complete in microseconds; recording every one would be
#: noise, not attribution
STALL_MIN_SECS = 1e-3


class Span(NamedTuple):
    name: str
    tid: int
    t0: float
    dur: float
    attrs: Optional[Dict]


class SpanRecorder:
    """Thread-safe ring buffer of :class:`Span` events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._buf: List[Span] = []
        self._n = 0                      # total spans ever recorded

    def record(self, name: str, t0: float, dur: float,
               tid: Optional[int] = None,
               attrs: Optional[Dict] = None) -> None:
        sp = Span(name, tid if tid is not None else threading.get_ident(),
                  t0, dur, attrs)
        with self._lock:
            if self._n < self.capacity:
                self._buf.append(sp)
            else:
                self._buf[self._n % self.capacity] = sp
            self._n += 1

    @property
    def dropped(self) -> int:
        """Spans the ring overwrote (oldest-first) since the last clear."""
        with self._lock:
            return max(self._n - self.capacity, 0)

    def __len__(self) -> int:
        with self._lock:
            return min(self._n, self.capacity)

    def spans(self) -> List[Span]:
        """Retained spans, oldest to newest."""
        with self._lock:
            if self._n <= self.capacity:
                return list(self._buf)
            head = self._n % self.capacity
            return self._buf[head:] + self._buf[:head]

    def clear(self) -> None:
        with self._lock:
            self._buf = []
            self._n = 0

    def chrome_events(self) -> List[Dict]:
        """The retained spans as Chrome-trace complete events (``ph:X``,
        microsecond ``ts``/``dur`` on the perf_counter timeline)."""
        pid = os.getpid()
        return [{"name": sp.name, "cat": "avenir", "ph": "X",
                 "ts": sp.t0 * 1e6, "dur": sp.dur * 1e6,
                 "pid": pid, "tid": sp.tid,
                 "args": sp.attrs or {}}
                for sp in self.spans()]

    def export_chrome(self, path: str) -> str:
        """Write the Chrome-trace JSON file (atomic tmp+rename; open it
        in Perfetto / chrome://tracing). Returns `path`."""
        doc = {"traceEvents": self.chrome_events(),
               "displayTimeUnit": "ms",
               "metadata": {"dropped_spans": self.dropped}}
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)
        return path


# --------------------------------------------------------------------------
# module-global surface (what the instrumentation points call)
# --------------------------------------------------------------------------
_ENABLED = os.environ.get("AVENIR_TRACE", "1") not in ("0", "false", "off")
_recorder = SpanRecorder()

now = time.perf_counter


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> bool:
    """Toggle recording; returns the previous state. The bench overhead
    tripwire uses this for its ON/OFF A/B; production leaves it on."""
    global _ENABLED
    prev, _ENABLED = _ENABLED, bool(on)
    return prev


def recorder() -> SpanRecorder:
    return _recorder


def record(name: str, t0: float, **attrs) -> None:
    """Record a span that began at `t0` (from :func:`now`) and ends now.
    One flag load when disabled — cheap enough for per-chunk call sites."""
    if not _ENABLED:
        return
    _recorder.record(name, t0, time.perf_counter() - t0,
                     attrs=attrs or None)


def record_min(name: str, t0: float, min_dur: float = STALL_MIN_SECS,
               **attrs) -> None:
    """Record the span only when it lasted at least `min_dur` seconds —
    the stall-attribution call sites use this so instantaneous queue
    handoffs don't flood the ring."""
    if not _ENABLED:
        return
    dur = time.perf_counter() - t0
    if dur >= min_dur:
        _recorder.record(name, t0, dur, attrs=attrs or None)


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[None]:
    """Context-manager span around a region (exception-safe: the span
    records however the block exits)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record(name, t0, **attrs)


@contextlib.contextmanager
def capture(capacity: int = DEFAULT_CAPACITY) -> Iterator[SpanRecorder]:
    """Swap in a FRESH recorder (and force tracing on) for the duration
    — the span-coverage auditor and tests capture one run's spans in
    isolation this way — then restore the previous recorder and flag."""
    global _recorder
    fresh = SpanRecorder(capacity)
    prev_rec, _recorder = _recorder, fresh
    prev_on = set_enabled(True)
    try:
        yield fresh
    finally:
        _recorder = prev_rec
        set_enabled(prev_on)


# --------------------------------------------------------------------------
# process-global streaming histograms
# --------------------------------------------------------------------------
_hist_lock = threading.Lock()
_hists: Dict[str, "object"] = {}


def observe(name: str, value: float) -> None:
    """Fold one sample into the process-global histogram `name` (created
    on first use) — the always-on aggregate view next to the span ring
    (e.g. ``chunk_latency_ms`` fed by SharedScan)."""
    if not _ENABLED:
        return
    from avenir_tpu.obs.histogram import LatencyHistogram

    with _hist_lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = LatencyHistogram()
        h.add(value)


def hist(name: str):
    """A merged COPY of the process-global histogram `name` (None when
    nothing observed it yet) — a copy, so callers can merge/mutate
    without racing the live accumulator."""
    from avenir_tpu.obs.histogram import LatencyHistogram

    with _hist_lock:
        h = _hists.get(name)
        return None if h is None else LatencyHistogram().merge(h)


def hist_summaries() -> Dict[str, Dict[str, float]]:
    """{name: summary} of every process-global histogram."""
    with _hist_lock:
        return {name: h.summary() for name, h in sorted(_hists.items())}


def reset_hists() -> None:
    with _hist_lock:
        _hists.clear()
