"""Streaming latency histograms: fixed log-spaced buckets, exact merge.

The job server used to surface latency as one scalar per result
(``Server:QueueWaitMs``) — no distribution, no tail. This accumulator
is the RunningStats of latencies: counts and per-bucket sums are
additive, so ``merge`` is associative/commutative and per-worker (or
per-shard) histograms combine exactly, the same algebra every fold
state in the repo already obeys.

Bucket layout is a module constant (quarter-octave geometric spacing:
~19% relative resolution over [1e-6, ~1.1e9)), so any two histograms
merge without negotiation. Quantiles return the MEAN of the selected
bucket's samples — an estimator bounded by the bucket's ~19% width, and
EXACT whenever the bucket holds one distinct value (which is how the
tests pin it on known inputs). ``min``/``max``/``mean`` are always
exact.

Units are the caller's (the server feeds milliseconds); values <= the
lowest edge clamp into bucket 0 and stay exact through its bucket sum.
"""

from __future__ import annotations

import math
from bisect import bisect_right
from typing import Dict, List

#: lowest bucket edge and geometric spacing factor (2**0.25 per bucket)
_LO = 1e-6
_FACTOR = 2.0 ** 0.25
_N_BUCKETS = 200
#: upper edges of buckets 0..N-2 (bucket i holds values in
#: [_EDGES[i-1], _EDGES[i]) — bisect_right places a value equal to an
#: edge in the NEXT bucket; the last bucket is open-ended)
_EDGES = tuple(_LO * _FACTOR ** (i + 1) for i in range(_N_BUCKETS - 1))


class LatencyHistogram:
    """Mergeable log-bucketed accumulator (module docstring)."""

    __slots__ = ("counts", "sums", "count", "total", "min_val", "max_val")

    def __init__(self):
        self.counts: List[int] = [0] * _N_BUCKETS
        self.sums: List[float] = [0.0] * _N_BUCKETS
        self.count = 0
        self.total = 0.0
        self.min_val = math.inf
        self.max_val = -math.inf

    def add(self, value: float) -> "LatencyHistogram":
        v = float(value)
        i = bisect_right(_EDGES, v) if v > _LO else 0
        self.counts[i] += 1
        self.sums[i] += v
        self.count += 1
        self.total += v
        if v < self.min_val:
            self.min_val = v
        if v > self.max_val:
            self.max_val = v
        return self

    def add_many(self, values) -> "LatencyHistogram":
        for v in values:
            self.add(v)
        return self

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold `other` into self (additive — associative and
        commutative, the shard-merge algebra)."""
        for i, c in enumerate(other.counts):
            if c:
                self.counts[i] += c
                self.sums[i] += other.sums[i]
        self.count += other.count
        self.total += other.total
        self.min_val = min(self.min_val, other.min_val)
        self.max_val = max(self.max_val, other.max_val)
        return self

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantile(self, p: float) -> float:
        """Value at percentile `p` in [0, 100]: the mean of the bucket
        containing the rank-``ceil(p/100 * count)`` sample (0.0 on an
        empty histogram; p=0 returns the exact min)."""
        if not 0.0 <= p <= 100.0:
            raise ValueError(f"percentile must be in [0, 100], got {p}")
        if self.count == 0:
            return 0.0
        if p == 0.0:
            return self.min_val
        rank = min(max(int(math.ceil(p / 100.0 * self.count)), 1),
                   self.count)
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                return self.sums[i] / c
        return self.max_val          # unreachable; counts sum to count

    def summary(self) -> Dict[str, float]:
        """The quantile row every surface prints (stats(), metrics.json,
        trace_report): count/mean/min/max plus p50/p95/p99."""
        if self.count == 0:
            return {"count": 0, "mean": 0.0, "min": 0.0, "max": 0.0,
                    "p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {"count": self.count,
                "mean": round(self.mean, 6),
                "min": round(self.min_val, 6),
                "max": round(self.max_val, 6),
                "p50": round(self.quantile(50), 6),
                "p95": round(self.quantile(95), 6),
                "p99": round(self.quantile(99), 6)}

    def to_dict(self) -> Dict:
        """JSON-serializable sparse form (non-empty buckets only)."""
        return {"buckets": {str(i): [self.counts[i], self.sums[i]]
                            for i in range(_N_BUCKETS) if self.counts[i]},
                "count": self.count, "total": self.total,
                "min": None if self.count == 0 else self.min_val,
                "max": None if self.count == 0 else self.max_val}

    @classmethod
    def from_dict(cls, d: Dict) -> "LatencyHistogram":
        h = cls()
        for key, (c, s) in d.get("buckets", {}).items():
            h.counts[int(key)] = int(c)
            h.sums[int(key)] = float(s)
        h.count = int(d.get("count", 0))
        h.total = float(d.get("total", 0.0))
        if d.get("min") is not None:
            h.min_val = float(d["min"])
        if d.get("max") is not None:
            h.max_val = float(d["max"])
        return h
