"""`python -m avenir_tpu stats <dir>` — render a live metrics snapshot.

The resident job server atomically renames a ``metrics.json`` snapshot
next to its spool every few seconds (jobserver.JobServer, the
``metrics_path`` surface); this renderer is the operator's one-command
view of it: queue depths, admission pressure, warm-store occupancy and
the latency histograms (queue wait / admission hold / dispatch /
chunk), without attaching to the server process. Accepts the snapshot
file or the directory holding it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List


def load_metrics(path: str) -> Dict:
    """The snapshot dict at `path` (a metrics.json, or a directory —
    e.g. the spool dir — containing one)."""
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.json")
    with open(path) as fh:
        return json.load(fh)


def _fmt_bytes(n: float) -> str:
    return f"{n / (1 << 20):.1f}MB"


def _hist_rows(hists: Dict[str, Dict]) -> List[str]:
    lines = [f"  {'histogram':<22s} {'count':>7s} {'p50':>9s} "
             f"{'p95':>9s} {'p99':>9s} {'max':>9s}"]
    for name, h in sorted(hists.items()):
        lines.append(
            f"  {name:<22s} {int(h.get('count', 0)):>7d} "
            f"{h.get('p50', 0.0):>9.2f} {h.get('p95', 0.0):>9.2f} "
            f"{h.get('p99', 0.0):>9.2f} {h.get('max', 0.0):>9.2f}")
    return lines


def render_metrics(snap: Dict) -> str:
    """The snapshot as the operator table (pure function of the dict,
    so tests pin the rendering without a filesystem)."""
    lines: List[str] = []
    age = time.time() - snap.get("ts_unix", time.time())
    lines.append(f"avenir job server metrics "
                 f"(snapshot {age:.1f}s old, "
                 f"uptime {snap.get('uptime_s', 0.0):.1f}s)")
    queues = snap.get("queues", {})
    depth = sum(queues.values())
    lines.append(f"queues: {depth} queued across {len(queues)} tenant(s)"
                 + ("" if not queues else "  [" + ", ".join(
                     f"{t}={n}" for t, n in sorted(queues.items())) + "]"))
    infl = snap.get("inflight", {})
    budget = infl.get("budget_bytes", 0) or 1
    lines.append(f"admission: {_fmt_bytes(infl.get('priced_bytes', 0))} "
                 f"priced in flight of {_fmt_bytes(budget)} budget "
                 f"({100.0 * infl.get('priced_bytes', 0) / budget:.1f}%), "
                 f"{infl.get('batches', 0)} batch(es) running, "
                 f"peak {_fmt_bytes(infl.get('peak_priced_bytes', 0))}")
    warm = snap.get("warm", {})
    lines.append(f"warm store: {int(warm.get('pinned_sources', 0))} "
                 f"pinned source(s), {_fmt_bytes(warm.get('pinned_bytes', 0))}"
                 f", hits={int(warm.get('hits', 0))} "
                 f"misses={int(warm.get('misses', 0))}")
    stats = snap.get("stats", {})
    lines.append(f"served: {int(stats.get('served', 0))} "
                 f"(failed {int(stats.get('failed', 0))}), "
                 f"batches={int(stats.get('batches', 0))} "
                 f"coalesced={int(stats.get('coalesced', 0))} "
                 f"holds={int(stats.get('admission_holds', 0))} "
                 f"compile-warm={int(stats.get('compile_warm_dispatches', 0))}"
                 f" warm-hits={int(stats.get('warm_hits', 0))}")
    hists = snap.get("hists", {})
    if hists:
        lines.append("latency histograms (ms):")
        lines.extend(_hist_rows(hists))
    return "\n".join(lines)


def stats_main(argv) -> int:
    """CLI body for ``python -m avenir_tpu stats <dir-or-file>``."""
    import argparse

    ap = argparse.ArgumentParser(prog="avenir_tpu stats")
    ap.add_argument("path", help="metrics.json, or the directory "
                                 "(e.g. the spool dir) containing it")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw snapshot JSON instead of the table")
    args = ap.parse_args(argv)
    try:
        snap = load_metrics(args.path)
    except (OSError, ValueError) as e:
        print(f"cannot load metrics snapshot from {args.path!r}: {e}")
        return 2
    print(json.dumps(snap, indent=1) if args.json else render_metrics(snap))
    return 0
