"""`python -m avenir_tpu stats <paths...>` — render metrics snapshots.

The resident job server atomically renames a ``metrics.json`` snapshot
next to its spool every few seconds (jobserver.JobServer, the
``metrics_path`` surface); this renderer is the operator's one-command
view of it: queue depths, admission pressure, warm-store occupancy and
the latency histograms (queue wait / admission hold / dispatch /
chunk), without attaching to the server process. Accepts snapshot
files, directories holding one, or a FLEET root (``host*/metrics.json``
underneath); given several snapshots it renders the MERGED view —
counters summed, histograms folded through the additive
``LatencyHistogram.merge`` algebra over the snapshots' sparse
``hists_raw`` buckets, so a fleet's p99 is computed from the combined
distribution, never averaged from per-host summaries.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, List


def load_metrics(path: str) -> Dict:
    """The snapshot dict at `path` (a metrics.json, or a directory —
    e.g. the spool dir — containing one)."""
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.json")
    with open(path) as fh:
        return json.load(fh)


def expand_metrics_paths(paths: List[str]) -> List[str]:
    """Every metrics.json the CLI arguments name: a file stays itself;
    a directory with a metrics.json contributes it; a directory with
    ``host*/metrics.json`` underneath (a fleet root) contributes every
    host's — so ``stats <fleet-root>`` sees the whole fleet."""
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            own = os.path.join(path, "metrics.json")
            hosts = sorted(glob.glob(
                os.path.join(path, "host*", "metrics.json")))
            if hosts:
                # the per-host truth beats the (possibly stale) rolled-
                # up fleet file when both exist under a fleet root
                out.extend(hosts)
            elif os.path.exists(own):
                out.append(own)
            else:
                raise OSError(
                    f"no metrics.json (or host*/metrics.json) under "
                    f"{path!r}")
        else:
            out.append(path)
    return out


def merge_snapshots(snaps: List[Dict]) -> Dict:
    """Fold N metrics snapshots into one fleet view. Counters, queue
    depths and warm/inflight occupancy are additive and sum; the
    latency histograms merge EXACTLY through each snapshot's sparse
    ``hists_raw`` buckets (``LatencyHistogram.merge`` — the same
    algebra every fold state in the repo obeys). A snapshot predating
    the raw surface contributes its counters but no distribution;
    ``peak_priced_bytes`` sums to the fleet-wide upper bound (per-host
    peaks need not be simultaneous)."""
    from avenir_tpu.obs.histogram import LatencyHistogram

    out: Dict = {"hosts": len(snaps), "ts_unix": 0.0, "uptime_s": 0.0,
                 "queues": {}, "inflight": {}, "warm": {}, "stats": {},
                 "hists": {}, "hists_raw": {}, "score": None,
                 "draining": False,
                 "trace": {"spans": 0, "dropped_spans": 0,
                           "enabled": False}}
    merged: Dict[str, LatencyHistogram] = {}
    for snap in snaps:
        out["ts_unix"] = max(out["ts_unix"], snap.get("ts_unix", 0.0))
        out["uptime_s"] = max(out["uptime_s"], snap.get("uptime_s", 0.0))
        out["draining"] = out["draining"] or bool(snap.get("draining"))
        for tenant, n in (snap.get("queues") or {}).items():
            out["queues"][tenant] = out["queues"].get(tenant, 0) + int(n)
        for section in ("inflight", "warm", "stats"):
            for key, val in (snap.get(section) or {}).items():
                if isinstance(val, (int, float)):
                    out[section][key] = out[section].get(key, 0) + val
        trace = snap.get("trace") or {}
        out["trace"]["spans"] += int(trace.get("spans", 0))
        out["trace"]["dropped_spans"] += int(trace.get("dropped_spans",
                                                       0))
        out["trace"]["enabled"] = out["trace"]["enabled"] \
            or bool(trace.get("enabled"))
        for name, raw in (snap.get("hists_raw") or {}).items():
            merged.setdefault(name, LatencyHistogram()).merge(
                LatencyHistogram.from_dict(raw))
        # score-plane roll-up: counters and per-model dispatch counts
        # are additive across hosts (the per-model latency hists
        # already merge above through hists_raw)
        score = snap.get("score")
        if score:
            if out["score"] is None:
                out["score"] = {"stats": {}, "per_model_predicts": {},
                                "cache": {}}
            for section in ("stats", "per_model_predicts", "cache"):
                for key, val in (score.get(section) or {}).items():
                    if isinstance(val, (int, float)):
                        bucket = out["score"][section]
                        bucket[key] = bucket.get(key, 0) + val
    out["hists"] = {name: h.summary() for name, h in merged.items()}
    out["hists_raw"] = {name: h.to_dict() for name, h in merged.items()}
    return out


def _fmt_bytes(n: float) -> str:
    return f"{n / (1 << 20):.1f}MB"


def _hist_rows(hists: Dict[str, Dict]) -> List[str]:
    lines = [f"  {'histogram':<22s} {'count':>7s} {'p50':>9s} "
             f"{'p95':>9s} {'p99':>9s} {'max':>9s}"]
    for name, h in sorted(hists.items()):
        lines.append(
            f"  {name:<22s} {int(h.get('count', 0)):>7d} "
            f"{h.get('p50', 0.0):>9.2f} {h.get('p95', 0.0):>9.2f} "
            f"{h.get('p99', 0.0):>9.2f} {h.get('max', 0.0):>9.2f}")
    return lines


def render_metrics(snap: Dict) -> str:
    """The snapshot as the operator table (pure function of the dict,
    so tests pin the rendering without a filesystem)."""
    lines: List[str] = []
    age = time.time() - snap.get("ts_unix", time.time())
    hosts = int(snap.get("hosts", 1))
    what = f"fleet metrics ({hosts} hosts merged, " if hosts > 1 \
        else "job server metrics (snapshot "
    lines.append(f"avenir {what}{age:.1f}s old, "
                 f"uptime {snap.get('uptime_s', 0.0):.1f}s"
                 + (", DRAINING)" if snap.get("draining") else ")"))
    router = snap.get("router")
    if router:
        rs = router.get("stats", {})
        lines.append(
            f"router: {rs.get('placed', 0)} placed, "
            f"hits={rs.get('affinity_hits', 0)} "
            f"misses={rs.get('affinity_misses', 0)} "
            f"spills={rs.get('spills', 0)} held={rs.get('held', 0)} "
            f"across {len(router.get('hosts', []))} host(s)")
    queues = snap.get("queues", {})
    depth = sum(queues.values())
    lines.append(f"queues: {depth} queued across {len(queues)} tenant(s)"
                 + ("" if not queues else "  [" + ", ".join(
                     f"{t}={n}" for t, n in sorted(queues.items())) + "]"))
    infl = snap.get("inflight", {})
    budget = infl.get("budget_bytes", 0) or 1
    lines.append(f"admission: {_fmt_bytes(infl.get('priced_bytes', 0))} "
                 f"priced in flight of {_fmt_bytes(budget)} budget "
                 f"({100.0 * infl.get('priced_bytes', 0) / budget:.1f}%), "
                 f"{infl.get('batches', 0)} batch(es) running, "
                 f"peak {_fmt_bytes(infl.get('peak_priced_bytes', 0))}")
    warm = snap.get("warm", {})
    lines.append(f"warm store: {int(warm.get('pinned_sources', 0))} "
                 f"pinned source(s), {_fmt_bytes(warm.get('pinned_bytes', 0))}"
                 f", hits={int(warm.get('hits', 0))} "
                 f"misses={int(warm.get('misses', 0))}")
    stats = snap.get("stats", {})
    lines.append(f"served: {int(stats.get('served', 0))} "
                 f"(failed {int(stats.get('failed', 0))}), "
                 f"batches={int(stats.get('batches', 0))} "
                 f"coalesced={int(stats.get('coalesced', 0))} "
                 f"holds={int(stats.get('admission_holds', 0))} "
                 f"compile-warm={int(stats.get('compile_warm_dispatches', 0))}"
                 f" warm-hits={int(stats.get('warm_hits', 0))}")
    hists = snap.get("hists", {})
    if hists:
        lines.append("latency histograms (ms):")
        lines.extend(_hist_rows(hists))
    return "\n".join(lines)


def stats_main(argv) -> int:
    """CLI body for ``python -m avenir_tpu stats <paths...>`` — one
    snapshot renders as-is; several (or a fleet root) render the
    additive-merged fleet view."""
    import argparse

    ap = argparse.ArgumentParser(prog="avenir_tpu stats")
    ap.add_argument("paths", nargs="+",
                    help="metrics.json file(s), directories containing "
                         "one, or a fleet root (host*/metrics.json)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw snapshot JSON instead of the table")
    args = ap.parse_args(argv)
    try:
        files = expand_metrics_paths(args.paths)
        snaps = [load_metrics(p) for p in files]
    except (OSError, ValueError) as e:
        print(f"cannot load metrics snapshot(s) from {args.paths}: {e}")
        return 2
    snap = snaps[0] if len(snaps) == 1 else merge_snapshots(snaps)
    # a fleet root's own rolled-up file carries the router section;
    # surface it next to the host counters whenever the arguments
    # named a fleet root — a 1-host fleet is still a fleet
    if "router" not in snap:
        for path in args.paths:
            own = os.path.join(path, "metrics.json") \
                if os.path.isdir(path) else path
            try:
                with open(own) as fh:
                    router = json.load(fh).get("router")
            except (OSError, ValueError):
                continue
            if router:
                snap["router"] = router
                break
    print(json.dumps(snap, indent=1) if args.json else render_metrics(snap))
    return 0
