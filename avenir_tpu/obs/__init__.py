"""avenir-trace: the always-on, low-overhead telemetry subsystem.

Three pieces, all host-side and stdlib-pure (imported by core.stream at
package init, so nothing here may import jax/numpy at module scope):

- **Span flight recorder** (:mod:`avenir_tpu.obs.trace`): a thread-safe
  ring buffer of ``(name, tid, t0, dur, attrs)`` span events with
  bounded memory and Chrome-trace/Perfetto JSON export. Instrumentation
  points live in core/stream (per-chunk read/parse/fold spans plus
  producer/consumer stall attribution), runner (per-job phase spans for
  the solo, shared, incremental and fused-incremental paths) and
  server/jobserver (per-request queued/held/dispatch spans with batch
  linkage attrs).
- **Streaming histograms** (:mod:`avenir_tpu.obs.histogram`): fixed
  log-spaced bucket accumulators that merge like ``RunningStats``
  (counts and sums are additive, so ``merge`` is associative and
  shard/worker results combine exactly); quantiles come from per-bucket
  means, so they are exact whenever a bucket holds one distinct value.
- **Span-coverage auditor** (:mod:`avenir_tpu.obs.coverage`): runs every
  registered stream entry (analysis/manifest.stream_entries) and
  asserts it emits the mandatory span set (read/parse/fold/finish) —
  instrumentation can never silently rot; gated 8/8 by
  ``bench_scaling.graftlint_tripwire``.

Overhead contract: ``bench_scaling.obs_tripwire`` asserts a fused
10M-row proxy run with tracing ON stays within 3% of the wall clock
with tracing OFF, with byte-identical artifacts. Tracing is ON by
default (``AVENIR_TRACE=0`` or :func:`set_enabled` turns it off); every
record call is one enabled-flag load away from free when off.
"""

# the submodule is named ``histogram`` (not ``hist``) on purpose: a
# submodule named ``hist`` would shadow the ``obs.hist(name)`` accessor
# __all__ advertises below
from avenir_tpu.obs.histogram import LatencyHistogram
from avenir_tpu.obs.trace import (Span, SpanRecorder, capture, enabled,
                                  hist, hist_summaries, now, observe,
                                  record, record_min, recorder, reset_hists,
                                  set_enabled, span)

__all__ = [
    "Span", "SpanRecorder", "LatencyHistogram",
    "capture", "enabled", "set_enabled", "recorder",
    "now", "record", "record_min", "span",
    "observe", "hist", "hist_summaries", "reset_hists",
]
