"""Keyed reductions: the Hadoop shuffle/combiner/reducer collapsed to one op.

Every counting job in the reference (Bayesian distributions, mutual
information, Markov transition counts, Apriori supports, correlation
contingency tables) is "emit (key tuple) -> 1 or (1, x, x^2); shuffle; sum".
With schema-declared cardinalities every key is a dense integer, so the whole
shuffle collapses to `jax.ops.segment_sum` on device — and to a `lax.psum`
over the mesh's data axis when row shards live on different chips
(see avenir_tpu.parallel.mesh.sharded_sum).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp


def keyed_reduce(
    keys: jax.Array,
    values: Optional[jax.Array],
    num_keys: int,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Sum `values` (or 1s) per integer key.

    keys: int array [n]; values: [n] or [n, d] or None (count mode);
    weights: optional [n] multiplier (e.g. record validity mask).
    Returns [num_keys] or [num_keys, d].
    """
    if values is None:
        values = jnp.ones(keys.shape[0], dtype=jnp.float32)
    if weights is not None:
        values = values * (weights if values.ndim == 1 else weights[:, None])
    return jax.ops.segment_sum(values, keys, num_segments=num_keys)


def combine_codes(codes: Sequence[jax.Array], bins: Sequence[int]) -> jax.Array:
    """Flatten a tuple of dense codes into one mixed-radix key.

    The reference shuffles on composite Tuple keys (classVal, featureOrd,
    bin); with static cardinalities the same composite key is
    `((c0 * b1) + c1) * b2 + c2 ...` — a single int32 keyspace of size
    prod(bins) that segment_sum can index directly.
    """
    assert len(codes) == len(bins) and len(codes) >= 1
    key = codes[0].astype(jnp.int32)
    for c, b in zip(codes[1:], bins[1:]):
        key = key * b + c.astype(jnp.int32)
    return key


def one_hot_count(
    codes: jax.Array,
    num_bins: int,
    weights: Optional[jax.Array] = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Histogram via one-hot matmul — MXU-friendly for wide batch counting.

    codes: [n] or [n, F] int; returns [num_bins] or [F, num_bins].
    For [n, F] inputs this is a single (F x n) @ (n x bins) style contraction
    realized as one_hot + sum, which XLA lowers to an MXU matmul — the fast
    path for counting many features at once (vs. F separate segment_sums).
    """
    oh = jax.nn.one_hot(codes, num_bins, dtype=dtype)   # [..., num_bins]
    if weights is not None:
        oh = oh * (weights[:, None] if codes.ndim == 1 else weights[:, None, None])
    return jnp.sum(oh, axis=0)          # [num_bins] or [F, num_bins]


def cross_count(
    row_codes: jax.Array,
    col_codes: jax.Array,
    num_rows: int,
    num_cols: int,
    weights: Optional[jax.Array] = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Contingency table count[i, j] = #(row_codes==i & col_codes==j).

    Realized as one_hot(rows).T @ one_hot(cols) — a dense matmul on the MXU.
    This is the workhorse for class-conditional feature distributions,
    Cramér correlation, mutual information and Markov bigram counting.
    """
    r = jax.nn.one_hot(row_codes, num_rows, dtype=dtype)    # [n, R]
    c = jax.nn.one_hot(col_codes, num_cols, dtype=dtype)    # [n, C]
    if weights is not None:
        r = r * weights[:, None]
    return r.T @ c


def moment_reduce(
    keys: jax.Array,
    x: jax.Array,
    num_keys: int,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-key (count, sum, sum-of-squares) — the continuous-feature triple
    the reference emits for Gaussian stats (BayesianDistribution mapper emits
    (1, x, x^2) per record). Returns [num_keys, 3]."""
    ones = jnp.ones_like(x)
    trip = jnp.stack([ones, x, x * x], axis=-1)             # [n, 3]
    if weights is not None:
        trip = trip * weights[:, None]
    return jax.ops.segment_sum(trip, keys, num_segments=num_keys)


def rowmap(fn, *arrays):
    """vmap over the leading (row) axis — the per-record mapper."""
    return jax.vmap(fn)(*arrays)
