"""Compute core: the TPU-native replacement for Hadoop shuffle semantics.

Primitive vocabulary (SURVEY §2.12 mapping):
- rowmap          : vmap'd per-record kernel        (parallel mappers)
- keyed_reduce    : segment_sum over dense keys      (shuffle + combiner + reducer)
- topk_by_group   : per-group ranked selection       (secondary sort)
- allpairs_distance: blocked pairwise distances      (sifarish SameTypeSimilarity)
- infotheory      : entropy / gini / MI algebra      (InfoContentStat et al.)
- bitset          : packed popcount containment      (Apriori/GSP support counts)
"""

from avenir_tpu.ops.reduce import keyed_reduce, combine_codes, one_hot_count
from avenir_tpu.ops.distance import pairwise_distance, blocked_topk_neighbors
from avenir_tpu.ops.infotheory import entropy, gini, bits_entropy
from avenir_tpu.ops.bitset import (bitset_contain_counts, bitset_contain_mask,
                                   pack_rows_u32, pack_index_rows_u32)
