"""Bit-packed set containment: the streamed miners' counting kernel.

The Apriori / GSP streaming path is N-proportional in exactly one place:
"does transaction t contain candidate c" evaluated for every (row,
candidate) pair of every chunk. The dense formulation — uint8 multi-hot
rows against a float32 candidate matrix, `(T @ C.T) == k` — pays 8x the
memory it needs per block (one byte per vocabulary bit) and recompiles
per candidate length because k is a static argument.

Here transaction rows are packed 32 vocabulary bits per uint32 word
(`pack_rows_u32`), and containment runs as a popcount fold over the words:

    overlap[b, c] = sum_w popcount(trans[b, w] & cand[c, w])
    contained     = overlap == popcount-weight(cand[c])

The candidate weight is computed in-kernel, so ONE compiled executable
counts candidates of every itemset length — a whole mining round (and the
final transaction-id pass over kept sets of ALL lengths) batches into a
single fused [C_total, W] candidate matrix per chunk. Blocks shrink ~8x
(uint32 bitset vs uint8 multi-hot), which is what keeps the 100M-row
streamed Apriori inside its RSS budget. `jnp`-portable: population_count
lowers to the VPU on TPU and to vectorized code on CPU.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

WORD_BITS = 32


def words_for(n_bits: int) -> int:
    """uint32 words needed for n_bits vocabulary bits (>= 1: zero-width
    arrays would force a separate compiled shape for the empty edge)."""
    return max((max(n_bits, 0) + WORD_BITS - 1) // WORD_BITS, 1)


def pack_rows_u32(multihot: np.ndarray) -> np.ndarray:
    """uint8/bool multi-hot [N, V] -> uint32 bitset [N, words_for(V)].

    Bit b of word w holds vocabulary column w*32 + b (little-endian bit
    order); packer and candidate encoder must agree, nothing else reads
    the layout."""
    mh = np.ascontiguousarray(multihot, dtype=np.uint8)
    n, v = mh.shape
    w = words_for(v)
    pad_cols = w * WORD_BITS - v
    if pad_cols:
        mh = np.pad(mh, ((0, 0), (0, pad_cols)))
    packed = np.packbits(mh, axis=1, bitorder="little")
    return packed.view(np.uint32).reshape(n, w)


def pack_index_rows_u32(item_rows: Sequence[Sequence[int]], n_bits: int,
                        n_rows: int = 0) -> np.ndarray:
    """Candidate index tuples -> uint32 bitset [max(n_rows, len), W].

    Rows past len(item_rows) stay all-zero (shape-bucket padding); the
    kernel counts zero-weight rows as 0, so padding never counts."""
    rows = max(n_rows, len(item_rows))
    out = np.zeros((rows, words_for(n_bits)), np.uint32)
    for r, items in enumerate(item_rows):
        for i in items:
            out[r, i // WORD_BITS] |= np.uint32(1) << np.uint32(i % WORD_BITS)
    return out


@jax.jit
def _overlap_fold(trans: jnp.ndarray, cand: jnp.ndarray) -> jnp.ndarray:
    """popcount(t & c) summed over words: int32 [B, C].

    A lax.scan over the word axis keeps the live intermediate at [B, C]
    instead of materializing the [B, C, W] AND product."""
    def step(acc, w):
        t_w, c_w = w                                     # [B], [C]
        hit = jax.lax.population_count(t_w[:, None] & c_w[None, :])
        return acc + hit.astype(jnp.int32), None

    init = jnp.zeros((trans.shape[0], cand.shape[0]), jnp.int32)
    acc, _ = jax.lax.scan(step, init, (trans.T, cand.T))
    return acc


@jax.jit
def bitset_contain_counts(trans: jnp.ndarray, cand: jnp.ndarray
                          ) -> jnp.ndarray:
    """counts[c] = #rows of `trans` whose bitset is a superset of cand[c].

    trans uint32 [B, W], cand uint32 [C, W] — candidates of MIXED itemset
    lengths share one call (the weight is computed per candidate, not
    passed statically). All-zero candidate rows (shape padding) count 0."""
    weight = jnp.sum(
        jax.lax.population_count(cand).astype(jnp.int32), axis=1)   # [C]
    contained = _overlap_fold(trans, cand) == weight[None, :]       # [B, C]
    return jnp.sum(contained & (weight > 0)[None, :], axis=0,
                   dtype=jnp.int32)


@partial(jax.jit, donate_argnums=(0,))
def bitset_fold_counts(acc: jnp.ndarray, trans: jnp.ndarray,
                       cand: jnp.ndarray) -> jnp.ndarray:
    """acc + bitset_contain_counts(trans, cand) with the accumulator
    DONATED: the per-chunk fold carry of the streamed miners. A chunk
    loop re-dispatching this keeps exactly one [C] int32 buffer alive on
    device (the donated input aliases the output) and never round-trips
    the host — counts are exact int32 (bounded by the transaction count,
    < 2^31 at any measured scale), so the fold is chunk-layout-invariant
    by integer associativity."""
    return acc + bitset_contain_counts(trans, cand)


@jax.jit
def bitset_contain_mask(trans: jnp.ndarray, cand: jnp.ndarray
                        ) -> jnp.ndarray:
    """bool [B, C]: row b contains candidate c (zero-weight rows False) —
    the exact-transaction-id pass over kept sets of every length."""
    weight = jnp.sum(
        jax.lax.population_count(cand).astype(jnp.int32), axis=1)
    return (_overlap_fold(trans, cand) == weight[None, :]) & \
        (weight > 0)[None, :]


def packed_block_nbytes(block_rows: int, n_bits: int) -> Tuple[int, int]:
    """(packed, dense) block byte sizes — the ~8x RSS headroom the packed
    path buys; surfaced so benches can report it without re-deriving."""
    return (block_rows * words_for(n_bits) * 4, block_rows * max(n_bits, 1))
