"""Pallas TPU kernel: fused distance tile + streaming top-k for KNN.

The KNN hot loop (SURVEY §7 "hard parts": blocked streaming top-k is the
main genuinely new kernel) spends its time producing an [nq, nt] distance
surface and reducing each row to its k smallest entries. The jnp path
(ops/distance.blocked_topk_neighbors) materializes each [nq, block] tile
through HBM and pays for a full sort-based lax.top_k per block. This kernel
keeps each [BQ, BT] tile entirely in VMEM and replaces the sort with k
iterative min-extractions (k is small — 5-ish — so k VPU passes over the
tile beat a sort), merging into a running [BQ, k] best buffer that lives in
the revisited output block across the train-block grid axis.

Memory: tile is BQ x BT f32 in VMEM (default 256 x 8192 = 8 MB, the
measured sweet spot under the 16 MB scoped-vmem limit), distances never
touch HBM; output is [nq, k] + [nq, k] only.

Numeric-feature metrics only (euclidean via one MXU matmul, manhattan via a
D-pass VPU loop); the mixed categorical path stays on the jnp route.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
_INF = float("inf")


_PACK_BITS = 12                      # low mantissa bits carrying the column
_PACK_MASK = (1 << _PACK_BITS) - 1
# sentinel for masked/empty packed slots: a huge FINITE float (~3.19e38) with
# zero pack bits, so bit-pattern ordering stays monotonic (NaN/inf patterns
# would break int comparisons after bitcast) and decode stays comparable
_SENTINEL = np.int32(0x7F700000)


def _dot_precision(compute_dtype):
    """TPU dot_general defaults to bf16 MXU passes even for f32 operands;
    request HIGHEST so compute_dtype=float32 is genuinely f32 (measured
    ~4e-3 relative distance error otherwise). bfloat16 keeps the native
    single-pass rate."""
    return (jax.lax.Precision.HIGHEST
            if jnp.dtype(compute_dtype) == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _tile_distance(q, t, metric, compute_dtype):
    """[BQ, BT] distance tile (squared sums for euclidean)."""
    if metric == "euclidean":
        # squared distances via one MXU matmul; sqrt deferred to the end.
        # compute_dtype=bfloat16 runs the matmul at the MXU's native rate
        # (f32 accumulate); norms stay f32 so the loss is only in the cross
        # term's 8 mantissa bits.
        qs = jnp.sum(q * q, axis=1)[:, None]
        ts = jnp.sum(t * t, axis=1)[None, :]
        return jnp.maximum(
            qs + ts - 2.0 * jax.lax.dot_general(
                q.astype(compute_dtype), t.astype(compute_dtype),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_dot_precision(compute_dtype)),
            0.0,
        )
    # manhattan: D broadcast passes on the VPU
    tile = jnp.zeros((q.shape[0], t.shape[0]), jnp.float32)
    for f in range(q.shape[1]):
        tile = tile + jnp.abs(q[:, f][:, None] - t[:, f][None, :])
    return tile


def _merge_into_best(best_d_ref, best_i_ref, cand_d, cand_i, k):
    """Fold [BQ, m] candidates into the carried [BQ, k] best buffers via k
    min+argmin rounds on the (small) concatenated array."""
    all_d = jnp.concatenate([best_d_ref[...], cand_d], axis=1)
    all_i = jnp.concatenate([best_i_ref[...], cand_i], axis=1)
    pos = jax.lax.broadcasted_iota(jnp.int32, all_d.shape, 1)
    new_d = []
    new_i = []
    for _ in range(k):
        m = jnp.min(all_d, axis=1)
        am = jnp.argmin(all_d, axis=1).astype(jnp.int32)
        sel = pos == am[:, None]
        # gather the index at the argmin lane via a masked reduction
        picked_i = jnp.sum(jnp.where(sel, all_i, 0), axis=1)
        new_d.append(m)
        new_i.append(picked_i)
        all_d = jnp.where(sel, _INF, all_d)
    best_d_ref[...] = jnp.stack(new_d, axis=1)
    best_i_ref[...] = jnp.stack(new_i, axis=1)


def _knn_kernel(q_ref, t_ref, best_d_ref, best_i_ref, *, k: int,
                metric: str, block_t: int, n_valid: int, nt: int,
                compute_dtype=jnp.float32):
    """Exact path: k min+argmin extraction rounds over the full tile."""
    tb = pl.program_id(1)

    @pl.when(tb == 0)
    def _init():
        best_d_ref[...] = jnp.full_like(best_d_ref, _INF)
        best_i_ref[...] = jnp.full_like(best_i_ref, -1)

    tile = _tile_distance(q_ref[...], t_ref[...], metric, compute_dtype)
    base = tb * block_t
    col = jax.lax.broadcasted_iota(jnp.int32, tile.shape, 1)
    if n_valid < nt:                        # static: skip mask when unpadded
        tile = jnp.where(base + col < n_valid, tile, _INF)

    # k min-extractions: tile top-k without a sort
    cand_d = []
    cand_i = []
    for _ in range(k):
        m = jnp.min(tile, axis=1)                    # [BQ]
        am = jnp.argmin(tile, axis=1).astype(jnp.int32)
        cand_d.append(m[:, None])
        cand_i.append(base + am[:, None])
        tile = jnp.where(col == am[:, None], _INF, tile)

    _merge_into_best(best_d_ref, best_i_ref,
                     jnp.concatenate(cand_d, axis=1),
                     jnp.concatenate(cand_i, axis=1), k)


def _knn_kernel_packed(q_ref, t_ref, best_d_ref, best_i_ref, *, k: int,
                       metric: str, block_t: int, n_valid: int, nt: int,
                       compute_dtype=jnp.float32):
    """Packed-key path: distances are non-negative f32, so their int32 bit
    patterns order identically; the low _PACK_BITS mantissa bits are
    repurposed to carry the in-tile column. A k-deep compare-exchange
    insertion network then keeps the k smallest keys PER LANE in one pass
    over the tile (2 VPU ops per element per depth, indices ride free),
    and the row top-k — provably a subset of the per-lane top-k union —
    is extracted from the [BQ, k*128] remainder. Cost: ~2k cheap passes
    instead of k (min + argmin + mask) lane-reduction passes.

    Quantization: zeroing _PACK_BITS mantissa bits shifts distances by
    <= 2^-12 relative (~2.4e-4) and can reorder genuinely tied-to-that-
    precision neighbors; exact path is the default."""
    lanes = 128
    chunks = block_t // lanes
    tb = pl.program_id(1)

    @pl.when(tb == 0)
    def _init():
        best_d_ref[...] = jnp.full_like(best_d_ref, _INF)
        best_i_ref[...] = jnp.full_like(best_i_ref, -1)

    tile = _tile_distance(q_ref[...], t_ref[...], metric, compute_dtype)
    base = tb * block_t
    bits = jax.lax.bitcast_convert_type(tile, jnp.int32)
    col = jax.lax.broadcasted_iota(jnp.int32, tile.shape, 1)
    key = jnp.bitwise_or(jnp.bitwise_and(bits, ~jnp.int32(_PACK_MASK)), col)
    if n_valid < nt:                        # static: skip mask when unpadded
        key = jnp.where(base + col < n_valid, key, _SENTINEL)

    # insertion network: carries[j] holds the (j+1)-th smallest key per lane
    bq = key.shape[0]
    carries = [jnp.full((bq, lanes), _SENTINEL, jnp.int32) for _ in range(k)]
    for c in range(chunks):
        x = key[:, c * lanes:(c + 1) * lanes]
        for j in range(k):
            lo = jnp.minimum(carries[j], x)
            x = jnp.maximum(carries[j], x)
            carries[j] = lo

    # extract the row top-k from the k*128 survivors: the packed row-min IS
    # (distance, column) — no argmin or gather needed, and masking by key
    # equality is exact because packed keys are unique per tile (distinct
    # column bits; sentinels only equal the min once everything is consumed)
    cand = jnp.concatenate(carries, axis=1)           # [BQ, k*128] packed
    out_d = []
    out_i = []
    out_e = []
    for _ in range(k):
        m = jnp.min(cand, axis=1)
        # int32 (not bool) empty flags: Mosaic rejects bool concat
        out_e.append(jnp.where(m == _SENTINEL, 1, 0)[:, None])
        out_d.append(jax.lax.bitcast_convert_type(
            jnp.bitwise_and(m, ~jnp.int32(_PACK_MASK)), jnp.float32)[:, None])
        out_i.append(
            (base + jnp.bitwise_and(m, jnp.int32(_PACK_MASK)))[:, None])
        cand = jnp.where(cand == m[:, None], _SENTINEL, cand)
    # empty slots are exactly the sentinel bit pattern (checked before
    # decode, so a genuine quantized distance that happens to be huge is
    # still reported rather than laundered away); launder empties to +inf
    # so the final isinf -> -1 index masking applies
    dmat = jnp.where(jnp.concatenate(out_e, axis=1) == 1, _INF,
                     jnp.concatenate(out_d, axis=1))
    _merge_into_best(best_d_ref, best_i_ref, dmat,
                     jnp.concatenate(out_i, axis=1), k)


_LANES = 128
# lane-kernel corpus cap: 12 chunk-id bits (keeps distance quantization
# <= 2^-11); callers route bigger corpora to the exact kernel
LANE_CORPUS_CAP = _LANES * (1 << 12)


def _lane_pack_bits(nt: int) -> int:
    """Mantissa bits needed to carry a global 128-column chunk id."""
    n_chunks = (nt + _LANES - 1) // _LANES
    return max(1, (n_chunks - 1).bit_length())


def _hi_depth(k: int) -> int:
    """Carry depth needed for the hi (pair-loser) stream.

    A hi-stream element e in the row top-k has, for each smaller hi-stream
    element h in its lane, TWO distinct row elements below e (h and h's
    pair partner), plus e's own partner: 2H + 1 <= k - 1, so
    H <= floor((k-2)/2) and depth H+1 suffices. k=1: a pair loser can
    never be the row minimum, so the hi stream needs no carries at all."""
    return 0 if k < 2 else (k - 2) // 2 + 1


def _knn_kernel_lanes(q_ref, t_ref, keys_ref, *, k: int, metric: str,
                      block_t: int, n_valid: int, nt: int, pack_bits: int,
                      compute_dtype=jnp.float32):
    """Lane-resident packed top-k (the round-3 fast path).

    Differences from _knn_kernel_packed:
    - the low mantissa bits carry the *global 128-column chunk id*
      (column // 128); the lane index is implicit in the vector position,
      so pack_bits = log2(nt/128) instead of log2(block_t) — finer
      quantization (2^-13 at nt=128k vs 2^-12) and no block_t cap.
    - the per-lane carries live in the revisited output block across the
      whole train-block grid axis; there is NO per-tile extraction or
      merge. The row top-k is recovered from the final packed buffer by
      one tiny XLA pass (_extract_lane_topk), amortized over all tiles.
    - a pair-fold front end: adjacent 128-column chunks are compare-
      exchanged once, then the winners (lo) feed a k-deep insertion
      network and the losers (hi) a _hi_depth(k)-deep one. The kernel is
      VMEM-bandwidth-bound, and the fold halves the elements entering the
      deep network: ~(2 + 3*(2k-1)/2 + 3*(2h-1)/2) streamed passes per
      element instead of 3*(2k-1).

    Correctness of the per-lane carry: a row element with global rank r
    has at most r-1 smaller elements anywhere, hence fewer than k smaller
    elements in its own lane, so every row-top-k lo-element survives the
    k-deep lo carry; the hi bound is proven at _hi_depth."""
    chunks = block_t // _LANES
    assert chunks % 2 == 0, "block_t must be a multiple of 256 (pair fold)"
    tb = pl.program_id(1)
    mask = jnp.int32((1 << pack_bits) - 1)
    khi = _hi_depth(k)

    @pl.when(tb == 0)
    def _init():
        keys_ref[...] = jnp.full_like(keys_ref, _SENTINEL)

    if metric == "euclidean":
        # the wrapper pre-scales q by -2, so dist^2 = qs + ts + (-2q)@t
        # with qs recovered as sum((-2q)^2)/4 — one fewer full-tile pass
        # than computing qs + ts - 2*(q@t)
        qv = q_ref[...]
        tv = t_ref[...]
        qs = 0.25 * jnp.sum(qv * qv, axis=1)[:, None]
        ts = jnp.sum(tv * tv, axis=1)[None, :]
        dot = jax.lax.dot_general(
            qv.astype(compute_dtype), tv.astype(compute_dtype),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=_dot_precision(compute_dtype))
        tile = jnp.maximum(qs + ts + dot, 0.0)
    else:
        tile = _tile_distance(q_ref[...], t_ref[...], metric, compute_dtype)
    bits = jax.lax.bitcast_convert_type(tile, jnp.int32)
    base_chunk = tb * chunks

    carr_lo = [keys_ref[:, j * _LANES:(j + 1) * _LANES] for j in range(k)]
    carr_hi = [keys_ref[:, (k + j) * _LANES:(k + j + 1) * _LANES]
               for j in range(khi)]
    if n_valid < nt:
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, _LANES), 1)

    def packed_chunk(c):
        x = jnp.bitwise_or(
            jnp.bitwise_and(bits[:, c * _LANES:(c + 1) * _LANES], ~mask),
            base_chunk + c,
        )
        if n_valid < nt:                    # static: only padded corpora
            col = (base_chunk + c) * _LANES + lane
            x = jnp.where(col < n_valid, x, _SENTINEL)
        return x

    def insert(carries, x):
        depth = len(carries)
        for j in range(depth):
            lo = jnp.minimum(carries[j], x)
            if j < depth - 1:
                x = jnp.maximum(carries[j], x)
            carries[j] = lo

    for c in range(0, chunks, 2):
        x0 = packed_chunk(c)
        x1 = packed_chunk(c + 1)
        insert(carr_lo, jnp.minimum(x0, x1))
        if khi:
            insert(carr_hi, jnp.maximum(x0, x1))
    keys_ref[...] = jnp.concatenate(carr_lo + carr_hi, axis=1)


def _extract_lane_topk(keys: jnp.ndarray, k: int, pack_bits: int):
    """[nq, k*128] packed per-lane carries -> (dist_sq [nq,k], col [nq,k]).

    Packed keys order identically to the (non-negative) distances they
    encode, so the k algebraically-smallest keys ARE the row top-k. They
    are recovered with k min+argmin extraction rounds — NOT lax.top_k,
    whose sort-based TPU lowering measured ~70x slower than the pallas
    kernel it post-processes. The position's low 7 bits are the lane.
    Empty slots hold _SENTINEL (a huge finite float with zero pack bits)
    and decode to (+inf, -1); a genuine distance whose bit pattern reaches
    the sentinel (>= ~3.19e38) is indistinguishable from empty by
    construction — unreachable for normalized features."""
    mask = jnp.int32((1 << pack_bits) - 1)
    pos_iota = jax.lax.broadcasted_iota(jnp.int32, keys.shape, 1)
    cand = keys
    ks, ps = [], []
    imax = jnp.int32(np.iinfo(np.int32).max)
    for _ in range(k):
        m = jnp.min(cand, axis=1)
        am = jnp.argmin(cand, axis=1).astype(jnp.int32)
        ks.append(m[:, None])
        ps.append(am[:, None])
        cand = jnp.where(pos_iota == am[:, None], imax, cand)
    key = jnp.concatenate(ks, axis=1)
    pos = jnp.concatenate(ps, axis=1)
    lane = pos % _LANES
    chunk = jnp.bitwise_and(key, mask)
    dbits = jnp.bitwise_and(key, ~mask)
    empty = key >= _SENTINEL
    dist = jnp.where(
        empty, _INF, jax.lax.bitcast_convert_type(dbits, jnp.float32))
    col = jnp.where(empty, -1, chunk * _LANES + lane)
    return dist, col


@functools.partial(
    jax.jit,
    static_argnames=("k", "block_q", "block_t", "metric", "n_valid",
                     "interpret", "compute_dtype", "n_attrs"),
)
def knn_topk_lanes(
    q: jnp.ndarray,                 # [nq, D] f32, nq % block_q == 0
    t: jnp.ndarray,                 # [nt, D] f32, nt % block_t == 0
    k: int = 8,
    block_q: int = 512,
    block_t: int = 4096,
    metric: str = "euclidean",
    n_valid: Optional[int] = None,
    interpret: bool = False,
    compute_dtype: str = "float32",
    n_attrs: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(dist [nq, k] ascending, index [nq, k]) via the lane-resident packed
    kernel — the fastest path. Distances are quantized to 2^-(23-pack_bits)
    relative (pack_bits = log2(nt/128); 2^-13 at nt=128k, never coarser
    than 2^-11 under the nt cap below), which can reorder near-ties.
    Semantics otherwise match knn_topk_pallas."""
    nq, d = q.shape
    nt = t.shape[0]
    assert nq % block_q == 0, f"pad queries to a multiple of {block_q}"
    assert nt % block_t == 0, f"pad train rows to a multiple of {block_t}"
    assert block_t % (2 * _LANES) == 0, "pair fold needs block_t % 256 == 0"
    assert k <= block_t
    pack_bits = _lane_pack_bits(nt)
    assert pack_bits <= 12, (
        f"corpus {nt} needs {pack_bits} chunk-id bits; cap is 12 "
        f"(<= {LANE_CORPUS_CAP} rows) to keep quantization <= 2^-11")
    nv = nt if n_valid is None else n_valid
    if metric == "euclidean":
        q = q * jnp.float32(-2.0)       # see _knn_kernel_lanes epilogue

    kernel = functools.partial(
        _knn_kernel_lanes, k=k, metric=metric, block_t=block_t, n_valid=nv,
        nt=nt, pack_bits=pack_bits,
        compute_dtype=jnp.dtype(compute_dtype).type)
    grid = (nq // block_q, nt // block_t)
    width = (k + _hi_depth(k)) * _LANES
    keys = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, d), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((block_q, width), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nq, width), jnp.int32),
        interpret=interpret,
    )(q, t)
    best_d, best_i = _extract_lane_topk(keys, k, pack_bits)
    # n_attrs: semantic attribute count when columns one-hot-expand fewer
    # mixed attributes (ops.distance mixed semantics); defaults to columns
    na = d if n_attrs is None else n_attrs
    if metric == "euclidean":
        best_d = jnp.sqrt(jnp.maximum(best_d, 0.0) / max(na, 1))
    else:
        best_d = best_d / max(na, 1)
    best_i = jnp.where(jnp.isinf(best_d), -1, best_i)
    return best_d, best_i


def _kernel_score(dist, kernel: str, kernel_param: float):
    """Reference vote scores (Neighborhood.java:150-218, KERNEL_SCALE=100)
    on [BQ] final attribute-averaged distances — the same formulas as
    models.knn._vote, evaluated in-kernel."""
    d = jnp.floor(dist * 100.0)
    if kernel == "none":
        return jnp.ones_like(d)
    if kernel == "linearMultiplicative":
        return jnp.where(d == 0, 200.0, jnp.floor(100.0 / jnp.maximum(d, 1.0)))
    if kernel == "linearAdditive":
        return jnp.maximum(100.0 - d, 0.0)
    if kernel == "gaussian":
        t = d / kernel_param
        return jnp.floor(100.0 * jnp.exp(-0.5 * t * t))
    raise ValueError(f"unknown kernel {kernel}")


def _knn_kernel_lanes_vote(q_ref, t_ref, lab_ref, keys_ref, scores_ref, *,
                           k: int, metric: str, block_t: int, n_valid: int,
                           nt: int, label_bits: int, n_classes: int,
                           n_attrs: int, kernel_fn: str, kernel_param: float,
                           n_tb: int, compute_dtype=jnp.float32):
    """Lane-resident top-k with a FUSED class vote epilogue.

    Same carry structure as _knn_kernel_lanes, but the key's low mantissa
    bits carry the train row's CLASS LABEL instead of its chunk id — the
    fused classify job needs votes, not neighbor identities, and
    label_bits (1-3) is far finer quantization than the 10-12 chunk-id
    bits (2^-20ish vs 2^-12). On the final train block the kernel
    extracts the row top-k from the carries and accumulates the
    kernel-weighted one-hot vote into scores [BQ, C] — the only HBM
    output that scales with k is gone (C columns instead of
    (k + khi) * 128 packed lanes), attacking the measured output-rate
    ceiling of the top-k kernel directly."""
    chunks = block_t // _LANES
    assert chunks % 2 == 0, "block_t must be a multiple of 256 (pair fold)"
    tb = pl.program_id(1)
    mask = jnp.int32((1 << label_bits) - 1)
    khi = _hi_depth(k)

    @pl.when(tb == 0)
    def _init():
        keys_ref[...] = jnp.full_like(keys_ref, _SENTINEL)
        scores_ref[...] = jnp.zeros_like(scores_ref)

    if metric == "euclidean":
        qv = q_ref[...]
        tv = t_ref[...]
        qs = 0.25 * jnp.sum(qv * qv, axis=1)[:, None]
        ts = jnp.sum(tv * tv, axis=1)[None, :]
        dot = jax.lax.dot_general(
            qv.astype(compute_dtype), tv.astype(compute_dtype),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=_dot_precision(compute_dtype))
        tile = jnp.maximum(qs + ts + dot, 0.0)
    else:
        tile = _tile_distance(q_ref[...], t_ref[...], metric, compute_dtype)
    bits = jax.lax.bitcast_convert_type(tile, jnp.int32)
    # full-tile label OR + validity mask: Mosaic rejects 128-lane chunk
    # slices of the [1, block_t] labels block ("Invalid input layout"),
    # but lowers the whole-tile broadcast fine — chunk AFTER packing,
    # exactly like the topk kernel chunks its column-packed keys
    key_full = jnp.bitwise_or(jnp.bitwise_and(bits, ~mask), lab_ref[...])
    if n_valid < nt:
        col = jax.lax.broadcasted_iota(jnp.int32, key_full.shape, 1)
        key_full = jnp.where(tb * block_t + col < n_valid, key_full,
                             _SENTINEL)

    carr_lo = [keys_ref[:, j * _LANES:(j + 1) * _LANES] for j in range(k)]
    carr_hi = [keys_ref[:, (k + j) * _LANES:(k + j + 1) * _LANES]
               for j in range(khi)]

    def packed_chunk(c):
        return key_full[:, c * _LANES:(c + 1) * _LANES]

    def insert(carries, x):
        depth = len(carries)
        for j in range(depth):
            lo = jnp.minimum(carries[j], x)
            if j < depth - 1:
                x = jnp.maximum(carries[j], x)
            carries[j] = lo

    for c in range(0, chunks, 2):
        x0 = packed_chunk(c)
        x1 = packed_chunk(c + 1)
        insert(carr_lo, jnp.minimum(x0, x1))
        if khi:
            insert(carr_hi, jnp.maximum(x0, x1))
    keys_ref[...] = jnp.concatenate(carr_lo + carr_hi, axis=1)

    @pl.when(tb == n_tb - 1)
    def _vote_epilogue():
        # k min-extraction rounds with NO argmin: Mosaic only lowers
        # index-reductions for f32 and the packed keys are int32, so each
        # round consumes ALL lanes equal to the row minimum at once and
        # weights the vote by the duplicate count (clipped to the k-budget
        # left). Identical semantics to one-at-a-time extraction —
        # duplicate packed keys carry the same (distance, label) and so
        # the same vote — and fewer reduction passes when ties exist.
        cand = keys_ref[...]
        bq = cand.shape[0]
        cols = [jnp.zeros((bq,), jnp.float32) for _ in range(n_classes)]
        imax = jnp.int32(np.iinfo(np.int32).max)
        remaining = jnp.full((bq,), k, jnp.int32)
        for _ in range(k):
            m = jnp.min(cand, axis=1)                       # [BQ] packed
            eq = cand == m[:, None]
            cnt = jnp.sum(eq.astype(jnp.int32), axis=1)
            cand = jnp.where(eq, imax, cand)
            empty = m >= _SENTINEL
            take = jnp.where(empty, 0, jnp.minimum(cnt, remaining))
            remaining = remaining - take
            d2 = jax.lax.bitcast_convert_type(
                jnp.bitwise_and(m, ~mask), jnp.float32)
            if metric == "euclidean":
                dist = jnp.sqrt(jnp.maximum(d2, 0.0) / max(n_attrs, 1))
            else:
                dist = d2 / max(n_attrs, 1)
            # select, don't multiply: once every lane is consumed m is
            # int32 max, whose label-masked bits BITCAST TO NaN — and
            # NaN * 0 is NaN, which would poison the class columns
            s = jnp.where(take > 0,
                          _kernel_score(dist, kernel_fn, kernel_param)
                          * take.astype(jnp.float32), 0.0)
            lab = jnp.bitwise_and(m, mask)
            for c in range(n_classes):
                cols[c] = cols[c] + jnp.where(lab == c, s, 0.0)
        scores_ref[...] = jnp.stack(cols, axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_classes", "n_attrs", "kernel_fn",
                     "kernel_param", "block_q", "block_t", "metric",
                     "n_valid", "interpret", "compute_dtype"),
)
def knn_classify_lanes(
    q: jnp.ndarray,                 # [nq, D] f32, nq % block_q == 0
    t: jnp.ndarray,                 # [nt, D] f32, nt % block_t == 0
    t_labels: jnp.ndarray,          # [nt] int32 class codes
    k: int = 8,
    n_classes: int = 2,
    n_attrs: Optional[int] = None,
    kernel_fn: str = "none",
    kernel_param: float = 1.0,
    block_q: int = 512,
    block_t: int = 4096,
    metric: str = "euclidean",
    n_valid: Optional[int] = None,
    interpret: bool = False,
    compute_dtype: str = "float32",
) -> jnp.ndarray:
    """Fully fused KNN classification: class scores [nq, n_classes] of the
    kernel-weighted top-k vote (Neighborhood semantics, non-class-cond
    modes), computed without the top-k results ever leaving the kernel.
    `n_attrs` overrides the distance-normalization divisor when columns
    are a one-hot expansion of fewer semantic attributes (mixed data)."""
    nq, d = q.shape
    nt = t.shape[0]
    assert nq % block_q == 0, f"pad queries to a multiple of {block_q}"
    assert nt % block_t == 0, f"pad train rows to a multiple of {block_t}"
    assert block_t % (2 * _LANES) == 0, "pair fold needs block_t % 256 == 0"
    assert k <= block_t
    label_bits = max(1, (n_classes - 1).bit_length())
    assert label_bits <= 6, f"{n_classes} classes need > 6 label bits"
    nv = nt if n_valid is None else n_valid
    na = d if n_attrs is None else n_attrs
    if metric == "euclidean":
        q = q * jnp.float32(-2.0)
    n_tb = nt // block_t

    kernel = functools.partial(
        _knn_kernel_lanes_vote, k=k, metric=metric, block_t=block_t,
        n_valid=nv, nt=nt, label_bits=label_bits, n_classes=n_classes,
        n_attrs=na, kernel_fn=kernel_fn, kernel_param=float(kernel_param),
        n_tb=n_tb, compute_dtype=jnp.dtype(compute_dtype).type)
    grid = (nq // block_q, n_tb)
    width = (k + _hi_depth(k)) * _LANES
    _, scores = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, d), lambda i, j: (j, 0)),
            pl.BlockSpec((1, block_t), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((block_q, width), lambda i, j: (i, 0)),
            pl.BlockSpec((block_q, n_classes), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nq, width), jnp.int32),
            jax.ShapeDtypeStruct((nq, n_classes), jnp.float32),
        ],
        # the full-tile packed-key intermediate (block_q x block_t i32, on
        # top of the f32 distance tile) overflows the 16M default scoped-
        # vmem stack at the bench shapes (1024x4096) by ~2M; raise the cap
        # modestly (a 96M cap sent the mosaic allocator into a search that
        # did not terminate within 20 minutes)
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=24 * 1024 * 1024),
        interpret=interpret,
    )(q, t, t_labels.astype(jnp.int32)[None, :])
    return scores


@functools.partial(
    jax.jit,
    static_argnames=("k", "block_q", "block_t", "metric", "n_valid",
                     "interpret", "compute_dtype", "packed", "n_attrs"),
)
def knn_topk_pallas(
    q: jnp.ndarray,                 # [nq, D] f32, nq % block_q == 0
    t: jnp.ndarray,                 # [nt, D] f32, nt % block_t == 0
    k: int = 8,
    block_q: int = 256,
    block_t: int = 8192,
    metric: str = "euclidean",
    n_valid: Optional[int] = None,
    interpret: bool = False,
    compute_dtype: str = "float32",
    packed: bool = False,
    n_attrs: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(dist [nq, k] ascending, index [nq, k]) of the k nearest train rows.

    Distances match ops.distance.pairwise_distance semantics (attribute-
    averaged; euclidean = sqrt of mean squared per-attribute distance) for
    pre-normalized numeric features. Pad rows (pad_train / query padding)
    to the block sizes; `n_valid` masks train padding.

    compute_dtype="bfloat16" runs the euclidean cross-term matmul in bf16
    (f32 accumulate) at the MXU's native rate — ~8 relative decimal digits
    become ~2-3, which can reorder near-tied neighbors but moves reported
    distances by <1e-2 relative; exact f32 is the default.

    packed=True uses the packed-key insertion-network kernel
    (_knn_kernel_packed): ~2-3x faster tile reduction in exchange for
    quantizing distances to ~2^-12 relative (and the tie-reordering that
    implies). Exact bit-level distances stay the default."""
    nq, d = q.shape
    nt = t.shape[0]
    assert nq % block_q == 0, f"pad queries to a multiple of {block_q}"
    assert nt % block_t == 0, f"pad train rows to a multiple of {block_t}"
    assert k <= block_t
    if packed:
        assert block_t % 128 == 0 and block_t <= (1 << _PACK_BITS), (
            f"packed kernel needs block_t % 128 == 0 and <= {1 << _PACK_BITS}")
    nv = nt if n_valid is None else n_valid

    kernel = functools.partial(
        _knn_kernel_packed if packed else _knn_kernel,
        k=k, metric=metric, block_t=block_t, n_valid=nv, nt=nt,
        compute_dtype=jnp.dtype(compute_dtype).type)
    grid = (nq // block_q, nt // block_t)
    best_d, best_i = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, d), lambda i, j: (j, 0)),
        ],
        out_specs=[
            # revisited across the train axis: the running best buffer
            pl.BlockSpec((block_q, k), lambda i, j: (i, 0)),
            pl.BlockSpec((block_q, k), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nq, k), jnp.float32),
            jax.ShapeDtypeStruct((nq, k), jnp.int32),
        ],
        interpret=interpret,
    )(q, t)
    na = d if n_attrs is None else n_attrs
    if metric == "euclidean":
        # kernel carries squared sums; finish to attribute-averaged sqrt
        best_d = jnp.sqrt(jnp.maximum(best_d, 0.0) / max(na, 1))
    else:
        best_d = best_d / max(na, 1)
    best_i = jnp.where(jnp.isinf(best_d), -1, best_i)
    return best_d, best_i


def pallas_available() -> bool:
    """The compiled kernel needs a real TPU backend; everywhere else the
    interpret path (tests) or the jnp route serves."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
