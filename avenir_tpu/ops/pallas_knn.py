"""Pallas TPU kernel: fused distance tile + streaming top-k for KNN.

The KNN hot loop (SURVEY §7 "hard parts": blocked streaming top-k is the
main genuinely new kernel) spends its time producing an [nq, nt] distance
surface and reducing each row to its k smallest entries. The jnp path
(ops/distance.blocked_topk_neighbors) materializes each [nq, block] tile
through HBM and pays for a full sort-based lax.top_k per block. This kernel
keeps each [BQ, BT] tile entirely in VMEM and replaces the sort with k
iterative min-extractions (k is small — 5-ish — so k VPU passes over the
tile beat a sort), merging into a running [BQ, k] best buffer that lives in
the revisited output block across the train-block grid axis.

Memory: tile is BQ x BT f32 in VMEM (default 256 x 8192 = 8 MB, the
measured sweet spot under the 16 MB scoped-vmem limit), distances never
touch HBM; output is [nq, k] + [nq, k] only.

Numeric-feature metrics only (euclidean via one MXU matmul, manhattan via a
D-pass VPU loop); the mixed categorical path stays on the jnp route.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
_INF = float("inf")


def _knn_kernel(q_ref, t_ref, best_d_ref, best_i_ref, *, k: int,
                metric: str, block_t: int, n_valid: int):
    tb = pl.program_id(1)
    q = q_ref[...]                                   # [BQ, D]
    t = t_ref[...]                                   # [BT, D]
    bq = q.shape[0]

    @pl.when(tb == 0)
    def _init():
        best_d_ref[...] = jnp.full_like(best_d_ref, _INF)
        best_i_ref[...] = jnp.full_like(best_i_ref, -1)

    if metric == "euclidean":
        # squared distances via one MXU matmul; sqrt deferred to the end
        qs = jnp.sum(q * q, axis=1)[:, None]
        ts = jnp.sum(t * t, axis=1)[None, :]
        tile = jnp.maximum(
            qs + ts - 2.0 * jax.lax.dot_general(
                q, t, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32),
            0.0,
        )
    else:  # manhattan: D broadcast passes on the VPU
        tile = jnp.zeros((q.shape[0], t.shape[0]), jnp.float32)
        for f in range(q.shape[1]):
            tile = tile + jnp.abs(q[:, f][:, None] - t[:, f][None, :])

    base = tb * block_t
    col = jax.lax.broadcasted_iota(jnp.int32, tile.shape, 1)
    idx = base + col
    tile = jnp.where(idx < n_valid, tile, _INF)

    # k min-extractions: tile top-k without a sort
    cand_d = []
    cand_i = []
    for _ in range(k):
        m = jnp.min(tile, axis=1)                    # [BQ]
        am = jnp.argmin(tile, axis=1).astype(jnp.int32)
        cand_d.append(m)
        cand_i.append(base + am)
        tile = jnp.where(col == am[:, None], _INF, tile)

    # merge candidates with the carried best: 2k-wide per-row extraction
    all_d = jnp.concatenate(
        [best_d_ref[...]] + [c[:, None] for c in cand_d], axis=1)  # [BQ, 2k]
    all_i = jnp.concatenate(
        [best_i_ref[...]] + [c[:, None] for c in cand_i], axis=1)
    pos = jax.lax.broadcasted_iota(jnp.int32, all_d.shape, 1)
    new_d = []
    new_i = []
    for _ in range(k):
        m = jnp.min(all_d, axis=1)
        am = jnp.argmin(all_d, axis=1).astype(jnp.int32)
        sel = pos == am[:, None]
        # gather the index at the argmin lane via a masked reduction
        picked_i = jnp.sum(jnp.where(sel, all_i, 0), axis=1)
        new_d.append(m)
        new_i.append(picked_i)
        all_d = jnp.where(sel, _INF, all_d)
    best_d_ref[...] = jnp.stack(new_d, axis=1)
    best_i_ref[...] = jnp.stack(new_i, axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("k", "block_q", "block_t", "metric", "n_valid",
                     "interpret"),
)
def knn_topk_pallas(
    q: jnp.ndarray,                 # [nq, D] f32, nq % block_q == 0
    t: jnp.ndarray,                 # [nt, D] f32, nt % block_t == 0
    k: int = 8,
    block_q: int = 256,
    block_t: int = 8192,
    metric: str = "euclidean",
    n_valid: Optional[int] = None,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(dist [nq, k] ascending, index [nq, k]) of the k nearest train rows.

    Distances match ops.distance.pairwise_distance semantics (attribute-
    averaged; euclidean = sqrt of mean squared per-attribute distance) for
    pre-normalized numeric features. Pad rows (pad_train / query padding)
    to the block sizes; `n_valid` masks train padding."""
    nq, d = q.shape
    nt = t.shape[0]
    assert nq % block_q == 0, f"pad queries to a multiple of {block_q}"
    assert nt % block_t == 0, f"pad train rows to a multiple of {block_t}"
    assert k <= block_t
    nv = nt if n_valid is None else n_valid

    kernel = functools.partial(_knn_kernel, k=k, metric=metric,
                               block_t=block_t, n_valid=nv)
    grid = (nq // block_q, nt // block_t)
    best_d, best_i = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, d), lambda i, j: (j, 0)),
        ],
        out_specs=[
            # revisited across the train axis: the running best buffer
            pl.BlockSpec((block_q, k), lambda i, j: (i, 0)),
            pl.BlockSpec((block_q, k), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nq, k), jnp.float32),
            jax.ShapeDtypeStruct((nq, k), jnp.int32),
        ],
        interpret=interpret,
    )(q, t)
    if metric == "euclidean":
        # kernel carries squared sums; finish to attribute-averaged sqrt
        best_d = jnp.sqrt(jnp.maximum(best_d, 0.0) / max(d, 1))
    else:
        best_d = best_d / max(d, 1)
    best_i = jnp.where(jnp.isinf(best_d), -1, best_i)
    return best_d, best_i


def pallas_available() -> bool:
    """The compiled kernel needs a real TPU backend; everywhere else the
    interpret path (tests) or the jnp route serves."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
