"""All-pairs distances + streaming top-k: the sifarish replacement.

The reference KNN pipeline outsources pairwise train-test distances to an
external MapReduce job (sifarish SameTypeSimilarity, driven at
resource/knn.sh:44-57) whose output is re-shuffled through two more jobs
before the KNN reducer sees ranked neighbors (knn/NearestNeighbor.java).
Here the whole thing is one fused device program:

- mixed-attribute distance (numeric range-normalized L1 + categorical
  mismatch), the metric SameTypeSimilarity computes, expressed as matmuls
  over one-hot/2-norm expansions so the MXU does the work;
- blocked streaming top-k over train tiles, so 1B-row train sets never
  materialize an [n_test, n_train] matrix (SURVEY §7 "hard parts").

Distances are float; the reference's int scaling (sts.distance.scale=1000)
is applied only at the output/CSV layer for file compatibility.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def pairwise_distance(
    q_num: jnp.ndarray,
    t_num: jnp.ndarray,
    q_cat: Optional[jnp.ndarray] = None,
    t_cat: Optional[jnp.ndarray] = None,
    cat_bins: Optional[Tuple[int, ...]] = None,
    num_ranges: Optional[jnp.ndarray] = None,
    metric: str = "manhattan",
    num_weights: Optional[jnp.ndarray] = None,
    cat_weights: Optional[Tuple[float, ...]] = None,
) -> jnp.ndarray:
    """Dense [nq, nt] mixed-attribute distance block.

    q_num/t_num: float [nq, Dn] / [nt, Dn] numeric features.
    q_cat/t_cat: int [nq, Dc] / [nt, Dc] categorical codes.
    cat_bins: per-categorical-feature cardinality (for one-hot expansion).
    num_ranges: [Dn] normalization ranges (max-min per schema); defaults 1.
    metric: 'manhattan' (SameTypeSimilarity-style avg per-attribute distance)
            or 'euclidean' (sqrt of mean squared per-attribute distance).
    num_weights/cat_weights: per-attribute weights (the distance-schema
    weighting of chombo InterRecordDistance); default 1 each.

    The result is the weight-averaged per-attribute distance in [0, 1]-ish
    space, matching the reference's attribute-averaged semantics.
    """
    nq = q_num.shape[0] if q_num is not None and q_num.ndim == 2 else q_cat.shape[0]
    nt = t_num.shape[0] if t_num is not None and t_num.ndim == 2 else t_cat.shape[0]
    d_total = jnp.zeros((nq, nt), dtype=jnp.float32)
    w_total = 0.0

    if q_num is not None and q_num.shape[-1] > 0:
        dn = q_num.shape[-1]
        rng = num_ranges if num_ranges is not None else jnp.ones((dn,), jnp.float32)
        w = (num_weights if num_weights is not None
             else jnp.ones((dn,), jnp.float32))
        # weight folds into the feature scaling: w*|q-t| for L1 needs a w
        # factor, w*(q-t)^2 for L2 a sqrt(w) factor
        scale = (jnp.sqrt(w) if metric == "euclidean" else w) / jnp.maximum(rng, 1e-9)
        qs = q_num * scale
        ts = t_num * scale
        if metric == "euclidean":
            # ||q-t||^2 = ||q||^2 + ||t||^2 - 2 q.t — one MXU matmul
            sq = jnp.sum(qs * qs, axis=1)[:, None] + jnp.sum(ts * ts, axis=1)[None, :]
            d2 = jnp.maximum(sq - 2.0 * (qs @ ts.T), 0.0)
            d_total = d_total + d2
        else:
            # L1 has no matmul form; tile over the (small) feature axis
            d_total = d_total + jnp.sum(
                jnp.abs(qs[:, None, :] - ts[None, :, :]), axis=-1
            )
        w_total = w_total + jnp.sum(w)

    if q_cat is not None and q_cat.shape[-1] > 0:
        dc = q_cat.shape[-1]
        assert cat_bins is not None and len(cat_bins) == dc
        cw = cat_weights if cat_weights is not None else (1.0,) * dc
        # weighted mismatch = sum_f w_f - sum_f w_f [q_f == t_f]; equality
        # via one-hot matmul
        matches = jnp.zeros((nq, nt), dtype=jnp.float32)
        for f in range(dc):
            qo = jax.nn.one_hot(q_cat[:, f], cat_bins[f], dtype=jnp.float32)
            to = jax.nn.one_hot(t_cat[:, f], cat_bins[f], dtype=jnp.float32)
            matches = matches + cw[f] * (qo @ to.T)
        # per-attribute categorical distance is 0/1, so d_f^2 == d_f and the
        # mismatch count is the right contribution for both metrics
        d_total = d_total + (sum(cw) - matches)
        w_total = w_total + sum(cw)

    w_total = jnp.maximum(w_total, 1e-9)
    if metric == "euclidean":
        return jnp.sqrt(d_total / w_total)
    return d_total / w_total


def pad_train(
    t_num: Optional[np.ndarray],
    t_cat: Optional[np.ndarray],
    block: int,
) -> Tuple[Optional[np.ndarray], Optional[np.ndarray], int]:
    """Pad train arrays up to a multiple of `block`.

    Returns (t_num, t_cat, n_valid); pass n_valid to blocked_topk_neighbors
    so padded rows are masked to +inf distance (pad values themselves are
    inert — index masking is what excludes them)."""
    n = t_num.shape[0] if t_num is not None else t_cat.shape[0]
    rem = (-n) % block
    if rem:
        if t_num is not None:
            t_num = np.concatenate(
                [np.asarray(t_num),
                 np.zeros((rem, t_num.shape[1]), dtype=np.asarray(t_num).dtype)]
            )
        if t_cat is not None:
            t_cat = np.concatenate(
                [np.asarray(t_cat),
                 np.zeros((rem, t_cat.shape[1]), dtype=np.asarray(t_cat).dtype)]
            )
    return t_num, t_cat, n


@partial(jax.jit, static_argnames=("k", "block", "metric", "cat_bins", "approx"))
def blocked_topk_neighbors(
    q_num: jnp.ndarray,
    t_num: jnp.ndarray,
    q_cat: Optional[jnp.ndarray] = None,
    t_cat: Optional[jnp.ndarray] = None,
    cat_bins: Optional[Tuple[int, ...]] = None,
    num_ranges: Optional[jnp.ndarray] = None,
    k: int = 8,
    block: int = 32768,
    metric: str = "manhattan",
    n_valid: Optional[int] = None,
    approx: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Streaming k-nearest-neighbor search: scan train set in tiles.

    Returns (dist [nq, k], index [nq, k]) of the k nearest train rows per
    query row, without materializing the full [nq, nt] matrix. Train rows
    are processed `block` at a time under lax.scan; each block reduces to k
    candidates (so the merge works on nblocks*k, not nt). Large blocks
    amortize the top_k cost — the per-block distance tile [nq, block] is the
    peak memory. `n_valid` (default: all rows) masks divisibility padding —
    rows at index >= n_valid get +inf distance and can never enter the
    top-k; use `pad_train` to pad the arrays. `approx=True` uses the
    TPU-optimized lax.approx_min_k per block (recall ~0.95+) — exact
    semantics only off."""
    nt = t_num.shape[0] if t_num is not None else t_cat.shape[0]
    assert nt % block == 0, "pad train rows to a multiple of block (pad_train)"
    assert k <= block, f"k ({k}) must be <= block ({block})"
    nq = q_num.shape[0] if q_num is not None else q_cat.shape[0]
    nblocks = nt // block
    n_valid_arr = jnp.int32(nt if n_valid is None else n_valid)

    def block_topk(b):
        """Reduce one train block to its local top-k candidates."""
        start = b * block
        tn = lax.dynamic_slice_in_dim(t_num, start, block, 0) if t_num is not None else None
        tc = lax.dynamic_slice_in_dim(t_cat, start, block, 0) if t_cat is not None else None
        d = pairwise_distance(q_num, tn, q_cat, tc, cat_bins, num_ranges, metric)
        idx = start + jnp.arange(block, dtype=jnp.int32)[None, :]
        d = jnp.where(idx < n_valid_arr, d, jnp.inf)
        if approx:
            bd, bpos = lax.approx_min_k(d, k)
        else:
            neg, bpos = lax.top_k(-d, k)
            bd = -neg
        return bd, start + bpos.astype(jnp.int32)

    if nblocks == 1:
        dist, idx = block_topk(jnp.int32(0))
    else:
        # running-carry merge: each block reduces to k candidates, then a
        # tiny [nq, 2k] top_k folds them into the carry — O(nq*k) memory,
        # so billion-row train sets stream without big intermediates
        def body(carry, b):
            best_d, best_i = carry
            bd, bi = block_topk(b)
            cat_d = jnp.concatenate([best_d, bd], axis=1)
            cat_i = jnp.concatenate([best_i, bi], axis=1)
            neg, pos = lax.top_k(-cat_d, k)
            return (-neg, jnp.take_along_axis(cat_i, pos, axis=1)), None

        init = (
            jnp.full((nq, k), jnp.inf, dtype=jnp.float32),
            jnp.full((nq, k), -1, dtype=jnp.int32),
        )
        (dist, idx), _ = lax.scan(body, init, jnp.arange(nblocks))
    # unfillable slots (n_valid < k): -1 sentinel instead of phantom rows
    idx = jnp.where(jnp.isinf(dist), -1, idx)
    return dist, idx
