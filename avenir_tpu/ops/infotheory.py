"""Information-theory algebra over count tensors.

Replaces the reference's per-node accumulator objects (util/InfoContentStat,
util/AttributeSplitStat, explore/MutualInformationScore) with vectorized
functions over count/probability arrays: a whole tree level's or feature
set's statistics evaluate in one call.
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12


def _norm(counts: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    tot = counts.sum(axis=axis, keepdims=True)
    return counts / jnp.maximum(tot, _EPS)


def entropy(counts: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Shannon entropy (nats) of count vectors along `axis`."""
    p = _norm(counts, axis)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, _EPS)), 0.0), axis=axis)


def bits_entropy(counts: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Entropy in bits (log2) — matches the reference's InfoContentStat
    which uses log2 (util/InfoContentStat.java processStat)."""
    return entropy(counts, axis) / jnp.log(2.0)


def gini(counts: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Gini index 1 - sum p^2 of count vectors along `axis`."""
    p = _norm(counts, axis)
    return 1.0 - jnp.sum(p * p, axis=axis)


def weighted_split_score(
    seg_class_counts: jnp.ndarray, algo: str = "entropy"
) -> jnp.ndarray:
    """Population-weighted impurity of a split.

    seg_class_counts: [..., S, K] counts per split-segment per class.
    Returns [...]: sum_s (n_s / n) * impurity(segment s) — the quantity the
    tree reducer minimizes over candidate splits
    (tree/DecisionTreeBuilder.java:495-532, AttributeSplitStat).
    """
    score_fn = bits_entropy if algo in ("entropy", "infoGain") else gini
    seg_tot = seg_class_counts.sum(axis=-1)                    # [..., S]
    tot = jnp.maximum(seg_tot.sum(axis=-1, keepdims=True), _EPS)
    imp = score_fn(seg_class_counts, axis=-1)                  # [..., S]
    return jnp.sum(seg_tot / tot * imp, axis=-1)


def mutual_information(joint_counts: jnp.ndarray) -> jnp.ndarray:
    """MI (nats) from a joint count table [..., A, B] between its last two axes."""
    pj = joint_counts / jnp.maximum(
        joint_counts.sum(axis=(-2, -1), keepdims=True), _EPS
    )
    pa = pj.sum(axis=-1, keepdims=True)
    pb = pj.sum(axis=-2, keepdims=True)
    ratio = pj / jnp.maximum(pa * pb, _EPS)
    return jnp.sum(jnp.where(pj > 0, pj * jnp.log(jnp.maximum(ratio, _EPS)), 0.0),
                   axis=(-2, -1))
