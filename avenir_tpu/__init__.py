"""avenir_tpu: a TPU-native classical-ML / data-mining framework.

A ground-up JAX/XLA re-design of the capabilities of the avenir toolkit
(reference: Hadoop MapReduce + Spark + Storm jobs). Instead of one JVM job
per pipeline stage with HDFS files in between, every algorithm here is a
set of jitted, shardable array programs:

- per-record "mapper" logic      -> jax.vmap row kernels
- keyed shuffle + reducers       -> dense-key segment_sum + lax.psum over a Mesh
- secondary sort (ranked values) -> lax.top_k within shards
- iterative driver shell loops   -> host Python loops around jitted steps,
                                    model state stays on device
- Storm streaming bolts          -> async host loop feeding a jitted kernel

Compatibility surfaces kept from the reference: FeatureSchema JSON metadata
(resource/churn.json style), flat .properties config files with per-job key
prefixes, CSV record IO, and file-based model formats (DecisionPathList JSON,
CSV distribution models).
"""

__version__ = "0.1.0"

from avenir_tpu.core.schema import FeatureSchema, FeatureField
from avenir_tpu.core.config import JobConfig, load_hocon, load_properties
from avenir_tpu.core.dataset import Dataset
from avenir_tpu.core.stream import (CsvBlockReader, iter_csv_chunks,
                                    prefetched)

__all__ = [
    "FeatureSchema",
    "FeatureField",
    "JobConfig",
    "load_properties",
    "load_hocon",
    "Dataset",
    "CsvBlockReader",
    "iter_csv_chunks",
    "prefetched",
    "__version__",
]
