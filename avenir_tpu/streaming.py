"""Streaming learner loop: the Storm + Redis topology as an async host loop.

Reference (SURVEY §3.5): ReinforcementLearnerTopology.java:42-84 builds a
RedisSpout → shuffleGrouping → ReinforcementLearnerBolt topology; per event
the bolt drains queued rewards into the learner, selects the next action
batch, and pushes (eventID, actions) to a Redis list
(ReinforcementLearnerBolt.java:93-125, RedisActionWriter.java:48,
RedisSpout.java:86-100 rpop of "eventID,roundNum" messages).

Here the topology is a thread + two queues: the event queue feeds
LearnerStream.run(), reward messages interleave exactly as in the bolt
(reward-typed tuples call set_reward directly; event-typed tuples drain the
reward reader first). Reader/writer are small interfaces with in-memory
queue implementations; a Redis pair with the same queue semantics plugs in
when a `redis` client is available (not bundled — the loop itself never
depends on it).
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from avenir_tpu.models.reinforce import Action, create_learner


class RewardReader:
    """RewardReader.java:30 — drain pending (actionID, reward) messages."""

    def read_rewards(self) -> List[Tuple[str, int]]:
        raise NotImplementedError


class ActionWriter:
    """ActionWriter.java:27 — publish selected actions for an event."""

    def write(self, event_id: str, actions: Sequence[Action]) -> None:
        raise NotImplementedError


class QueueRewardReader(RewardReader):
    """In-memory reward queue ("actionID,reward" messages like the Redis
    list payloads, RedisRewardReader.java:46-60)."""

    def __init__(self):
        self.q: "queue.Queue[Tuple[str, int]]" = queue.Queue()

    def push(self, action_id: str, reward: int) -> None:
        self.q.put((action_id, reward))

    def read_rewards(self) -> List[Tuple[str, int]]:
        out = []
        while True:
            try:
                out.append(self.q.get_nowait())
            except queue.Empty:
                return out


class QueueActionWriter(ActionWriter):
    """In-memory action output queue ("eventID,action1,action2,..." payload
    format of RedisActionWriter.java:48-57)."""

    def __init__(self):
        self.q: "queue.Queue[str]" = queue.Queue()

    def write(self, event_id: str, actions: Sequence[Action]) -> None:
        self.q.put(event_id + "," + ",".join(a.id for a in actions))

    def pop(self, timeout: Optional[float] = None) -> Optional[str]:
        try:
            return self.q.get(timeout=timeout)
        except queue.Empty:
            return None


class RedisRewardReader(RewardReader):
    """Redis-list reward reader (RedisRewardReader.java:31). Requires a
    `redis` client object; message format "actionID,reward"."""

    def __init__(self, client, reward_queue: str):
        self.client = client
        self.queue = reward_queue

    def read_rewards(self) -> List[Tuple[str, int]]:
        out = []
        while True:
            msg = self.client.rpop(self.queue)
            if msg is None:
                return out
            if isinstance(msg, bytes):
                msg = msg.decode()
            action_id, reward = msg.split(",")
            out.append((action_id, int(reward)))


class RedisActionWriter(ActionWriter):
    """Redis-list action writer (RedisActionWriter.java:48)."""

    def __init__(self, client, action_queue: str):
        self.client = client
        self.queue = action_queue

    def write(self, event_id: str, actions: Sequence[Action]) -> None:
        self.client.lpush(
            self.queue, event_id + "," + ",".join(a.id for a in actions))


class LearnerStream:
    """The topology: event intake → reward drain → select → action output.

    Synchronous use: process_event() / process_reward() mirror the bolt's
    two tuple types (ReinforcementLearnerBolt.process). Async use: start()
    spawns the loop thread consuming the event queue (the RedisSpout role),
    submit_event() enqueues, stop() joins."""

    #: loop poll granularity: bounds how long the worker blocks on the
    #: event queue before re-checking the shutdown flag, so a lost
    #: sentinel (e.g. consumed by a replay race) can't wedge the thread
    POLL_SECS = 0.2

    def __init__(self, learner_type: str, action_ids: Sequence[str],
                 config: Dict,
                 reward_reader: Optional[RewardReader] = None,
                 action_writer: Optional[ActionWriter] = None,
                 max_replays: int = 3):
        self.learner = create_learner(learner_type, action_ids, config)
        self.reward_reader = reward_reader or QueueRewardReader()
        self.action_writer = action_writer or QueueActionWriter()
        self.events: "queue.Queue[Optional[Tuple[str, int]]]" = queue.Queue()
        self.thread: Optional[threading.Thread] = None
        self.processed = 0
        # Storm ack/replay analog (chombo GenericSpout pendingMsgHolder,
        # RedisSpout.java:39): an event whose processing raises is replayed
        # up to max_replays times, then dropped onto the failed list
        self.max_replays = max_replays
        self.replays: Dict[str, int] = {}
        self.failed: List[Tuple[str, str]] = []   # (event_id, error)
        # guards the caller-visible state the loop thread mutates
        # (processed/replays/failed); the event queue itself is the
        # sanctioned handoff for the tuples
        self._lock = threading.Lock()
        self._stop_requested = threading.Event()

    # ------------------------------------------------------ bolt semantics
    def process_event(self, event_id: str, round_num: int = 0) -> List[Action]:
        for action_id, reward in self.reward_reader.read_rewards():
            self.learner.set_reward(action_id, reward)
        actions = self.learner.next_actions()
        self.action_writer.write(event_id, actions)
        with self._lock:
            self.processed += 1
        return actions

    def process_reward(self, action_id: str, reward: int) -> None:
        self.learner.set_reward(action_id, reward)

    # --------------------------------------------------------- async loop
    def submit_event(self, event_id: str, round_num: int = 0) -> None:
        self.events.put((event_id, round_num))

    def start(self) -> "LearnerStream":
        self._stop_requested = threading.Event()

        def loop():
            while True:
                try:
                    # timeout, not a bare get(): a worker blocked forever
                    # on an empty queue is indistinguishable from a hang,
                    # and a sentinel lost to a replay race would wedge it
                    item = self.events.get(timeout=self.POLL_SECS)
                except queue.Empty:
                    if self._stop_requested.is_set():
                        return
                    continue
                if item is None:
                    # a replayed tuple may have been re-enqueued behind
                    # the stop sentinel: drop the sentinel and keep
                    # draining (NEVER re-enqueue it — two stop() calls
                    # would leave two sentinels ping-ponging forever);
                    # once the queue is quiet the poll timeout sees the
                    # stop flag and exits
                    if self.events.empty():
                        return
                    continue
                try:
                    self.process_event(*item)
                    with self._lock:
                        self.replays.pop(item[0], None)    # acked
                except Exception as exc:
                    with self._lock:
                        n = self.replays.get(item[0], 0) + 1
                        self.replays[item[0]] = n
                    if n <= self.max_replays:
                        self.events.put(item)          # Storm tuple replay
                    else:
                        # clear the counter: a future submission of the same
                        # event id starts with a fresh replay budget
                        with self._lock:
                            self.replays.pop(item[0], None)
                            self.failed.append((item[0], repr(exc)))

        self.thread = threading.Thread(target=loop, daemon=True)
        self.thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Signal shutdown (flag + sentinel), join the loop thread, and
        VERIFY it exited: a worker still alive after `timeout` is wedged
        (e.g. inside a learner call) and raises instead of silently
        truncating the stream on return."""
        if self.thread is None:
            return
        self._stop_requested.set()
        self.events.put(None)
        self.thread.join(timeout)
        if self.thread.is_alive():
            raise RuntimeError(
                f"LearnerStream worker failed to stop within {timeout}s "
                f"(wedged inside process_event?); events pending: "
                f"~{self.events.qsize()}")
        self.thread = None
