"""Canonical pipelines: the reference's tutorial shell flows as Pipelines.

Each factory wires the stages one of the resource/*.sh case-statement
drivers (SURVEY §2.11) ran by hand, against the same properties keys, so
the 20+ *_tutorial.txt run-books translate 1:1: build the pipeline, call
run(). Iterative flows (Apriori k-rounds, tree levels) that the reference
drove by re-running jobs with file rotation run inside their jobs here, but
every between-round file still lands on disk.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from avenir_tpu.core.config import load_properties
from avenir_tpu.runner import JobResult, Pipeline, Stage, job_prefix, run_job


def _props(conf) -> Dict[str, str]:
    """Properties from a file path, a dict, or a JobConfig."""
    if isinstance(conf, str):
        return load_properties(conf)
    if hasattr(conf, "props"):
        return dict(conf.props)
    return dict(conf)


def knn_pipeline(conf, train_csv: str, test_csv: str, work_dir: str,
                 schema_path: Optional[str] = None) -> Pipeline:
    """The 5-stage resource/knn.sh flow (SURVEY §3.3).

    Stage (1) sifarish distances -> recordSimilarity; stages (2)-(4)
    (NB distributions, feature posterior, join) -> bayesianDistr + the
    fused class-conditional weighting inside nearestNeighbor; stage (5) ->
    nearestNeighbor. The distance file is still produced for downstream
    consumers even though the fused KNN recomputes distances on device.
    """
    os.makedirs(work_dir, exist_ok=True)
    overrides: Dict[str, str] = {}
    if schema_path:
        for p in ("sts", "bad", "bap", "nen"):
            overrides[f"{p}.feature.schema.file.path"] = schema_path
    model_path = os.path.join(work_dir, "distr.csv")
    overrides.setdefault("bap.bayesian.model.file.path", model_path)
    return Pipeline(_props(conf), [
        Stage("similarity", "recordSimilarity", [train_csv, test_csv],
              os.path.join(work_dir, "simi.txt"), dict(overrides)),
        Stage("bayesianDistr", "bayesianDistr", [train_csv], model_path,
              dict(overrides)),
        Stage("featurePosterior", "bayesianPredictor", [train_csv],
              os.path.join(work_dir, "condProb.txt"),
              {**overrides, "bap.output.feature.prob.only": "true"}),
        Stage("join", "featureCondProbJoiner",
              [os.path.join(work_dir, "simi.txt"),
               os.path.join(work_dir, "condProb.txt")],
              os.path.join(work_dir, "join.txt"), dict(overrides)),
        Stage("nearestNeighbor", "nearestNeighbor", [train_csv, test_csv],
              os.path.join(work_dir, "knn_out.txt"), dict(overrides)),
    ])


def decision_tree_pipeline(conf, train_csv: str, work_dir: str,
                           schema_path: Optional[str] = None,
                           forest: bool = False) -> Pipeline:
    """resource/detr.sh / rafo.sh: the per-level decTree + mvDecFiles
    rotation (SURVEY §3.4) as one job whose DecisionPathList JSON lands at
    dtb.decision.file.path.out; rafo's forest variant writes per-tree files."""
    os.makedirs(work_dir, exist_ok=True)
    overrides: Dict[str, str] = {}
    if schema_path:
        overrides["dtb.feature.schema.file.path"] = schema_path
    overrides.setdefault(
        "dtb.decision.file.path.out", os.path.join(work_dir, "decPathOut.txt"))
    job = "randomForest" if forest else "decTree"
    return Pipeline(_props(conf), [
        Stage("decTree", job, [train_csv],
              os.path.join(work_dir, "forest") if forest else "",
              overrides),
    ])


def association_pipeline(conf, trans_csv: str, work_dir: str) -> Pipeline:
    """resource/carm.sh: frequent itemsets (all k rounds) then association
    rules over the per-k itemset files."""
    os.makedirs(work_dir, exist_ok=True)
    iset_dir = os.path.join(work_dir, "itemsets")
    pipe = Pipeline(_props(conf), [
        Stage("apriori", "frequentItemsApriori", [trans_csv], iset_dir),
        # inputs of the rules stage are resolved after apriori runs
        Stage("rules", "associationRuleMiner", [],
              os.path.join(work_dir, "rules.txt")),
    ])

    orig_run = pipe.run

    def run(only=None):
        results: Dict[str, JobResult] = {}
        if only in (None, "apriori"):
            results.update(orig_run("apriori"))
        if only in (None, "rules"):
            ap = pipe.results.get("apriori")
            if ap is None:
                raise RuntimeError("run the apriori stage first")
            pipe.stages[1].inputs = list(ap.outputs)
            results.update(orig_run("rules"))
        return results

    pipe.run = run  # type: ignore[method-assign]
    return pipe


def profile_pipeline(conf, train_csv: str, work_dir: str,
                     schema_path: Optional[str] = None) -> Pipeline:
    """The corpus-profiling flow: NB distributions + mutual information
    + Fisher discriminant over ONE labeled corpus — the three jobs every
    modeling run-book starts with, each of which used to make its own
    full pass over the same multi-GB CSV. All three are shared-scan
    folds, so ``run(fuse=True)`` executes them as ONE SharedScan pass
    (one disk read + one parse per chunk, three fold sinks); plain
    ``run()`` keeps the one-job-one-scan path, byte-identical outputs
    either way."""
    os.makedirs(work_dir, exist_ok=True)
    overrides: Dict[str, str] = {}
    if schema_path:
        for p in ("bad", "mut", "fid"):
            overrides[f"{p}.feature.schema.file.path"] = schema_path
    return Pipeline(_props(conf), [
        Stage("bayesianDistr", "bayesianDistr", [train_csv],
              os.path.join(work_dir, "distr.csv"), dict(overrides)),
        Stage("mutualInformation", "mutualInformation", [train_csv],
              os.path.join(work_dir, "mi.txt"), dict(overrides)),
        Stage("fisherDiscriminant", "fisherDiscriminant", [train_csv],
              os.path.join(work_dir, "fisher.txt"), dict(overrides)),
    ])


def bandit_round(conf, stats_csv: str, out_path: str, round_num: int,
                 job: str = "greedyRandomBandit") -> JobResult:
    """One decision round of the price-optimization loop
    (resource/price_optimize_tutorial.txt:20-82): reward-aggregate rows in,
    selected items out. The driver loop lives with the caller, exactly like
    the tutorial's manual rounds — reward aggregation between rounds is the
    caller's data pipeline."""
    props = _props(conf)
    props[f"{job_prefix(job)}.current.round.num"] = str(round_num)
    return run_job(job, props, [stats_csv], out_path)
