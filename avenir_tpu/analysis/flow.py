"""graftlint-flow: concurrency/determinism analysis of the host streaming
layer, plus the mechanical chunk-invariance auditor.

The AST rules (rules.py) see single-statement shapes; the IR rules
(ir.py) see what tracing produced. The hazards that cost streamed jobs
whole runs live BETWEEN those levels, in the host coordination code the
reference delegated to Hadoop/Storm: threads, queues, and fold order.
A `queue.get()` with no timeout is a hang the bench watcher cannot
distinguish from a chip flap; an unjoined worker thread is silent
truncation at shutdown; shared state mutated off-thread without a lock
is a read-tear on the caller; blocking IO inside a fold body quietly
deletes the double-buffered overlap; and a float accumulator folded
across chunks reassociates with the chunk layout, so "same input, same
output" stops being true bit-for-bit.

Two layers, mirroring graftlint-ir's split:

- **Flow rules** — interprocedural dataflow over each module's
  concurrency surface: a :class:`ConcurrencyModel` resolves which
  names/attributes hold queues, locks and threads (through assignment
  aliasing), which functions run on worker threads (through
  ``Thread(target=...)`` and transitive ``self.method()`` calls), and
  which folds consume streamed chunk iterators. The five rules judge
  those facts, not single call sites.
- **Chunk-invariance auditor** — the manifest's streamed fold kernels
  (analysis/manifest.py, ``stream_entries()``: NB, MI, Markov,
  Apriori, GSP, discriminant) each run to completion under >= 3
  permuted chunk layouts AND under an adversarial prefetch scheduler
  (deterministic jitter injected into every ``core.stream.prefetched``
  producer), asserting byte-identical output artifacts. Determinism is
  proven mechanically per run, not claimed.

Findings flow through the shared engine (same ``path::rule::scope``
keys, same allowlist baseline); entry points: ``graftlint --flow``
(analysis/cli.py) or :func:`run_flow` in-process. A stream kernel that
fails to RUN raises :class:`FlowAuditError` — the CLI maps that to exit
code 2, distinct from exit 1 (an invariance violation is a finding
under ``flow-chunk-invariance``; like the payload rule, never
allowlist it — fix the fold).
"""

from __future__ import annotations

import ast
import os
import random
import shutil
import tempfile
import threading
import time
import weakref
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from avenir_tpu.analysis.engine import (BaselineEntry, Finding, ModuleContext,
                                        Report, apply_baseline,
                                        collect_findings)

#: the auditor's pseudo-rule id: invariance violations surface as
#: findings under it (never allowlist one — a fold whose result depends
#: on chunk layout is wrong, not inconvenient)
FLOW_AUDIT_RULE = "flow-chunk-invariance"

_THREAD_CTORS = ("threading.Thread",)
_QUEUE_CTORS = ("queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
                "queue.PriorityQueue", "multiprocessing.Queue")
_LOCK_CTORS = ("threading.Lock", "threading.RLock", "threading.Condition",
               "threading.Semaphore", "threading.BoundedSemaphore")
#: iterator factories whose `for` loops are chunk/fold loops — the
#: device-overlap pipeline the blocking-io and order rules protect
_FOLD_SOURCES = {"double_buffered", "prefetched", "stream_job_inputs",
                 "stream_job_lines", "stream_job_byte_blocks"}
#: method calls treated as container mutation for the shared-state rule
_MUTATORS = {"append", "extend", "insert", "add", "discard", "remove",
             "pop", "popitem", "clear", "update", "setdefault"}


class FlowAuditError(RuntimeError):
    """A streamed fold kernel could not be prepared or run."""


# --------------------------------------------------------------------------
# per-module concurrency model (shared by all five rules)
# --------------------------------------------------------------------------
def _target_ids(target: ast.AST) -> List[str]:
    """Identifier keys a binding target contributes to the alias graph:
    plain names as ``name``, self-attributes as ``.attr`` (attribute
    identity is keyed on the attr name — modules here are small and the
    coarseness is documented)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, ast.Attribute) and isinstance(target.value,
                                                        ast.Name) \
            and target.value.id == "self":
        return ["." + target.attr]
    return []


def _receiver_id(node: ast.AST) -> Optional[str]:
    """Identifier key of a call/attribute receiver, same keying as
    :func:`_target_ids`."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return "." + node.attr
    return None


class _Aliases:
    """Union-find over identifier keys, connected by plain assignments
    (including tuple-to-tuple unpacks like ``t, self.x = self.x, None``):
    the dataflow skeleton the queue/lock/thread facts ride on."""

    def __init__(self, tree: ast.Module):
        self.parent: Dict[str, str] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            pairs: List[Tuple[ast.AST, ast.AST]] = []
            tgt, val = node.targets[0], node.value
            if isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple) \
                    and len(tgt.elts) == len(val.elts):
                pairs.extend(zip(tgt.elts, val.elts))
            else:
                pairs.append((tgt, val))
            for t, v in pairs:
                vid = _receiver_id(v)
                if vid is None:
                    continue
                for tid in _target_ids(t):
                    self.union(tid, vid)

    def find(self, key: str) -> str:
        root = key
        while self.parent.get(root, root) != root:
            root = self.parent[root]
        while self.parent.get(key, key) != key:
            self.parent[key], key = root, self.parent[key]
        return root

    def union(self, a: str, b: str) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[ra] = rb

    def same(self, a: str, b: str) -> bool:
        return self.find(a) == self.find(b)


class ConcurrencyModel:
    """The module facts every flow rule consumes: which identifiers are
    bound (possibly through aliases) to queues/locks/threads, where each
    thread is created and whether anything in its alias chain is ever
    joined, and which functions execute on a worker thread."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.aliases = _Aliases(ctx.tree)
        self.queue_ids: Set[str] = set()
        self.lock_ids: Set[str] = set()
        # thread creations: (Thread(...) call node, bound id or None)
        self.threads: List[Tuple[ast.Call, Optional[str]]] = []
        self.joined_ids: Set[str] = set()
        self._collect()

    def _ctor_kind(self, call: ast.Call) -> Optional[str]:
        name = self.ctx.dotted(call.func)
        if name in _THREAD_CTORS:
            return "thread"
        if name in _QUEUE_CTORS:
            return "queue"
        if name in _LOCK_CTORS:
            return "lock"
        return None

    def _collect(self) -> None:
        tree = self.ctx.tree
        for node in ast.walk(tree):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is not None and isinstance(value, ast.Call):
                kind = self._ctor_kind(value)
                if kind is not None:
                    ids = [i for t in targets for i in _target_ids(t)]
                    if kind == "queue":
                        self.queue_ids.update(ids)
                    elif kind == "lock":
                        self.lock_ids.update(ids)
                    else:
                        self.threads.append((value, ids[0] if ids else None))
        for node in ast.walk(tree):
            # bare `threading.Thread(...).start()` — never bindable, so
            # never joinable (track it with no id)
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Attribute) \
                    and isinstance(node.func.value, ast.Call) \
                    and self._ctor_kind(node.func.value) == "thread" \
                    and node.func.attr == "start":
                self.threads.append((node.func.value, None))
            # join sites: `x.join(...)` where the receiver is an
            # identifier (str.join on literals never is)
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Attribute) \
                    and node.func.attr == "join":
                rid = _receiver_id(node.func.value)
                if rid is not None:
                    self.joined_ids.add(rid)

    # ------------------------------------------------------------ queries
    def is_queue(self, receiver: ast.AST) -> bool:
        rid = _receiver_id(receiver)
        return rid is not None and any(self.aliases.same(rid, q)
                                       for q in self.queue_ids)

    def is_lock_expr(self, expr: ast.AST) -> bool:
        rid = _receiver_id(expr)
        if rid is not None:
            return any(self.aliases.same(rid, l) for l in self.lock_ids)
        # `with self._lock.acquire()`-ish / `with lock() as ...` shapes
        if isinstance(expr, ast.Call):
            return self.is_lock_expr(expr.func.value) \
                if isinstance(expr.func, ast.Attribute) else False
        return False

    def thread_joined(self, bound_id: Optional[str]) -> bool:
        if bound_id is None:
            return False
        return any(self.aliases.same(bound_id, j) for j in self.joined_ids)

    # -------------------------------------------------- worker reachability
    def worker_functions(self) -> List[ast.FunctionDef]:
        """Function defs that execute on a worker thread: every
        ``Thread(target=...)`` target resolved to a def in this module,
        plus same-class methods transitively called as ``self.m()`` from
        one — the interprocedural step that pins LearnerStream.replays."""
        ctx = self.ctx
        by_name: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)

        seeds: List[ast.FunctionDef] = []
        for call, _ in self.threads:
            target = next((kw.value for kw in call.keywords
                           if kw.arg == "target"), None)
            if target is None:
                continue
            if isinstance(target, ast.Name):
                seeds.extend(by_name.get(target.id, []))
            elif isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self":
                seeds.extend(f for f in by_name.get(target.attr, [])
                             if self._same_class(f, call))

        reached: List[ast.FunctionDef] = []
        frontier = list(seeds)
        while frontier:
            fn = frontier.pop()
            if fn in reached:
                continue
            reached.append(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self":
                    for cand in by_name.get(node.func.attr, []):
                        if self._same_class(cand, fn):
                            frontier.append(cand)
        return reached

    def _enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = self.ctx.parent(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.ctx.parent(cur)
        return None

    def _same_class(self, a: ast.AST, b: ast.AST) -> bool:
        ca, cb = self._enclosing_class(a), self._enclosing_class(b)
        return ca is not None and ca is cb


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------
_MODEL_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _concurrency_model(ctx: ModuleContext) -> ConcurrencyModel:
    """One ConcurrencyModel per module, shared by the three rules that
    consume it (building it walks the full AST several times)."""
    model = _MODEL_CACHE.get(ctx)
    if model is None:
        model = ConcurrencyModel(ctx)
        _MODEL_CACHE[ctx] = model
    return model


class FlowRule:
    rule_id: str = ""
    description: str = ""
    hint: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(ctx.path, getattr(node, "lineno", 1), self.rule_id,
                       message, hint or self.hint, ctx.scope_of(node))


class UnboundedQueueGetRule(FlowRule):
    """``X.get()`` with no timeout (and not ``block=False``) on a
    receiver whose alias chain holds a ``queue.Queue``. The blocked
    thread hangs forever if the producer dies or the sentinel is lost —
    from outside, indistinguishable from a hung device. Dict ``.get``
    never fires: the receiver must be queue-typed in the module's
    dataflow."""

    rule_id = "flow-unbounded-queue-get"
    description = "queue.get() with no timeout can block forever"
    hint = ("get(timeout=...) in a loop that re-checks a shutdown flag / "
            "worker liveness (see LearnerStream.start and "
            "core.stream._Prefetcher.__next__), or get_nowait() + backoff")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        model = _concurrency_model(ctx)
        if not model.queue_ids:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr != "get":
                continue
            if node.args or any(kw.arg in ("timeout", "block")
                                for kw in node.keywords):
                continue
            if model.is_queue(node.func.value):
                yield self.finding(
                    ctx, node,
                    "bare queue .get() blocks forever if the producer "
                    "dies or the shutdown sentinel is lost — a hang the "
                    "bench watcher cannot tell from a chip flap")


class UnjoinedThreadRule(FlowRule):
    """A ``threading.Thread`` that nothing in its assignment-alias chain
    ever ``.join()``s. At interpreter shutdown a daemon worker is killed
    mid-block — for the prefetch pipeline that is silent output
    truncation; for a non-daemon it is a leak that outlives the job."""

    rule_id = "flow-unjoined-thread"
    description = "thread started but never joined anywhere in the module"
    hint = ("bind the Thread, join it on the owner's stop()/close() path "
            "(alias-chain joins like `t, self.t = self.t, None; t.join()` "
            "count), and verify is_alive() after a bounded join")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        model = _concurrency_model(ctx)
        for call, bound_id in model.threads:
            if model.thread_joined(bound_id):
                continue
            what = (f"thread bound to `{bound_id.lstrip('.')}`"
                    if bound_id else "unbound thread (Thread(...).start())")
            yield self.finding(
                ctx, call,
                f"{what} is never joined: shutdown kills the worker "
                f"mid-block (silent truncation) or leaks it past the job")


class SharedStateUnlockedRule(FlowRule):
    """Public ``self.`` attributes mutated from worker-thread-reachable
    code (the ``Thread(target=...)`` function and every same-class
    method it transitively calls) without holding a module-known lock.
    A public attribute is caller-readable by contract, so the mutation
    races every caller read. Queue attributes are exempt — a queue IS
    the sanctioned handoff — as are mutations lexically inside a
    ``with <lock>:`` block."""

    rule_id = "flow-shared-state-unlocked"
    description = "worker thread mutates caller-visible state without a lock"
    hint = ("guard the mutation (and the caller-facing reads) with a "
            "threading.Lock held attribute, or hand the data over a queue "
            "instead of sharing the field")

    def _under_lock(self, ctx: ModuleContext, model: ConcurrencyModel,
                    node: ast.AST) -> bool:
        cur = ctx.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.With, ast.AsyncWith)) and any(
                    model.is_lock_expr(item.context_expr)
                    for item in cur.items):
                return True
            cur = ctx.parent(cur)
        return False

    def _mutated_attr(self, node: ast.AST) -> Optional[str]:
        """Public self-attr a statement/call mutates, else None."""
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                rid = _receiver_id(base)
                if rid is not None and rid.startswith("."):
                    return rid[1:]
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            rid = _receiver_id(node.func.value)
            if rid is not None and rid.startswith("."):
                return rid[1:]
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        model = _concurrency_model(ctx)
        if not model.threads:
            return
        workers = model.worker_functions()
        seen: Set[Tuple[str, str]] = set()
        for fn in workers:
            for node in ast.walk(fn):
                attr = self._mutated_attr(node)
                if attr is None or attr.startswith("_"):
                    continue
                if model.is_queue(ast.Attribute(
                        value=ast.Name(id="self"), attr=attr)):
                    continue
                if self._under_lock(ctx, model, node):
                    continue
                key = (fn.name, attr)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, node,
                    f"worker-reachable `{fn.name}` mutates public "
                    f"`self.{attr}` without a lock: callers reading it "
                    f"race the worker (torn reads, lost updates)")


def _fold_loops(ctx: ModuleContext) -> Iterator[ast.For]:
    """`for` statements iterating a chunk/fold source (double_buffered,
    prefetched, stream_job_*) — the loops whose bodies are supposed to
    overlap with the producer thread."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor)):
            continue
        for sub in ast.walk(node.iter):
            if isinstance(sub, ast.Call):
                name = ctx.dotted(sub.func)
                if name is not None \
                        and name.rpartition(".")[2] in _FOLD_SOURCES:
                    yield node
                    break


def _body_nodes(loop: ast.For) -> Iterator[ast.AST]:
    """Nodes in the loop body, not descending into nested defs (their
    statements run when called, not per-chunk)."""
    stack = list(loop.body)
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


class BlockingIoInFoldRule(FlowRule):
    """File/Redis/process IO inside the body of a fold loop over a
    prefetched/double-buffered source. The fold body is the overlap
    window — device compute on block k while the host parses k+1; a
    blocking syscall there serializes the pipeline the double buffer
    exists to overlap (and the bench reads it as device slowness)."""

    rule_id = "flow-blocking-io-in-fold"
    description = "blocking host IO inside a streamed fold body"
    hint = ("hoist the IO out of the fold (open before, write after — "
            "accumulate per-chunk results and flush once), or move it "
            "into the producer side where the prefetch thread absorbs it")

    IO_CALLS = {"open", "os.system", "subprocess.run", "subprocess.Popen",
                "subprocess.call", "subprocess.check_output",
                "subprocess.check_call", "time.sleep", "socket.create_connection"}
    IO_TAILS = {"rpop", "lpush", "rpush", "brpop", "blpop", "flushall",
                "urlopen"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in _fold_loops(ctx):
            for node in _body_nodes(loop):
                if not isinstance(node, ast.Call):
                    continue
                name = ctx.dotted(node.func)
                if name is None:
                    continue
                if name in self.IO_CALLS \
                        or name.rpartition(".")[2] in self.IO_TAILS:
                    yield self.finding(
                        ctx, node,
                        f"`{name}` inside a streamed fold body blocks the "
                        f"consumer once per chunk, serializing the "
                        f"double-buffered encode/count overlap")


class OrderSensitiveFoldRule(FlowRule):
    """A float accumulator folded across streamed chunks
    (``acc += ...`` / ``acc = acc + ...`` in a fold loop, where `acc`
    was initialized float in the same function). Float addition is not
    associative: the result depends on where the chunk boundaries fall,
    so the job's output changes with block size — the bit-reproducibility
    the chunk-invariance auditor exists to pin. Integer-dtype
    accumulators are exact under any grouping and stay silent."""

    rule_id = "flow-order-sensitive-fold"
    description = "float accumulation across chunks depends on chunk layout"
    hint = ("accumulate exact values (integer dtype, or integer-valued "
            "floats within the documented exactness bound — see "
            "NaiveBayesModel._FLUSH_ROWS), or register the kernel in the "
            "chunk-invariance manifest and accept allclose, not bytes")

    _FLOAT_DTYPES = {"float16", "float32", "float64", "bfloat16"}
    _CTORS = {"zeros", "ones", "empty", "full", "zeros_like", "ones_like"}

    def _float_inits(self, ctx: ModuleContext, fn: ast.AST) -> Set[str]:
        """Names bound in `fn` (not nested defs) to a float-default or
        explicitly-float initializer."""
        out: Set[str] = set()
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            if self._is_float_init(ctx, node.value):
                out.add(node.targets[0].id)
        return out

    def _is_float_init(self, ctx: ModuleContext, value: ast.AST) -> bool:
        if isinstance(value, ast.Constant):
            return isinstance(value.value, float)
        if not isinstance(value, ast.Call):
            return False
        name = ctx.dotted(value.func)
        if name is None:
            return False
        mod, _, func = name.rpartition(".")
        if mod not in ("numpy", "jax.numpy") or func not in self._CTORS:
            return False
        dtype = next((kw.value for kw in value.keywords
                      if kw.arg == "dtype"), None)
        if dtype is None and len(value.args) > 1 and func != "full":
            dtype = value.args[1]
        if dtype is None and len(value.args) > 2 and func == "full":
            dtype = value.args[2]
        if dtype is None:
            # numpy's dtype-less constructors default to float64
            # (jnp to float32): a float accumulator either way
            return func != "full" or not value.args or not isinstance(
                value.args[-1], ast.Constant) or isinstance(
                value.args[-1].value, float)
        dname = ctx.dotted(dtype)
        if dname is not None:
            return dname.rpartition(".")[2] in self._FLOAT_DTYPES
        return isinstance(dtype, ast.Constant) \
            and str(dtype.value) in self._FLOAT_DTYPES

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in _fold_loops(ctx):
            owners = ctx.enclosing_functions(loop)
            owner = owners[0] if owners else ctx.tree
            floats = self._float_inits(ctx, owner)
            if not floats:
                continue
            for node in _body_nodes(loop):
                name: Optional[str] = None
                if isinstance(node, ast.AugAssign) \
                        and isinstance(node.target, ast.Name) \
                        and isinstance(node.op, ast.Add):
                    name = node.target.id
                elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.BinOp) \
                        and isinstance(node.value.op, ast.Add) \
                        and isinstance(node.value.left, ast.Name) \
                        and node.value.left.id == node.targets[0].id:
                    name = node.targets[0].id
                if name in floats:
                    yield self.finding(
                        ctx, node,
                        f"float accumulator `{name}` folds streamed "
                        f"chunks: addition reassociates with the chunk "
                        f"layout, so the result changes with block size")


ALL_FLOW_RULES = [UnboundedQueueGetRule, UnjoinedThreadRule,
                  SharedStateUnlockedRule, BlockingIoInFoldRule,
                  OrderSensitiveFoldRule]


def flow_rule_ids() -> List[str]:
    return [r.rule_id for r in ALL_FLOW_RULES] + [FLOW_AUDIT_RULE]


# --------------------------------------------------------------------------
# chunk-invariance auditor
# --------------------------------------------------------------------------
@contextmanager
def _stream_hook(fn):
    """Install `fn` as the core.stream producer hook for the duration."""
    from avenir_tpu.core import stream

    prev = stream._produce_hook
    stream._produce_hook = fn
    try:
        yield
    finally:
        stream._produce_hook = prev


class _ChunkCounter:
    """Counts items produced by every prefetched() worker during a run —
    the mechanical proof that two layouts actually chunked differently
    (an auditor comparing two single-chunk runs validates nothing)."""

    def __init__(self):
        self.n = 0
        self._lock = threading.Lock()

    def __call__(self) -> None:
        with self._lock:
            self.n += 1


class _AdversarialScheduler:
    """Deterministically-seeded jitter injected into every prefetch
    producer: each produced item is delayed 0-3ms, so queue occupancy,
    thread interleaving and consumer wait patterns all differ from the
    serial run. The fold's OUTPUT must not."""

    def __init__(self, seed: int):
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def __call__(self) -> None:
        with self._lock:
            delay = self._rng.random() * 0.003
        time.sleep(delay)


def audit_stream(spec) -> Tuple[dict, Optional[Finding]]:
    """Run one streamed fold kernel under every chunk layout in its spec
    plus the adversarial scheduler, and compare output artifacts
    byte-for-byte. Returns (audit row, invariance finding or None)."""
    workdir = tempfile.mkdtemp(prefix=f"graftlint_flow_{spec.name}_")
    try:
        ctx = spec.prepare(workdir)
        outputs: List[bytes] = []
        chunk_counts: List[int] = []
        for mb in spec.layouts:
            counter = _ChunkCounter()
            with _stream_hook(counter):
                outputs.append(spec.run(ctx, mb))
            chunk_counts.append(counter.n)
        sched = _AdversarialScheduler(seed=len(spec.name) * 7919 + 17)
        with _stream_hook(sched):
            adversarial = spec.run(ctx, spec.layouts[-1])
    except FlowAuditError:
        raise
    except Exception as e:
        raise FlowAuditError(f"{spec.name}: stream kernel failed to run: "
                             f"{e!r}") from e
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    layouts_ok = all(o == outputs[0] for o in outputs[1:])
    scheduler_ok = adversarial == outputs[0]
    distinct = len(set(chunk_counts)) >= 2
    row = {
        "kernel": spec.name,
        "layouts_mb": [float(mb) for mb in spec.layouts],
        "chunk_counts": chunk_counts,
        "layouts_distinct": distinct,
        "layouts_byte_identical": layouts_ok,
        "scheduler_byte_identical": scheduler_ok,
        "invariance_validated": layouts_ok and scheduler_ok and distinct,
    }
    finding = None
    if not row["invariance_validated"]:
        why = ("chunk layouts did not differ (auditor corpus too small "
               "for its block sizes)" if not distinct else
               "output bytes drift with the chunk layout" if not layouts_ok
               else "output bytes drift under the adversarial scheduler")
        finding = Finding(
            spec.path, spec.line, FLOW_AUDIT_RULE,
            f"streamed kernel `{spec.name}` is not chunk-invariant: {why} "
            f"(chunk counts {chunk_counts})",
            "make the fold exact (integer counts / bounded-exact floats) "
            "or fix the corpus so layouts differ; never allowlist a "
            "non-deterministic fold",
            spec.name)
    return row, finding


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------
def default_flow_paths(root: str) -> List[str]:
    """The gated repo surface, mirroring tests/test_graftlint.py: the
    package plus every host-side caller of it."""
    names = ["avenir_tpu", "tests", "docs", "tools", "bench.py",
             "bench_scaling.py", "__graft_entry__.py"]
    return [p for p in (os.path.join(root, n) for n in names)
            if os.path.exists(p)]


def run_flow(paths: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[FlowRule]] = None,
             baseline: Optional[Sequence[BaselineEntry]] = None,
             root: Optional[str] = None, include_md: bool = True,
             audit: bool = True, entries: Optional[Sequence] = None
             ) -> Report:
    """Lint `paths` (default: the gated repo surface) with the flow
    rules, run the chunk-invariance auditor over the streamed-kernel
    manifest, and apply the allowlist baseline to both finding sets."""
    active = list(rules) if rules is not None else \
        [r() for r in ALL_FLOW_RULES]
    root = os.path.abspath(root or os.getcwd())
    scan = list(paths) if paths else default_flow_paths(root)
    report, raw = collect_findings(scan, active, root, include_md)
    if audit:
        specs = list(entries) if entries is not None else None
        if specs is None:
            from avenir_tpu.analysis.manifest import stream_entries
            specs = stream_entries()
        for spec in specs:
            # NOT added to report.scanned: the audit doesn't lint the
            # kernel's file, and claiming it scanned would falsely stale
            # flow-rule baseline entries for manifest modules whenever an
            # explicit path subset excludes them
            row, finding = audit_stream(spec)
            report.invariance_audit.append(row)
            if finding is not None:
                raw.append(finding)
    active_ids = {r.rule_id for r in active}
    if audit:
        active_ids.add(FLOW_AUDIT_RULE)
    apply_baseline(report, raw, baseline, active_ids)
    return report
