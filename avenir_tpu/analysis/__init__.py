"""graftlint: AST-based JAX/TPU hazard analysis for this repo.

PR 1 won its miner speedups by hand-hunting accidental int64 temporaries,
host-sync points and recompile hazards; this package finds the same code
shapes mechanically (the Casper move, arXiv:1801.09802: treat the shapes
worth rewriting as a statically recognizable class, not archaeology).

Entry points:
  - ``python tools/graftlint.py <paths>`` / the ``graftlint`` console
    script (avenir_tpu.analysis.cli) — text or ``--json`` output;
    ``graftlint --ir`` runs the IR layer instead of source paths;
  - :func:`run_paths` — the in-process AST API (tests/test_graftlint.py
    runs it over the whole package; bench_scaling.py tripwires on its
    counts);
  - ``avenir_tpu.analysis.ir.run_ir`` — the IR layer: jaxpr rules +
    the distributed-family collective-payload audit over the kernel
    manifest (``avenir_tpu.analysis.manifest``). Imported lazily, never
    from this package root: AST mode must not pull in jax;
  - ``avenir_tpu.analysis.flow.run_flow`` — the flow layer
    (``graftlint --flow``): interprocedural concurrency/determinism
    rules over the host streaming surface + the chunk-invariance audit
    of the manifest's streamed fold kernels (jax pulled in only when
    the audit actually runs);
  - ``avenir_tpu.analysis.mem.run_mem`` — the mem layer
    (``graftlint --mem``): memory-footprint rules + the analytic
    footprint model and its mechanical RSS auditor, which proves the
    model against sampled peak RSS for every streamed job at >= 2
    block sizes (``mem.memory_manifest()`` exports the machine-
    readable admission oracle);
  - ``avenir_tpu.analysis.merge.run_merge`` — the merge layer
    (``graftlint --merge``): fold-state merge-algebra rules + the
    mechanical shard-merge/resume auditor, which proves every streamed
    job's carry merges across P ∈ {2, 4} shards and checkpoint-resumes
    byte-identically through the registered ``runner.StreamFoldOps``;
  - ``avenir_tpu.analysis.proto.run_proto`` — the proto layer
    (``graftlint --proto``): shared-filesystem protocol-discipline
    rules + the commit-point crash auditor, which hard-kills a real
    publish per registered commit site and proves recovery
    byte-identical;
  - ``avenir_tpu.analysis.race.run_race`` — the race layer
    (``graftlint --race``): cross-process race rules + the
    deterministic-interleaving explorer, which steps two real actor
    subprocesses through every registered interleave site's
    ``sched_point`` schedule space and proves exactly-one-winner /
    conservation / solo byte-identity per schedule, every failure a
    replayable ``--schedule`` trace;
  - ``avenir_tpu.analysis.keys.run_keys`` — the keys layer
    (``graftlint --keys``): cache-key completeness rules + the
    stale-serve perturbation auditor, which seeds every registered
    key site's cache, moves each registered input dimension one at a
    time, and proves view-affecting changes move the key with served
    bytes equal to a cold recompute, view-neutral changes warm-hit
    byte-identically, and version-skewed manifests refuse-and-go-cold
    (``graftlint --all`` runs all eight tiers with one worst-of exit;
    ``--all --parallel`` fans them out as subprocesses);
  - ``graftlint_baseline.txt`` — the allowlist: accepted findings keyed
    by ``path::rule::scope`` with a one-line justification each, shared
    by both modes.

See docs/graftlint.md for the rule catalogs and allowlisting policy.
"""

from avenir_tpu.analysis.engine import (Finding, Report, default_baseline_path,
                                        load_baseline, run_paths)
from avenir_tpu.analysis.rules import ALL_RULES, rule_ids

__all__ = ["Finding", "Report", "run_paths", "load_baseline",
           "default_baseline_path", "ALL_RULES", "rule_ids"]
