"""graftlint CLI: `graftlint <paths>` (console script) or
`python tools/graftlint.py <paths>`.

Eight modes sharing one report/baseline/exit contract, plus ``--all``:

- AST (default): lint source paths with the rules.py catalog.
- IR (``--ir``, no paths): trace the kernel manifest
  (analysis/manifest.py), run the jaxpr rules and the collective-payload
  audit (analysis/ir.py) on the virtual 8-device mesh.
- Flow (``--flow``, paths optional — defaults to the gated repo
  surface): the host concurrency/determinism rules (analysis/flow.py)
  plus the chunk-invariance audit of the streamed fold kernels
  (manifest ``stream_entries()``).
- Mem (``--mem``, paths optional — same default surface): the memory-
  footprint rules (analysis/mem.py) plus the RSS/live-bytes footprint
  audit that proves the analytic memory model against sampled peak RSS
  for every streamed job at >= 2 block sizes.
- Merge (``--merge``, paths optional — same default surface): the
  fold-state merge-algebra rules (analysis/merge.py) plus the
  shard-merge/resume audit proving every streamed job's carry merges
  across P ∈ {2, 4} shards and checkpoint-resumes byte-identically.
- Proto (``--proto``, paths optional — defaults to the shared-
  filesystem protocol surface): the publish/read protocol-discipline
  rules (analysis/proto.py) plus the commit-point crash auditor that
  hard-kills a real publish per registered commit site at
  before-rename and after-rename and proves recovery byte-identical.
- Race (``--race``, paths optional — defaults to the multi-writer
  protocol surface): the cross-process race rules (analysis/race.py)
  plus the deterministic-interleaving explorer that steps two real
  actor subprocesses through every registered interleave site's
  sched_point schedule space and proves exactly-one-winner /
  conservation / solo byte-identity per schedule. A failing schedule
  prints a replayable trace; ``--schedule <site>:<digits>`` replays
  exactly that interleaving.
- Keys (``--keys``, paths optional — defaults to the cache-key
  surface): the cache-key completeness rules (analysis/keys.py) plus
  the stale-serve perturbation auditor that seeds every registered
  key site's cache cold, perturbs each registered input dimension one
  at a time, and proves view-affecting changes move the key with
  served bytes equal to a cold recompute, view-neutral changes keep
  the key and warm-hit byte-identically, and version-skewed manifests
  refuse-and-go-cold. A stale serve surfaces as ``keys-stale-serve``
  and is never allowlistable.
- All (``--all``): the eight tiers in ONE process — combined JSON
  under a ``modes`` key (each tier's report carries its ``wall_s``)
  and a single worst-of exit code (one command for CI and the bench
  tripwire's local reproduction). ``--all --parallel`` fans the tiers
  out as subprocesses — same combined JSON, same worst-of exit, the
  wall clock of the slowest tier instead of the sum.

Exit-code contract (stable — bench_scaling.py and CI tripwire on it):
  0  clean: no findings, no stale baseline entries, no parse errors
  1  findings — non-allowlisted findings, stale baseline entries, or
     parse errors in the linted sources
  2  usage-or-trace-error — bad flags/baseline format/unreadable input,
     a manifest entry that failed to trace/lower (--ir), a stream
     kernel that failed to run (--flow / --mem / --merge), a crash
     child / commit-site registry failure (--proto), an actor pool
     / scheduler / interleave-site registry failure (--race), or a
     perturbation driver / key-site registry failure (--keys)
``--all`` exits with the WORST code any tier produced.

`--json` prints one machine-readable object in every single-tier mode
(same schema: `payload_audit` is empty outside --ir, `invariance_audit`
outside --flow, `footprint_audit` outside --mem, `merge_audit` outside
--merge, `proto_audit` outside --proto, `race_audit` outside --race,
`key_audit` outside --keys);
``--all --json`` prints ``{"modes": {<tier>: <report>},
"clean": bool}`` with every tier's report under its name.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from avenir_tpu.analysis.engine import (default_baseline_path, load_baseline,
                                        run_paths)
from avenir_tpu.analysis.rules import ALL_RULES, rule_ids

#: the eight analysis tiers, in audit-cost order (cheapest first)
TIERS = ("ast", "ir", "flow", "mem", "merge", "proto", "race", "keys")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST + IR JAX/TPU hazard analyzer (rule catalog: "
                    "docs/graftlint.md)")
    p.add_argument("paths", nargs="*",
                   help=".py/.md files or directories to lint (omit with "
                        "--ir)")
    p.add_argument("--ir", action="store_true",
                   help="lint the traceable-kernel manifest instead of "
                        "source paths: jaxpr rules + the distributed-family "
                        "collective-payload audit on the virtual 8-device "
                        "mesh")
    p.add_argument("--flow", action="store_true",
                   help="host concurrency/determinism analysis: the flow-* "
                        "rules over the paths (default: the gated repo "
                        "surface) + the chunk-invariance audit of the "
                        "streamed fold kernels")
    p.add_argument("--mem", action="store_true",
                   help="memory-footprint analysis: the mem-* rules over "
                        "the paths (default: the gated repo surface) + the "
                        "RSS footprint audit proving the analytic memory "
                        "model for every streamed job at >= 2 block sizes")
    p.add_argument("--merge", action="store_true",
                   help="fold-state merge-algebra analysis: the merge-* "
                        "rules over the paths (default: the gated repo "
                        "surface) + the shard-merge/resume audit proving "
                        "every streamed job's carry merges across shards "
                        "and checkpoint-resumes byte-identically")
    p.add_argument("--proto", action="store_true",
                   help="shared-filesystem protocol-discipline analysis: "
                        "the proto-* rules over the paths (default: the "
                        "protocol surface) + the commit-point crash audit "
                        "that hard-kills a real publish per registered "
                        "commit site at before-rename and after-rename and "
                        "proves recovery byte-identical with no stranded "
                        "tmp")
    p.add_argument("--race", action="store_true",
                   help="cross-process race analysis: the race-* rules "
                        "over the paths (default: the multi-writer "
                        "protocol surface) + the deterministic-"
                        "interleaving explorer that steps two real actor "
                        "subprocesses through every registered interleave "
                        "site's schedule space and proves exactly-one-"
                        "winner / conservation / solo byte-identity per "
                        "schedule")
    p.add_argument("--keys", action="store_true",
                   help="cache-key completeness analysis: the keys-* "
                        "rules over the paths (default: the cache-key "
                        "surface) + the stale-serve perturbation audit "
                        "that moves every registered input dimension of "
                        "every registered key site one at a time and "
                        "proves affecting changes move the key with "
                        "warm-served bytes equal to a cold recompute, "
                        "neutral changes warm-hit byte-identically, and "
                        "version-skewed manifests refuse-and-go-cold")
    p.add_argument("--schedule", default=None, metavar="SITE:DIGITS",
                   help="with --race: replay exactly one interleaving "
                        "trace (as printed by a failing schedule), e.g. "
                        "ledger.claim:01101")
    p.add_argument("--all", action="store_true", dest="all_tiers",
                   help="run all eight tiers in one process: combined "
                        "JSON (modes keyed by tier) and a single "
                        "worst-of exit code")
    p.add_argument("--parallel", action="store_true",
                   help="with --all: fan the tiers out as subprocesses "
                        "(same combined JSON and worst-of exit; per-tier "
                        "wall_s recorded either way)")
    p.add_argument("--baseline", default=None,
                   help="allowlist file (default: "
                        "avenir_tpu/analysis/graftlint_baseline.txt)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the allowlist")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON object instead of text")
    p.add_argument("--rules", default=None, metavar="ID[,ID...]",
                   help=f"comma-separated subset of: {', '.join(rule_ids())} "
                        f"(or the ir-* ids with --ir, the flow-* ids with "
                        f"--flow, the mem-* ids with --mem, the merge-* ids "
                        f"with --merge, the proto-* ids with --proto, the "
                        f"race-* ids with --race, the keys-* ids with "
                        f"--keys; --all accepts ids from "
                        f"any tier and skips tiers with none selected)")
    p.add_argument("--no-md", action="store_true",
                   help="skip ```python fences in .md files")
    p.add_argument("--allow-stale", action="store_true",
                   help="do not fail on baseline entries that no longer "
                        "match (use only while mid-refactor)")
    return p


def _bootstrap_ir_env() -> None:
    """Pin a CPU platform with enough virtual devices for the audit mesh
    BEFORE jax initializes (harmless no-op when the caller — e.g. the
    tier-1 test process — already initialized a big-enough pool).

    An inherited ``--xla_force_host_platform_device_count`` SMALLER than
    the audit needs is raised, not honored: callers like bench_scaling
    legitimately export a small pool for their own mesh, and inheriting
    it would turn a clean audit into a spurious trace error.
    ``GRAFTLINT_IR_DEVICES`` overrides the target pool size explicitly
    (the too-small-pool CLI test uses it; a real run never should)."""
    from avenir_tpu.analysis.manifest import AUDIT_DEVICES

    if "jax" in sys.modules:
        return                       # too late; run_ir checks the pool size
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    want = AUDIT_DEVICES
    flag = "--xla_force_host_platform_device_count"
    flags = []
    for f in os.environ.get("XLA_FLAGS", "").split():
        if f.startswith(flag):
            try:
                want = max(want, int(f.split("=", 1)[1]))
            except (IndexError, ValueError):
                pass
        else:
            flags.append(f)
    override = os.environ.get("GRAFTLINT_IR_DEVICES")
    if override is not None:
        want = int(override)         # explicit override beats everything
    flags.append(f"{flag}={want}")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def _report_root(args) -> Optional[str]:
    # finding keys must be cwd-independent so the baseline matches from
    # anywhere: anchor them to the repo root (the default baseline sits at
    # <root>/avenir_tpu/analysis/) or to an explicit baseline's directory
    if args.baseline:
        return os.path.dirname(os.path.abspath(args.baseline))
    if args.no_baseline:
        return None                  # cwd: keys are ephemeral anyway
    return os.path.dirname(os.path.dirname(os.path.dirname(
        default_baseline_path())))


def _print_report(report, is_ir: bool) -> None:
    for f in report.errors + report.findings:
        print(f.render())
    for e in report.stale:
        print(f"stale baseline entry (line {e.lineno}): {e.key} — the "
              f"finding it excused is gone; delete it", file=sys.stderr)
    unit = "kernel modules" if is_ir else "files"
    tail = ""
    if report.payload_audit:
        ok = sum(1 for a in report.payload_audit
                 if a["payload_model_validated"])
        tail = (f", payload audit {ok}/{len(report.payload_audit)} "
                f"families validated")
    if report.invariance_audit:
        ok = sum(1 for a in report.invariance_audit
                 if a["invariance_validated"])
        tail += (f", chunk-invariance audit {ok}/"
                 f"{len(report.invariance_audit)} stream kernels "
                 f"validated")
    if report.footprint_audit:
        ok = sum(1 for a in report.footprint_audit
                 if a["footprint_model_validated"])
        tail += (f", footprint audit {ok}/"
                 f"{len(report.footprint_audit)} streamed jobs "
                 f"validated")
    if report.merge_audit:
        ok = sum(1 for a in report.merge_audit if a["merge_validated"])
        tail += (f", merge audit {ok}/{len(report.merge_audit)} "
                 f"stream kernels validated")
    if report.proto_audit:
        ok = sum(1 for a in report.proto_audit
                 if a["commit_point_validated"])
        tail += (f", commit-point audit {ok}/"
                 f"{len(report.proto_audit)} commit sites validated")
    if report.race_audit:
        ok = sum(1 for a in report.race_audit
                 if a["interleaving_validated"])
        n_sched = sum(sum(a["schedules"].values())
                      for a in report.race_audit)
        tail += (f", interleaving audit {ok}/"
                 f"{len(report.race_audit)} sites validated over "
                 f"{n_sched} schedules")
    if report.key_audit:
        ok = sum(1 for a in report.key_audit if a["key_validated"])
        n_pert = sum(sum(a["perturbations"].values())
                     for a in report.key_audit)
        tail += (f", key-perturbation audit {ok}/"
                 f"{len(report.key_audit)} sites validated over "
                 f"{n_pert} perturbations")
    print(f"graftlint: {len(report.scanned)} {unit}, "
          f"{len(report.findings)} finding(s), "
          f"{len(report.suppressed)} allowlisted, "
          f"{len(report.stale)} stale baseline entr(y/ies)"
          + (f", {len(report.errors)} parse error(s)"
             if report.errors else "") + tail)


def _exit_code(report, args) -> int:
    if report.findings or report.errors:
        return 1
    if report.stale and not args.allow_stale:
        return 1
    return 0


def _tier_rule_ids() -> dict:
    """Every tier's known rule ids (audit pseudo-rules included) —
    the skip decision for a ``--rules`` subset, shared by the
    sequential and ``--parallel`` fan-outs."""
    from avenir_tpu.analysis.flow import flow_rule_ids
    from avenir_tpu.analysis.ir import ir_rule_ids
    from avenir_tpu.analysis.mem import mem_rule_ids
    from avenir_tpu.analysis.merge import merge_rule_ids
    from avenir_tpu.analysis.keys import keys_rule_ids
    from avenir_tpu.analysis.proto import proto_rule_ids
    from avenir_tpu.analysis.race import race_rule_ids

    return {"ast": rule_ids(), "ir": ir_rule_ids(),
            "flow": flow_rule_ids(), "mem": mem_rule_ids(),
            "merge": merge_rule_ids(), "proto": proto_rule_ids(),
            "race": race_rule_ids(), "keys": keys_rule_ids()}


def _run_all_parallel(args, wanted: Optional[List[str]]) -> int:
    """The ``--all --parallel`` mode: one subprocess per tier, same
    combined JSON (each tier's report under ``modes`` with its
    measured ``wall_s``) and the same worst-of exit as the sequential
    ``--all`` — but the wall clock of the slowest tier instead of the
    sum. Tier subprocesses re-enter this CLI in single-tier --json
    mode, so the per-tier contract is exactly the documented one."""
    import subprocess
    import time

    known = _tier_rule_ids()
    modes = {}
    worst = 0
    procs = []
    for name in TIERS:
        sub_wanted = None
        if wanted is not None:
            sub_wanted = [w for w in wanted if w in known[name]]
            if not sub_wanted:
                modes[name] = {"skipped": True}
                continue
        argv = [sys.executable, "-m", "avenir_tpu.analysis.cli",
                "--json"]
        if name == "ast":
            argv.extend(args.paths or _default_surface())
        else:
            argv.append(f"--{name}")
            if args.paths and name != "ir":
                argv.extend(args.paths)
        if args.no_baseline:
            argv.append("--no-baseline")
        elif args.baseline:
            argv.extend(["--baseline", args.baseline])
        if args.no_md:
            argv.append("--no-md")
        if args.allow_stale:
            argv.append("--allow-stale")
        if sub_wanted is not None:
            argv.extend(["--rules", ",".join(sub_wanted)])
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # -m avenir_tpu.analysis.cli must resolve even when the parent
        # was launched from outside the checkout (tools/graftlint.py
        # patches sys.path, which children don't inherit)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = os.pathsep.join(
            [pkg_root] + [p for p in env.get("PYTHONPATH", "").split(
                os.pathsep) if p])
        procs.append((name, time.monotonic(), subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=env, text=True)))
    for name, t0, proc in procs:
        out, err = proc.communicate()
        wall = time.monotonic() - t0
        if proc.returncode not in (0, 1):
            tail = (err or out).strip()[-400:]
            print(f"graftlint [{name}]: {tail}", file=sys.stderr)
            modes[name] = {"error": tail, "wall_s": round(wall, 3)}
            worst = 2
            continue
        try:
            rep = json.loads(out)
        except ValueError:
            print(f"graftlint [{name}]: unparsable tier output",
                  file=sys.stderr)
            modes[name] = {"error": "unparsable tier output",
                           "wall_s": round(wall, 3)}
            worst = 2
            continue
        rep["wall_s"] = round(wall, 3)
        modes[name] = rep
        worst = max(worst, proc.returncode)
        if not args.as_json:
            print(f"-- {name} ({wall:.2f}s): "
                  f"{len(rep.get('findings', []))} finding(s), "
                  f"clean={rep.get('clean')}")
    clean = worst == 0
    if args.as_json:
        print(json.dumps({"modes": modes, "clean": clean}, indent=1))
    else:
        print(f"graftlint --all --parallel: "
              f"{sum(1 for m in modes.values() if 'skipped' in m)} "
              f"tier(s) skipped, worst exit {worst}")
    return worst


def _run_all(args, baseline, wanted: Optional[List[str]]) -> int:
    """The ``--all`` mode: eight tiers, one process, worst-of exit.

    A ``--rules`` subset skips every tier it names no rules of (its
    audit included only when the tier's audit pseudo-rule is named), so
    fixture-level CI checks stay fast; the full run is what the bench
    tripwire executes every round."""
    if args.parallel:
        return _run_all_parallel(args, wanted)
    import time

    _bootstrap_ir_env()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from avenir_tpu.analysis.flow import (ALL_FLOW_RULES, FLOW_AUDIT_RULE,
                                          FlowAuditError, run_flow)
    from avenir_tpu.analysis.ir import (ALL_IR_RULES, IRTraceError,
                                        PAYLOAD_RULE, run_ir)
    from avenir_tpu.analysis.mem import (ALL_MEM_RULES, MEM_AUDIT_RULE,
                                         MemAuditError, run_mem)
    from avenir_tpu.analysis.merge import (ALL_MERGE_RULES, MERGE_AUDIT_RULE,
                                           MergeAuditError, run_merge)
    from avenir_tpu.analysis.proto import (ALL_PROTO_RULES, PROTO_AUDIT_RULE,
                                           ProtoAuditError, run_proto)
    from avenir_tpu.analysis.keys import (ALL_KEYS_RULES, KEYS_AUDIT_RULE,
                                          KeysAuditError, run_keys)
    from avenir_tpu.analysis.race import (ALL_RACE_RULES, RACE_AUDIT_RULE,
                                          RaceAuditError, run_race)

    paths = args.paths or None
    root = _report_root(args)
    md = not args.no_md

    def pick(rule_classes):
        if wanted is None:
            return [r() for r in rule_classes]
        return [r() for r in rule_classes if r.rule_id in wanted]

    def want_audit(audit_rule):
        return wanted is None or audit_rule in wanted

    modes = {}
    worst = 0
    runs = [
        ("ast", None, None,
         lambda: run_paths(paths or _default_surface(), rules=pick(ALL_RULES),
                           baseline=baseline, root=root, include_md=md),
         lambda: bool(pick(ALL_RULES))),
        ("ir", IRTraceError, "trace error",
         lambda: run_ir(rules=pick(ALL_IR_RULES), baseline=baseline,
                        audit=want_audit(PAYLOAD_RULE)),
         lambda: bool(pick(ALL_IR_RULES)) or want_audit(PAYLOAD_RULE)),
        ("flow", FlowAuditError, "stream audit error",
         lambda: run_flow(paths=paths, rules=pick(ALL_FLOW_RULES),
                          baseline=baseline, root=root, include_md=md,
                          audit=want_audit(FLOW_AUDIT_RULE)),
         lambda: bool(pick(ALL_FLOW_RULES)) or want_audit(FLOW_AUDIT_RULE)),
        ("mem", MemAuditError, "footprint audit error",
         lambda: run_mem(paths=paths, rules=pick(ALL_MEM_RULES),
                         baseline=baseline, root=root, include_md=md,
                         audit=want_audit(MEM_AUDIT_RULE)),
         lambda: bool(pick(ALL_MEM_RULES)) or want_audit(MEM_AUDIT_RULE)),
        ("merge", MergeAuditError, "merge audit error",
         lambda: run_merge(paths=paths, rules=pick(ALL_MERGE_RULES),
                           baseline=baseline, root=root, include_md=md,
                           audit=want_audit(MERGE_AUDIT_RULE)),
         lambda: bool(pick(ALL_MERGE_RULES)) or want_audit(MERGE_AUDIT_RULE)),
        ("proto", ProtoAuditError, "commit-point audit error",
         lambda: run_proto(paths=paths, rules=pick(ALL_PROTO_RULES),
                           baseline=baseline, root=root, include_md=md,
                           audit=want_audit(PROTO_AUDIT_RULE)),
         lambda: bool(pick(ALL_PROTO_RULES)) or want_audit(PROTO_AUDIT_RULE)),
        ("race", RaceAuditError, "interleaving audit error",
         lambda: run_race(paths=paths, rules=pick(ALL_RACE_RULES),
                          baseline=baseline, root=root, include_md=md,
                          audit=want_audit(RACE_AUDIT_RULE)),
         lambda: bool(pick(ALL_RACE_RULES)) or want_audit(RACE_AUDIT_RULE)),
        ("keys", KeysAuditError, "key-perturbation audit error",
         lambda: run_keys(paths=paths, rules=pick(ALL_KEYS_RULES),
                          baseline=baseline, root=root, include_md=md,
                          audit=want_audit(KEYS_AUDIT_RULE)),
         lambda: bool(pick(ALL_KEYS_RULES)) or want_audit(KEYS_AUDIT_RULE)),
    ]
    for name, err_cls, err_label, run, active in runs:
        if wanted is not None and not active():
            modes[name] = {"skipped": True}
            continue
        t0 = time.monotonic()
        try:
            report = run()
        except tuple(c for c in (err_cls, OSError) if c is not None) as e:
            label = err_label or "error"
            print(f"graftlint [{name}]: {label}: {e}", file=sys.stderr)
            modes[name] = {"error": str(e),
                           "wall_s": round(time.monotonic() - t0, 3)}
            worst = 2
            continue
        modes[name] = dict(report.to_json(),
                           wall_s=round(time.monotonic() - t0, 3))
        if not args.as_json:
            print(f"-- {name} " + "-" * (68 - len(name)))
            _print_report(report, is_ir=(name == "ir"))
        worst = max(worst, _exit_code(report, args))
    clean = worst == 0
    if args.as_json:
        print(json.dumps({"modes": modes, "clean": clean}, indent=1))
    else:
        print(f"graftlint --all: "
              f"{sum(1 for m in modes.values() if 'skipped' in m)} tier(s) "
              f"skipped, worst exit {worst}")
    return worst


def _default_surface() -> List[str]:
    from avenir_tpu.analysis.flow import default_flow_paths

    return default_flow_paths(os.getcwd())


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    tier_flags = sum(1 for m in (args.ir, args.flow, args.mem, args.merge,
                                 args.proto, args.race, args.keys)
                     if m)
    if tier_flags > 1 or (args.all_tiers and tier_flags):
        print("graftlint: --ir, --flow, --mem, --merge, --proto, --race "
              "and --keys are separate analysis tiers; run them as "
              "separate invocations (or use --all for every tier at once)",
              file=sys.stderr)
        return 2
    if args.ir and args.paths:
        print("graftlint: --ir lints the kernel manifest; do not pass "
              "paths (run the two modes as two invocations)",
              file=sys.stderr)
        return 2
    if args.schedule and not args.race:
        print("graftlint: --schedule replays an interleaving trace and "
              "needs --race", file=sys.stderr)
        return 2
    if args.parallel and not args.all_tiers:
        print("graftlint: --parallel fans out the tiers and needs --all",
              file=sys.stderr)
        return 2
    if not args.all_tiers and not tier_flags and not args.paths:
        print("graftlint: pass paths to lint, or --ir / --flow / --mem / "
              "--merge / --proto / --race / --keys for the manifest "
              "audits (or --all for every tier)", file=sys.stderr)
        return 2

    if args.ir:
        _bootstrap_ir_env()
        from avenir_tpu.analysis.ir import (ALL_IR_RULES, IRTraceError,
                                            ir_rule_ids, run_ir)
        known = ir_rule_ids()
    elif args.flow:
        # the invariance audit runs real jobs: pin the CPU platform the
        # way every other analysis consumer does
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from avenir_tpu.analysis.flow import (ALL_FLOW_RULES, FLOW_AUDIT_RULE,
                                              FlowAuditError, flow_rule_ids,
                                              run_flow)
        known = flow_rule_ids()
    elif args.mem:
        # the footprint audit runs real jobs too: same platform pin
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from avenir_tpu.analysis.mem import (ALL_MEM_RULES, MEM_AUDIT_RULE,
                                             MemAuditError, mem_rule_ids,
                                             run_mem)
        known = mem_rule_ids()
    elif args.merge:
        # the shard-merge/resume audit drives real fold sinks: same pin
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from avenir_tpu.analysis.merge import (ALL_MERGE_RULES,
                                               MERGE_AUDIT_RULE,
                                               MergeAuditError,
                                               merge_rule_ids, run_merge)
        known = merge_rule_ids()
    elif args.proto:
        # the commit-point audit spawns real publish jobs: same pin
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from avenir_tpu.analysis.proto import (ALL_PROTO_RULES,
                                               PROTO_AUDIT_RULE,
                                               ProtoAuditError,
                                               proto_rule_ids, run_proto)
        known = proto_rule_ids()
    elif args.race:
        # the interleaving audit spawns real actor children: same pin
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from avenir_tpu.analysis.race import (ALL_RACE_RULES,
                                              RACE_AUDIT_RULE,
                                              RaceAuditError,
                                              race_rule_ids, run_race)
        known = race_rule_ids()
    elif args.keys:
        # the perturbation audit runs real jobs over seeded roots: pin
        # the CPU platform the way every other audit consumer does
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from avenir_tpu.analysis.keys import (ALL_KEYS_RULES,
                                              KEYS_AUDIT_RULE,
                                              KeysAuditError,
                                              keys_rule_ids, run_keys)
        known = keys_rule_ids()
    elif args.all_tiers:
        known = [rid for ids in _tier_rule_ids().values() for rid in ids]
    else:
        known = rule_ids()

    if args.rules:
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = set(wanted) - set(known)
        if unknown:
            print(f"graftlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
    else:
        wanted = None

    try:
        baseline = ([] if args.no_baseline
                    else load_baseline(args.baseline or
                                       default_baseline_path()))
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.all_tiers:
        return _run_all(args, baseline, wanted)

    if args.ir:
        from avenir_tpu.analysis.ir import PAYLOAD_RULE
        ir_rules = ([r() for r in ALL_IR_RULES] if wanted is None
                    else [r() for r in ALL_IR_RULES if r.rule_id in wanted])
        audit = wanted is None or PAYLOAD_RULE in wanted
        try:
            report = run_ir(rules=ir_rules, baseline=baseline, audit=audit)
        except IRTraceError as e:
            print(f"graftlint: trace error: {e}", file=sys.stderr)
            return 2
    elif args.flow:
        flow_rules = ([r() for r in ALL_FLOW_RULES] if wanted is None
                      else [r() for r in ALL_FLOW_RULES
                            if r.rule_id in wanted])
        audit = wanted is None or FLOW_AUDIT_RULE in wanted
        try:
            report = run_flow(paths=args.paths or None, rules=flow_rules,
                              baseline=baseline, root=_report_root(args),
                              include_md=not args.no_md, audit=audit)
        except FlowAuditError as e:
            print(f"graftlint: stream audit error: {e}", file=sys.stderr)
            return 2
        except OSError as e:
            print(f"graftlint: cannot read input: {e}", file=sys.stderr)
            return 2
    elif args.mem:
        mem_rules = ([r() for r in ALL_MEM_RULES] if wanted is None
                     else [r() for r in ALL_MEM_RULES
                           if r.rule_id in wanted])
        audit = wanted is None or MEM_AUDIT_RULE in wanted
        try:
            report = run_mem(paths=args.paths or None, rules=mem_rules,
                             baseline=baseline, root=_report_root(args),
                             include_md=not args.no_md, audit=audit)
        except MemAuditError as e:
            print(f"graftlint: footprint audit error: {e}", file=sys.stderr)
            return 2
        except OSError as e:
            print(f"graftlint: cannot read input: {e}", file=sys.stderr)
            return 2
    elif args.merge:
        merge_rules = ([r() for r in ALL_MERGE_RULES] if wanted is None
                       else [r() for r in ALL_MERGE_RULES
                             if r.rule_id in wanted])
        audit = wanted is None or MERGE_AUDIT_RULE in wanted
        try:
            report = run_merge(paths=args.paths or None, rules=merge_rules,
                               baseline=baseline, root=_report_root(args),
                               include_md=not args.no_md, audit=audit)
        except MergeAuditError as e:
            print(f"graftlint: merge audit error: {e}", file=sys.stderr)
            return 2
        except OSError as e:
            print(f"graftlint: cannot read input: {e}", file=sys.stderr)
            return 2
    elif args.proto:
        proto_rules = ([r() for r in ALL_PROTO_RULES] if wanted is None
                       else [r() for r in ALL_PROTO_RULES
                             if r.rule_id in wanted])
        audit = wanted is None or PROTO_AUDIT_RULE in wanted
        try:
            report = run_proto(paths=args.paths or None, rules=proto_rules,
                               baseline=baseline, root=_report_root(args),
                               include_md=not args.no_md, audit=audit)
        except ProtoAuditError as e:
            print(f"graftlint: commit-point audit error: {e}",
                  file=sys.stderr)
            return 2
        except OSError as e:
            print(f"graftlint: cannot read input: {e}", file=sys.stderr)
            return 2
    elif args.race:
        race_rules = ([r() for r in ALL_RACE_RULES] if wanted is None
                      else [r() for r in ALL_RACE_RULES
                            if r.rule_id in wanted])
        audit = wanted is None or RACE_AUDIT_RULE in wanted
        schedule = None
        if args.schedule:
            from avenir_tpu.analysis.race import parse_schedule
            try:
                schedule = parse_schedule(args.schedule)
            except ValueError as e:
                print(f"graftlint: {e}", file=sys.stderr)
                return 2
        try:
            report = run_race(paths=args.paths or None, rules=race_rules,
                              baseline=baseline, root=_report_root(args),
                              include_md=not args.no_md, audit=audit,
                              schedule=schedule)
        except RaceAuditError as e:
            print(f"graftlint: interleaving audit error: {e}",
                  file=sys.stderr)
            return 2
        except OSError as e:
            print(f"graftlint: cannot read input: {e}", file=sys.stderr)
            return 2
    elif args.keys:
        keys_rules = ([r() for r in ALL_KEYS_RULES] if wanted is None
                      else [r() for r in ALL_KEYS_RULES
                            if r.rule_id in wanted])
        audit = wanted is None or KEYS_AUDIT_RULE in wanted
        try:
            report = run_keys(paths=args.paths or None, rules=keys_rules,
                              baseline=baseline, root=_report_root(args),
                              include_md=not args.no_md, audit=audit)
        except KeysAuditError as e:
            print(f"graftlint: key-perturbation audit error: {e}",
                  file=sys.stderr)
            return 2
        except OSError as e:
            print(f"graftlint: cannot read input: {e}", file=sys.stderr)
            return 2
    else:
        rules = (None if wanted is None
                 else [r() for r in ALL_RULES if r.rule_id in wanted])
        try:
            report = run_paths(args.paths, rules=rules, baseline=baseline,
                               root=_report_root(args),
                               include_md=not args.no_md)
        except OSError as e:
            print(f"graftlint: cannot read input: {e}", file=sys.stderr)
            return 2

    if args.as_json:
        print(json.dumps(report.to_json(), indent=1))
    else:
        _print_report(report, is_ir=args.ir)

    return _exit_code(report, args)


if __name__ == "__main__":
    sys.exit(main())
