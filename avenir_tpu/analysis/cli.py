"""graftlint CLI: `graftlint <paths>` (console script) or
`python tools/graftlint.py <paths>`.

Exit codes: 0 clean; 1 non-allowlisted findings, stale baseline entries,
or parse errors; 2 usage/baseline-format errors. `--json` prints one
machine-readable object (bench_scaling.py tripwires on its counts).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from avenir_tpu.analysis.engine import (default_baseline_path, load_baseline,
                                        run_paths)
from avenir_tpu.analysis.rules import ALL_RULES, rule_ids


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based JAX/TPU hazard analyzer (rule catalog: "
                    "docs/graftlint.md)")
    p.add_argument("paths", nargs="+",
                   help=".py/.md files or directories to lint")
    p.add_argument("--baseline", default=None,
                   help="allowlist file (default: "
                        "avenir_tpu/analysis/graftlint_baseline.txt)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the allowlist")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON object instead of text")
    p.add_argument("--rules", default=None, metavar="ID[,ID...]",
                   help=f"comma-separated subset of: {', '.join(rule_ids())}")
    p.add_argument("--no-md", action="store_true",
                   help="skip ```python fences in .md files")
    p.add_argument("--allow-stale", action="store_true",
                   help="do not fail on baseline entries that no longer "
                        "match (use only while mid-refactor)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.rules:
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = set(wanted) - set(rule_ids())
        if unknown:
            print(f"graftlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r() for r in ALL_RULES if r.rule_id in wanted]
    else:
        rules = None
    try:
        baseline = ([] if args.no_baseline
                    else load_baseline(args.baseline or
                                       default_baseline_path()))
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    # finding keys must be cwd-independent so the baseline matches from
    # anywhere: anchor them to the repo root (the default baseline sits at
    # <root>/avenir_tpu/analysis/) or to an explicit baseline's directory
    if args.baseline:
        root = os.path.dirname(os.path.abspath(args.baseline))
    elif args.no_baseline:
        root = None                      # cwd: keys are ephemeral anyway
    else:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            default_baseline_path())))

    try:
        report = run_paths(args.paths, rules=rules, baseline=baseline,
                           root=root, include_md=not args.no_md)
    except OSError as e:
        print(f"graftlint: cannot read input: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps(report.to_json(), indent=1))
    else:
        for f in report.errors + report.findings:
            print(f.render())
        for e in report.stale:
            print(f"stale baseline entry (line {e.lineno}): {e.key} — the "
                  f"finding it excused is gone; delete it", file=sys.stderr)
        print(f"graftlint: {len(report.scanned)} files, "
              f"{len(report.findings)} finding(s), "
              f"{len(report.suppressed)} allowlisted, "
              f"{len(report.stale)} stale baseline entr(y/ies)"
              + (f", {len(report.errors)} parse error(s)"
                 if report.errors else ""))

    if report.findings or report.errors:
        return 1
    if report.stale and not args.allow_stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
