"""graftlint --race: the deterministic-interleaving tier.

PR 17's proto tier proves every commit point survives a SINGLE actor
being hard-killed; this tier proves the protocols survive each other.
The fabric-unification work (ROADMAP top item) rewrites every
multi-writer seam in ``net/`` and ``dist/`` at once — mirrors, hedges
and sweepers are BY DESIGN concurrent racing actors — so the repo
needs a gate that explores adversarial schedules before the refactor
starts, in the established graftlint shape:

**Static rules** (AST) over the protocol surface (``dist/``, ``net/``,
``server/``, ``native/sidecar.py``, ``core/incremental.py``,
``tune/store.py``):

- ``race-check-then-act`` — an ``os.path.exists``/``isdir`` gate
  followed by a mutation (write-open, rename, unlink, rmtree) of the
  same shared path with no atomic claim between: the checked fact can
  be invalidated by a concurrent actor before the act lands.
- ``race-rmw-shared-record`` — a scope that reads AND atomically
  republishes the same shared record with no ``os.link`` CAS and no
  declared ownership (``single-writer`` / ``last-write-wins`` marker in
  the docstring): two concurrent read-modify-write passes silently drop
  one writer's update.
- ``race-stale-listdir-snapshot`` — iterating an ``os.listdir``
  snapshot and acting per entry without surviving the entry vanishing
  (no OSError-shaped guard): every directory scan races its writers.
- ``race-delete-while-checked-out`` — a class that keeps a
  checkout/refcount/pin guard yet deletes files in a method that never
  consults it: the eviction can pull state out from under a holder.
- ``race-monotonic-persisted`` — a bare ``time.monotonic()`` /
  ``perf_counter()`` stamp flowing into a persisted cross-process
  record (the inverse of proto's wall-clock-deadline rule: monotonic
  clocks are process-local, so a persisted stamp is meaningless — and
  wrong — in every other process). Durations (differences) are fine.

**Mechanical auditor** (:func:`audit_interleavings`): every
schedule-sensitive protocol step calls ``sched_point(name)``
(core/atomic.py, beside ``crash_point``), and the explorer drives the
:data:`INTERLEAVE_SITES` registry — per site, TWO real actor
subprocesses stepped by a file-turnstile scheduler. The scheduler only
grants a step when every unfinished actor is parked at a sched point
(or finished), so the choice set is determined by program structure,
not host timing — the property that makes every schedule a replayable
trace. Schedules are explored exhaustively over the first ``depth``
binary choices plus ``seeds`` seeded-random schedules, and per
schedule the auditor asserts: no actor crashed, the site's invariants
hold (exactly-one-winner, no double-fold, conservation —
``site.verify``), zero stranded protocol tmps, and byte-identity of
the site's declared artifacts to an uncrashed SOLO run (actors run
sequentially, hooks unarmed). A failing schedule surfaces as a
``race-interleaving`` finding carrying its replayable
``--schedule <site>:<steps>`` trace; the pseudo-rule is NEVER
baselined — schedule failures bypass the allowlist entirely. A regex
cross-check (:func:`check_sched_registry`) greps the surface for
``sched_point("<name>")`` call sites and fails loudly when code and
registry disagree in either direction.
"""

from __future__ import annotations

import ast
import itertools
import json
import os
import random
import re
import shutil
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import (Callable, Dict, Iterator, List, Optional, Sequence,
                    Set, Tuple)

from avenir_tpu.analysis.engine import (BaselineEntry, Finding,
                                        ModuleContext, Report,
                                        apply_baseline, collect_findings)
from avenir_tpu.analysis.proto import (_calls, _functions,
                                       _has_unique_marker, _pkg_root,
                                       _resolve_map, _soup,
                                       _terminal_name, _tmp_leftovers,
                                       _tmp_like, _write_open_path)
from avenir_tpu.core.atomic import SCHED_ENV

#: the audit pseudo-rule: interleaving-schedule verdicts surface under
#: this id and are NEVER allowlisted (the runner applies them AFTER the
#: baseline pass, so no allowlist entry can suppress one)
RACE_AUDIT_RULE = "race-interleaving"

#: test seam: a module name the resident actor children import before
#: serving jobs — its import side effect may register extra (fixture)
#: sites into INTERLEAVE_SITES, so tests can drive deliberately-racy
#: protocols through the real scheduler. Production never sets it.
SITE_MODULE_ENV = "AVENIR_RACE_SITE_MODULE"


class RaceAuditError(RuntimeError):
    """The interleaving explorer could not run (actor pool death,
    scheduler stall, registry mismatch, missing native machinery) — an
    environment/registry error, never a lint finding."""


def default_race_paths(root: str) -> List[str]:
    """The multi-writer protocol surface this tier lints."""
    names = [os.path.join("avenir_tpu", "dist"),
             os.path.join("avenir_tpu", "net"),
             os.path.join("avenir_tpu", "server"),
             os.path.join("avenir_tpu", "native", "sidecar.py"),
             os.path.join("avenir_tpu", "core", "incremental.py"),
             os.path.join("avenir_tpu", "tune", "store.py")]
    return [p for p in (os.path.join(root, n) for n in names)
            if os.path.exists(p)]


# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------
_CHECK_GATES = {"os.path.exists", "os.path.isfile", "os.path.isdir",
                "os.path.lexists"}
_MUTATE_CALLS = {"os.replace", "os.rename", "os.remove", "os.unlink",
                 "os.rmdir", "shutil.rmtree"}
_OS_GUARDS = {"OSError", "IOError", "FileNotFoundError",
              "FileExistsError", "PermissionError", "Exception",
              "BaseException"}
#: docstring evidence that concurrent writers were DESIGNED away
_OWNERSHIP_MARKERS = ("single-writer", "single writer",
                      "last-write-wins", "last write wins",
                      "one writer", "sole writer", "first-commit-wins")
#: attribute-name evidence of a checkout/refcount/pin guard
_GUARD_ATTR_MARKERS = ("refcount", "ref_count", "pin", "inuse",
                       "in_use", "checked_out", "holders")
_MONO_CALLS = {"time.monotonic", "time.perf_counter", "monotonic",
               "perf_counter"}
_PERSIST_TERMINALS = ("publish_json", "publish_bytes",
                      "write_json_atomic", "_write_atomic")
#: naming noise dropped before two path soups are compared for overlap
_STOP_TOKENS = {"os", "path", "join", "self", "dir", "dirs", "name",
                "names", "base", "root", "file", "f", "p", "n", "fh",
                "str", "s", "abspath", "dirname", "basename"}


def _soup_tokens(soup: str) -> Set[str]:
    out: Set[str] = set()
    for part in soup.split():
        for tok in re.split(r"[^a-z0-9]+", part):
            if len(tok) >= 2 and tok not in _STOP_TOKENS:
                out.add(tok)
    return out


def _overlap(soup_a: str, soup_b: str) -> bool:
    return bool(_soup_tokens(soup_a) & _soup_tokens(soup_b))


def _handler_catches(ctx: ModuleContext, handler: ast.ExceptHandler,
                     names: Set[str]) -> bool:
    if handler.type is None:
        return True                 # bare except catches everything
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    for t in types:
        dotted = ctx.dotted(t) or ""
        if dotted.rsplit(".", 1)[-1] in names:
            return True
    return False


def _guarded(ctx: ModuleContext, node: ast.AST,
             stop: Optional[ast.AST] = None,
             names: Set[str] = _OS_GUARDS) -> bool:
    """True when `node` sits inside a Try (below `stop`) whose handlers
    catch one of `names` — the EAFP idiom that makes a losing racer
    recover instead of crash."""
    cur = ctx.parent(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, ast.Try):
            if any(_handler_catches(ctx, h, names)
                   for h in cur.handlers):
                return True
        cur = ctx.parent(cur)
    return False


def _gate_paths(ctx: ModuleContext, test: ast.AST) -> List[ast.AST]:
    """The path expressions checked by os.path.exists/isfile/isdir
    calls inside one If/While test."""
    out = []
    for call in _calls(test):
        if (ctx.dotted(call.func) or "") in _CHECK_GATES and call.args:
            out.append(call.args[0])
    return out


def _read_open_path(ctx: ModuleContext, call: ast.Call
                    ) -> Optional[ast.AST]:
    """The path expression of a read-mode ``open`` call (no mode, or a
    literal "r"/"rb"), or None."""
    if ctx.dotted(call.func) not in ("open", "io.open") or not call.args:
        return None
    mode = call.args[1] if len(call.args) >= 2 else None
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return call.args[0]
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
            and mode.value in ("r", "rb", "rt"):
        return call.args[0]
    return None


def _docstring_of(node: ast.AST) -> str:
    try:
        return (ast.get_docstring(node) or "").lower()
    except TypeError:
        return ""


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------
class RaceRule:
    rule_id: str = ""
    description: str = ""
    hint: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(ctx.path, getattr(node, "lineno", 1),
                       self.rule_id, message, hint or self.hint,
                       ctx.scope_of(node))


class CheckThenActRule(RaceRule):
    """An ``os.path.exists``-family gate followed, in the gated suite,
    by a mutation of an overlapping shared path with no atomic claim
    between: any concurrent actor can invalidate the checked fact
    before the act lands — the textbook TOCTOU. The sanctioned shapes
    are EAFP (do the act, catch OSError/FileExistsError) and the
    link-CAS claim (``os.link`` + EEXIST), both exempted."""

    rule_id = "race-check-then-act"
    description = "exists/isdir gate then unclaimed mutation (TOCTOU)"
    hint = ("act first and catch OSError/FileExistsError (EAFP), or "
            "take an atomic claim between check and act (os.link CAS, "
            "rename-aside) — a checked fact is stale the instant a "
            "concurrent writer exists")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _functions(ctx):
            has_link_cas = any(
                (ctx.dotted(c.func) or "") == "os.link"
                for c in _calls(fn))
            if has_link_cas:
                continue            # the link-CAS discipline governs
            resolve = _resolve_map(ctx, fn)
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                gates = _gate_paths(ctx, node.test)
                if not gates:
                    continue
                gate_soup = " ".join(
                    _soup(ctx, g, resolve) for g in gates)
                if _tmp_like(gate_soup):
                    continue        # tmp files are writer-private
                for call in _calls(node):
                    if call in list(_calls(node.test)):
                        continue
                    dotted = ctx.dotted(call.func) or ""
                    if dotted in _MUTATE_CALLS:
                        acted = call.args
                    else:
                        wp = _write_open_path(ctx, call)
                        acted = [wp] if wp is not None else []
                    if not acted:
                        continue
                    act_soup = " ".join(
                        _soup(ctx, a, resolve) for a in acted)
                    if _tmp_like(act_soup) \
                            or not _overlap(gate_soup, act_soup):
                        continue
                    if _guarded(ctx, call, stop=fn):
                        continue    # EAFP recovery present
                    yield self.finding(
                        ctx, call,
                        f"`{ctx.scope_of(call)}` mutates a shared path "
                        f"behind an exists/isdir gate with no atomic "
                        f"claim between: a concurrent actor can "
                        f"invalidate the check before the act lands")
                    break           # one finding per gate


class RmwSharedRecordRule(RaceRule):
    """A scope (class, or the module's free functions) that both READS
    a shared record and atomically REPUBLISHES an overlapping path,
    with no ``os.link`` CAS and no declared ownership: two concurrent
    read-modify-write passes interleave as read/read/write/write and
    one writer's update silently vanishes. Scopes whose docstring
    declares the design (``single-writer``, ``last-write-wins``,
    ``first-commit-wins``) are exempt — the marker is the reviewable
    claim this rule forces into the code."""

    rule_id = "race-rmw-shared-record"
    description = "read-modify-write of a shared record without CAS " \
                  "or declared ownership"
    hint = ("serialize writers through an os.link CAS / rename-aside "
            "claim, or declare the design in the writer's / class's / "
            "module's docstring ('single-writer: ...' / "
            "'last-write-wins: ...') so the lost-update window is a "
            "reviewed decision")

    def _scopes(self, ctx: ModuleContext
                ) -> Iterator[Tuple[ast.AST, List[ast.AST]]]:
        classes = [n for n in ast.walk(ctx.tree)
                   if isinstance(n, ast.ClassDef)]
        for cls in classes:
            yield cls, [cls]
        in_class = {id(sub) for cls in classes
                    for sub in ast.walk(cls)}
        free = [n for n in ctx.tree.body
                if id(n) not in in_class]
        yield ctx.tree, free

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        module_doc = _docstring_of(ctx.tree)
        for scope, bodies in self._scopes(ctx):
            doc = _docstring_of(scope) if scope is not ctx.tree \
                else module_doc
            if any(m in doc or m in module_doc
                   for m in _OWNERSHIP_MARKERS):
                continue
            calls = [c for b in bodies for c in _calls(b)]
            if any((ctx.dotted(c.func) or "") == "os.link"
                   for c in calls):
                continue
            reads: List[str] = []
            for fn in (n for b in bodies for n in ast.walk(b)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))):
                resolve = _resolve_map(ctx, fn)
                for call in _calls(fn):
                    rp = _read_open_path(ctx, call)
                    if rp is None \
                            and (ctx.dotted(call.func) or "") \
                            in ("np.load", "numpy.load") and call.args:
                        rp = call.args[0]
                    if rp is not None:
                        reads.append(_soup(ctx, rp, resolve))
            if not reads:
                continue
            read_soup = " ".join(reads)
            for fn in (n for b in bodies for n in ast.walk(b)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))):
                if any(m in _docstring_of(fn)
                       for m in _OWNERSHIP_MARKERS):
                    continue        # ownership declared at the writer
                resolve = _resolve_map(ctx, fn)
                for call in _calls(fn):
                    term = _terminal_name(ctx, call)
                    dotted = ctx.dotted(call.func) or ""
                    if term not in _PERSIST_TERMINALS \
                            and dotted != "os.replace":
                        continue
                    pub_soup = " ".join(
                        _soup(ctx, a, resolve) for a in call.args)
                    if not _overlap(read_soup, pub_soup):
                        continue
                    if dotted == "os.replace" \
                            and _has_unique_marker(pub_soup):
                        continue    # rename-to-unique IS a claim CAS
                    yield self.finding(
                        ctx, call,
                        f"`{ctx.scope_of(call)}` republishes a shared "
                        f"record its scope also reads, with no link-"
                        f"CAS and no declared ownership: concurrent "
                        f"read-modify-write passes lose updates")
                    break
                else:
                    continue
                break               # one finding per scope


class StaleListdirSnapshotRule(RaceRule):
    """A loop over an ``os.listdir`` snapshot that acts on each entry
    (open, stat, remove, rename, parse) without surviving the entry
    vanishing: every directory listing is stale the moment it returns
    — claimers, sweepers and evictors delete entries concurrently, so
    per-entry acts must re-verify via the OSError they get back."""

    rule_id = "race-stale-listdir-snapshot"
    description = "listdir snapshot iterated without per-entry " \
                  "vanish guard"
    hint = ("wrap the per-entry act in try/except OSError and treat "
            "a vanished entry as claimed-by-someone-else (the spool/"
            "sweep idiom), or re-verify with a parse that returns "
            "None on torn/absent")

    _ACTS = {"os.stat", "os.remove", "os.unlink", "os.replace",
             "os.rename", "os.utime", "json.load", "open", "io.open"}

    def _listdir_iter(self, ctx: ModuleContext, fn: ast.AST,
                      node: ast.For) -> bool:
        def is_listdir(expr: ast.AST) -> bool:
            for call in _calls(expr):
                if (ctx.dotted(call.func) or "") == "os.listdir":
                    return True
            return False

        if is_listdir(node.iter):
            return True
        if isinstance(node.iter, ast.Name):
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) \
                        and any(isinstance(t, ast.Name)
                                and t.id == node.iter.id
                                for t in sub.targets) \
                        and is_listdir(sub.value):
                    return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _functions(ctx):
            for node in ast.walk(fn):
                if not isinstance(node, ast.For):
                    continue
                if not self._listdir_iter(ctx, fn, node):
                    continue
                targets = {t.id for t in ast.walk(node.target)
                           if isinstance(t, ast.Name)}
                for call in _calls(node):
                    if (ctx.dotted(call.func) or "") not in self._ACTS:
                        continue
                    uses_entry = any(
                        isinstance(sub, ast.Name) and sub.id in targets
                        for a in call.args for sub in ast.walk(a))
                    if not uses_entry:
                        continue
                    if _guarded(ctx, call, stop=node):
                        continue
                    yield self.finding(
                        ctx, call,
                        f"`{ctx.scope_of(call)}` acts on a listdir "
                        f"entry without surviving it vanishing: the "
                        f"snapshot is stale the moment it returns")
                    break


class DeleteWhileCheckedOutRule(RaceRule):
    """A class that tracks checkouts/refcounts/pins yet deletes state
    in a method that never consults the guard: the eviction can pull a
    directory or file out from under a live holder. An attribute only
    COUNTS as a deletion guard when some method in the class both
    consults it and deletes (the eviction idiom — WarmStore's budget
    sweep skipping ``_dir_inuse`` victims); a checkout-ish name the
    class never uses to gate a delete (CPU ``pin_cores`` affinity) is
    not one. Once the class demonstrates the guard discipline, every
    OTHER deleting method must follow it."""

    rule_id = "race-delete-while-checked-out"
    description = "delete path ignores the class's checkout/refcount " \
                  "guard"
    hint = ("consult the checkout/refcount/pin state before deleting "
            "(skip in-use victims, like WarmStore's budget sweep), or "
            "make the consumer survive mid-use deletion and document "
            "it at the delete site")

    _DELETES = {"shutil.rmtree", "os.remove", "os.unlink", "os.rmdir"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for cls in (n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)):
            guards: Set[str] = set()
            for sub in ast.walk(cls):
                if isinstance(sub, ast.Attribute) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id == "self" \
                        and any(m in sub.attr.lower()
                                for m in _GUARD_ATTR_MARKERS):
                    guards.add(sub.attr)
            if not guards:
                continue

            def consults_guard(fn: ast.AST) -> bool:
                return any(
                    isinstance(sub, ast.Attribute) and sub.attr in guards
                    for sub in ast.walk(fn)) or any(
                    isinstance(sub, ast.Name) and sub.id in guards
                    for sub in ast.walk(fn))

            def deletes(fn: ast.AST) -> bool:
                return any((ctx.dotted(c.func) or "") in self._DELETES
                           for c in _calls(fn))

            methods = [n for n in ast.walk(cls)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            # the guard discipline must be DEMONSTRATED: some method
            # gates a delete on the guard, or the name is a coincidence
            if not any(consults_guard(fn) and deletes(fn)
                       for fn in methods):
                continue
            for fn in methods:
                if consults_guard(fn):
                    continue
                for call in _calls(fn):
                    if (ctx.dotted(call.func) or "") in self._DELETES:
                        yield self.finding(
                            ctx, call,
                            f"`{ctx.scope_of(call)}` deletes state in "
                            f"a class that tracks checkouts "
                            f"({sorted(guards)}) without consulting "
                            f"the guard: a live holder loses its "
                            f"files mid-use")
                        break


class MonotonicPersistedRule(RaceRule):
    """A bare ``time.monotonic()`` / ``perf_counter()`` stamp flowing
    into a persisted cross-process record: monotonic clocks have a
    process-local epoch, so the persisted value is meaningless in any
    other process — the inverse of proto's wall-clock-deadline rule
    (wall time belongs in records, monotonic in in-process deadline
    math). Differences (durations) are legitimate and not flagged."""

    rule_id = "race-monotonic-persisted"
    description = "bare monotonic stamp persisted to a cross-process " \
                  "record"
    hint = ("persist time.time() (wall) in cross-process records and "
            "keep time.monotonic() for in-process durations/deadlines "
            "— a monotonic stamp read by another process compares "
            "epochs that have nothing to do with each other")

    _SINKS = ("dump", "dumps") + _PERSIST_TERMINALS

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _functions(ctx):
            tainted: Set[str] = set()
            dicts: Dict[str, ast.Dict] = {}
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Assign):
                    continue
                names = [t.id for t in sub.targets
                         if isinstance(t, ast.Name)]
                if isinstance(sub.value, ast.Call) \
                        and (ctx.dotted(sub.value.func) or "") \
                        in _MONO_CALLS:
                    tainted.update(names)
                elif isinstance(sub.value, ast.Dict):
                    for nm in names:
                        dicts[nm] = sub.value

            def stamped(expr: ast.AST) -> bool:
                # a BARE stamp: the tainted name or call itself, or a
                # dict literal carrying one as a value — NOT inside
                # arithmetic (a difference is a duration, fine)
                if isinstance(expr, ast.Name):
                    if expr.id in tainted:
                        return True
                    inner = dicts.get(expr.id)
                    return inner is not None and stamped(inner)
                if isinstance(expr, ast.Call):
                    return (ctx.dotted(expr.func) or "") in _MONO_CALLS
                if isinstance(expr, ast.Dict):
                    return any(stamped(v) for v in expr.values
                               if v is not None)
                return False

            for call in _calls(fn):
                if _terminal_name(ctx, call) not in self._SINKS:
                    continue
                if any(stamped(a) for a in call.args) \
                        or any(stamped(kw.value)
                               for kw in call.keywords):
                    yield self.finding(
                        ctx, call,
                        f"`{ctx.scope_of(call)}` persists a bare "
                        f"monotonic stamp into a cross-process "
                        f"record: the epoch is process-local, so "
                        f"every other process reads garbage")


ALL_RACE_RULES = [CheckThenActRule, RmwSharedRecordRule,
                  StaleListdirSnapshotRule, DeleteWhileCheckedOutRule,
                  MonotonicPersistedRule]


def race_rule_ids() -> List[str]:
    return [r.rule_id for r in ALL_RACE_RULES] + [RACE_AUDIT_RULE]


# --------------------------------------------------------------------------
# interleave sites: seed / two actors / invariants
# --------------------------------------------------------------------------
@dataclass
class InterleaveSite:
    """One registered two-actor protocol seam. ``seed`` prepares a
    fresh root; ``actors`` are the two racing drivers (JSON-serializable
    returns — they run in resident subprocesses); ``verify`` checks the
    site's invariants given the final root, both actors' values and the
    solo run's values; ``artifacts`` are root-relative files that must
    be byte-identical (canonicalized) to the solo run under EVERY
    schedule; ``sched`` names the sched_point hooks this seam steps
    (the registry half of the cross-check)."""

    name: str
    path: str
    sched: Tuple[str, ...]
    seed: Callable[[str], None]
    actors: Tuple[Callable[[str], dict], Callable[[str], dict]]
    verify: Callable[[str, dict, dict, dict, dict], List[str]]
    artifacts: Tuple[str, ...] = ()


# ---------------------------------------------------------- ledger.claim
def _seed_ledger(root: str) -> None:
    from avenir_tpu.dist.ledger import BlockLedger
    BlockLedger(root)


def _actor_claim_0(root: str) -> dict:
    from avenir_tpu.dist.ledger import BlockLedger
    return {"won": bool(BlockLedger(root).claim(7, worker=0))}


def _actor_claim_1(root: str) -> dict:
    from avenir_tpu.dist.ledger import BlockLedger
    return {"won": bool(BlockLedger(root).claim(7, worker=1))}


def _verify_ledger_claim(root, a, b, solo_a, solo_b) -> List[str]:
    from avenir_tpu.dist.ledger import BlockLedger
    problems = []
    wins = int(bool(a["won"])) + int(bool(b["won"]))
    if wins != 1:
        problems.append(f"{wins} claim winners (exactly-one expected)")
    info = BlockLedger(root).claim_info(7)
    if info is None:
        problems.append("no well-formed claim on disk after the race")
    elif wins == 1 and info["worker"] != (0 if a["won"] else 1):
        problems.append(
            f"claim file names worker {info['worker']} but the "
            f"winner was {0 if a['won'] else 1}")
    return problems


# --------------------------------------------------------- ledger.commit
_COMMIT_BLOB = b"level-9-fold-state"


def _actor_commit_0(root: str) -> dict:
    from avenir_tpu.dist.ledger import BlockLedger
    return {"won": bool(BlockLedger(root).commit(9, 0, _COMMIT_BLOB))}


def _actor_commit_1(root: str) -> dict:
    from avenir_tpu.dist.ledger import BlockLedger
    return {"won": bool(BlockLedger(root).commit(9, 1, _COMMIT_BLOB))}


def _verify_ledger_commit(root, a, b, solo_a, solo_b) -> List[str]:
    from avenir_tpu.dist.ledger import BlockLedger
    problems = []
    wins = int(bool(a["won"])) + int(bool(b["won"]))
    if wins != 1:
        problems.append(f"{wins} commit winners — a double-fold "
                        f"(exactly-one expected: folds are "
                        f"non-idempotent)")
    led = BlockLedger(root)
    if led.committed() != [9]:
        problems.append(f"committed set {led.committed()} != [9]")
    elif led.load_state(9) != _COMMIT_BLOB:
        problems.append("committed state bytes differ from the blob")
    dups = sorted(os.listdir(os.path.join(root, "ledger", "dups")))
    if wins == 1:
        loser = 1 if a["won"] else 0
        if dups != [f"b9.w{loser}.json"]:
            problems.append(
                f"dup markers {dups} != exactly the loser's "
                f"(worker {loser})")
    return problems


# ----------------------------------------------------------- lease.sweep
def _seed_lease(root: str) -> None:
    from avenir_tpu.net.fault import Lease, LeaseStore
    LeaseStore(root).write(
        Lease(name="r1.json", host=0, claimed_at=1000.0, ttl_s=5.0))


def _actor_lease_owner(root: str) -> dict:
    import time as _t
    from avenir_tpu.net.fault import Lease, LeaseStore
    store = LeaseStore(root)
    lease = Lease(name="r1.json", host=0, claimed_at=1000.0, ttl_s=5.0)
    for _ in range(2):
        store.renew(lease, _t.time())
    store.remove("r1.json")
    return {"renewed": 2}


def _actor_lease_sweeper(root: str) -> dict:
    import time as _t
    from avenir_tpu.net.fault import LeaseStore
    store = LeaseStore(root)
    now = _t.time()
    lease = store.load("r1.json")
    if lease is None or not lease.expired(now):
        return {"requeued": False, "taken_at": None}
    taken = store.take("r1.json")
    if taken is None:
        return {"requeued": False, "taken_at": None}
    if not taken.expired(now):
        store.write(taken)          # renewed under us: CAS lost
        return {"requeued": False, "taken_at": taken.claimed_at}
    return {"requeued": True, "taken_at": taken.claimed_at}


def _verify_lease_sweep(root, a, b, solo_a, solo_b) -> List[str]:
    from avenir_tpu.net.fault import LeaseStore
    problems = []
    if b["requeued"] and b["taken_at"] != 1000.0:
        problems.append(
            f"sweeper requeued a RENEWED lease (claimed_at "
            f"{b['taken_at']}, seeded 1000.0): the owner's renew was "
            f"destroyed — a double-placement")
    store = LeaseStore(root)
    for n in store.names():
        if store.load(n) is None:
            problems.append(f"torn lease file {n} after the race")
    return problems


# ----------------------------------------------------------- spool.claim
def _seed_spool(root: str) -> None:
    from avenir_tpu.core.atomic import publish_json
    from avenir_tpu.server.spool import spool_dirs
    in_dir, _work, _out = spool_dirs(root)
    publish_json({"job": "probe"}, os.path.join(in_dir, "q1.json"))


def _actor_spool_claim(root: str) -> dict:
    from avenir_tpu.server.spool import _claim, spool_dirs
    in_dir, work_dir, _out = spool_dirs(root)
    out = []
    for name, wp in _claim(in_dir, work_dir):
        with open(wp) as fh:
            out.append([name, fh.read()])
    return {"claimed": out}


def _verify_spool_claim(root, a, b, solo_a, solo_b) -> List[str]:
    problems = []
    total = a["claimed"] + b["claimed"]
    if len(total) != 1:
        problems.append(
            f"request claimed {len(total)} times (exactly-one-winner)")
    elif total[0][0] != "q1.json" \
            or json.loads(total[0][1]) != {"job": "probe"}:
        problems.append("claimed request name/content mangled")
    leftover = [n for n in os.listdir(os.path.join(root, "in"))
                if n.endswith(".json")]
    if leftover:
        problems.append(f"request still spooled after claim: "
                        f"{leftover}")
    work = os.listdir(os.path.join(root, "work"))
    if len(work) != 1:
        problems.append(f"work dir holds {len(work)} claims "
                        f"(conservation: exactly 1)")
    return problems


# ------------------------------------------------------------ warm.evict
def _warm_opts(root: str) -> dict:
    return {"dir": os.path.join(root, "cache"), "budget": 1 << 30}


def _warm_corpus(root: str) -> str:
    return os.path.join(root, "corpus.csv")


_WARM_BLOCK = 64


def _seed_warm(root: str) -> None:
    path = _warm_corpus(root)
    with open(path, "w") as fh:
        for i in range(24):
            fh.write(f"k{i:02d},v{i:02d}\n")
    from avenir_tpu.native.sidecar import byte_blocks
    feed = byte_blocks(_warm_opts(root), path, ",", 0, _WARM_BLOCK)
    if feed is None:
        raise RaceAuditError(
            "sidecar machinery unavailable (native ingest missing): "
            "the warm.evict / sidecar.manifest interleave sites "
            "cannot run")
    list(feed)                      # pack the sidecar warm


def _actor_warm_reader(root: str) -> dict:
    from avenir_tpu.native.sidecar import byte_blocks
    feed = byte_blocks(_warm_opts(root), _warm_corpus(root), ",", 0,
                       _WARM_BLOCK)
    if feed is None:
        raise RuntimeError("sidecar feed refused to engage")
    return {"blocks": [[off, ln, h] for off, ln, h, _p in feed]}


def _actor_warm_evictor(root: str) -> dict:
    from avenir_tpu.native.sidecar import SidecarHandle, bytes_dir
    dirpath = bytes_dir(_warm_opts(root), _warm_corpus(root), ",", 0,
                        _WARM_BLOCK)
    SidecarHandle(_warm_corpus(root), dirpath).close()
    return {"evicted": True}


def _verify_warm_evict(root, a, b, solo_a, solo_b) -> List[str]:
    problems = []
    if a["blocks"] != solo_a["blocks"]:
        problems.append(
            "scan coverage changed under eviction: the reader must "
            "yield the same (offset, length, hash) tiling cold as "
            "warm")
    return problems


# ------------------------------------------------------ sidecar.manifest
def _seed_sidecar_manifest(root: str) -> None:
    _seed_warm(root)                # 24 lines, packed warm
    path = _warm_corpus(root)
    prefix_end = os.path.getsize(path)
    with open(path, "a") as fh:
        for i in range(24, 40):
            fh.write(f"k{i:02d},v{i:02d}\n")
    with open(os.path.join(root, "prefix.json"), "w") as fh:
        json.dump({"prefix_end": prefix_end}, fh)


def _actor_sidecar_writer(root: str) -> dict:
    from avenir_tpu.native.sidecar import byte_blocks
    feed = byte_blocks(_warm_opts(root), _warm_corpus(root), ",", 0,
                       _WARM_BLOCK)
    if feed is None:
        raise RuntimeError("sidecar feed refused to engage")
    return {"blocks": [[off, ln, h] for off, ln, h, _p in feed]}


def _actor_sidecar_replayer(root: str) -> dict:
    from avenir_tpu.native.sidecar import byte_blocks
    with open(os.path.join(root, "prefix.json")) as fh:
        prefix_end = json.load(fh)["prefix_end"]
    feed = byte_blocks(_warm_opts(root), _warm_corpus(root), ",", 0,
                       _WARM_BLOCK, byte_range=(0, prefix_end),
                       write=False)
    if feed is None:
        return {"blocks": None}     # legal: replay-all-or-nothing
    return {"blocks": [[off, ln, h] for off, ln, h, _p in feed]}


def _verify_sidecar_manifest(root, a, b, solo_a, solo_b) -> List[str]:
    from avenir_tpu.native.sidecar import _load_manifest, bytes_dir
    problems = []
    if a["blocks"] != solo_a["blocks"]:
        problems.append("writer's extend pass tiled differently from "
                        "the solo run")
    if b["blocks"] is not None and b["blocks"] != solo_b["blocks"]:
        problems.append("reader replayed a tiling the solo run never "
                        "saw")
    man = _load_manifest(bytes_dir(_warm_opts(root),
                                   _warm_corpus(root), ",", 0,
                                   _WARM_BLOCK))
    if man is None:
        problems.append("no readable manifest after the race")
    else:
        covered = sum(int(e["length"]) for e in man["blocks"])
        size = os.path.getsize(_warm_corpus(root))
        if covered != size:
            problems.append(
                f"manifest covers {covered} of {size} corpus bytes "
                f"(conservation: the extend must tile gap-free)")
    return problems


# ------------------------------------------------------- checkpoint.save
def _ckpt_dir(root: str) -> str:
    return os.path.join(root, "state")


def _seed_ckpt(root: str) -> None:
    from avenir_tpu.core.incremental import CheckpointStore
    CheckpointStore(_ckpt_dir(root)).save({"seq": 1}, b"carry-one")


def _actor_ckpt_saver(root: str) -> dict:
    from avenir_tpu.core.incremental import CheckpointStore
    meta = CheckpointStore(_ckpt_dir(root)).save({"seq": 2},
                                                 b"carry-two")
    return {"seq": int(meta["seq"])}


def _actor_ckpt_loader(root: str) -> dict:
    from avenir_tpu.core.incremental import CheckpointStore
    store = CheckpointStore(_ckpt_dir(root))
    loads = []
    for _ in range(3):
        got = store.load()
        loads.append(None if got is None
                     else [int(got[0]["seq"]), got[1].decode()])
    return {"loads": loads}


def _verify_ckpt(root, a, b, solo_a, solo_b) -> List[str]:
    from avenir_tpu.core.incremental import block_hash
    problems = []
    legal = {(1, "carry-one"), (2, "carry-two")}
    seqs = []
    for got in b["loads"]:
        if got is None:
            continue                # GC'd-carry cold fallback: legal
        if tuple(got) not in legal:
            problems.append(f"torn checkpoint load {got}: neither "
                            f"seeded nor saved pair")
        seqs.append(got[0])
    if seqs != sorted(seqs):
        problems.append(f"checkpoint loads went backwards: {seqs}")
    want = {"MANIFEST.json",
            f"carry_000002_{block_hash(b'carry-two')[:8]}.npz"}
    have = set(os.listdir(_ckpt_dir(root)))
    if have != want:
        problems.append(f"final state dir {sorted(have)} != "
                        f"{sorted(want)} (superseded carry must be "
                        f"GC'd, the live one kept)")
    return problems


# -------------------------------------------------------- cand.publish
_CAND_MAN = {"tag": "k2", "job": "probe", "mask": ["a", "b"],
             "cands": [["a", "b"]], "c_pad": 64}


def _seed_cand(root: str) -> None:
    os.makedirs(os.path.join(root, "candidates"), exist_ok=True)


def _actor_cand_publisher(root: str) -> dict:
    from avenir_tpu.dist.driver import publish_candidates
    cand_dir = os.path.join(root, "candidates")
    publish_candidates(cand_dir, "k2", dict(_CAND_MAN))
    publish_candidates(cand_dir, "final", {"done": True, "rounds": 1})
    return {"published": ["k2", "final"]}


def _actor_cand_poller(root: str) -> dict:
    from avenir_tpu.dist.worker import _Worker
    path = os.path.join(root, "candidates", "k2.json")
    polls = []
    for _ in range(4):
        man = _Worker._load_manifest(None, path)
        polls.append(None if man is None else sorted(man))
    return {"polls": polls}


def _verify_cand(root, a, b, solo_a, solo_b) -> List[str]:
    problems = []
    want_keys = sorted(_CAND_MAN)
    seen_published = False
    for got in b["polls"]:
        if got is None:
            if seen_published:
                problems.append(
                    "a published manifest vanished from a later poll")
            continue
        seen_published = True
        if got != want_keys:
            problems.append(f"worker polled a PARTIAL manifest "
                            f"{got} (atomic publish must be "
                            f"complete-or-absent)")
    return problems


INTERLEAVE_SITES: List[InterleaveSite] = [
    InterleaveSite(
        "ledger.claim", "avenir_tpu/dist/ledger.py",
        ("ledger.claim",), _seed_ledger,
        (_actor_claim_0, _actor_claim_1), _verify_ledger_claim,
        ("ledger/claims/b7.json",)),
    InterleaveSite(
        "ledger.commit", "avenir_tpu/dist/ledger.py",
        ("ledger.commit",), _seed_ledger,
        (_actor_commit_0, _actor_commit_1), _verify_ledger_commit,
        ("ledger/states/b9.npz",)),
    InterleaveSite(
        "lease.sweep", "avenir_tpu/net/fault.py",
        ("lease.renew", "lease.sweep"), _seed_lease,
        (_actor_lease_owner, _actor_lease_sweeper), _verify_lease_sweep),
    InterleaveSite(
        "spool.claim", "avenir_tpu/server/spool.py",
        ("spool.claim",), _seed_spool,
        (_actor_spool_claim, _actor_spool_claim), _verify_spool_claim),
    InterleaveSite(
        "warm.evict", "avenir_tpu/native/sidecar.py",
        ("warm.evict", "sidecar.replay"), _seed_warm,
        (_actor_warm_reader, _actor_warm_evictor), _verify_warm_evict),
    InterleaveSite(
        "sidecar.manifest", "avenir_tpu/native/sidecar.py",
        ("sidecar.manifest",), _seed_sidecar_manifest,
        (_actor_sidecar_writer, _actor_sidecar_replayer),
        _verify_sidecar_manifest),
    InterleaveSite(
        "checkpoint.save", "avenir_tpu/core/incremental.py",
        ("checkpoint.save", "checkpoint.load"), _seed_ckpt,
        (_actor_ckpt_saver, _actor_ckpt_loader), _verify_ckpt),
    InterleaveSite(
        "cand.publish", "avenir_tpu/dist/driver.py",
        ("cand.publish", "cand.poll"), _seed_cand,
        (_actor_cand_publisher, _actor_cand_poller), _verify_cand,
        ("candidates/k2.json", "candidates/final.json")),
]


def interleave_sites() -> List[InterleaveSite]:
    return list(INTERLEAVE_SITES)


def _drive_actor(name: str, idx: int, root: str) -> dict:
    """The resident actor child's per-round entry: run one side of one
    registered interleave site."""
    for site in INTERLEAVE_SITES:
        if site.name == name:
            return site.actors[idx](root)
    raise SystemExit(f"unknown interleave site {name!r}")


# --------------------------------------------------------------------------
# registry cross-check
# --------------------------------------------------------------------------
_SCHED_REF_RE = re.compile(r'sched_point\(\s*"([a-z_.]+)"')


def sched_annotations(root: Optional[str] = None
                      ) -> Dict[str, Tuple[str, int]]:
    """Every sched_point name annotated on the protocol surface,
    mapped to the (repo-relative path, line) of its first call site."""
    root = root or _pkg_root()
    refs: Dict[str, Tuple[str, int]] = {}
    files: List[str] = []
    for p in default_race_paths(root):
        if os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames.sort()
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(names)
                             if n.endswith(".py"))
        else:
            files.append(p)
    for path in files:
        try:
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        for i, line in enumerate(text.splitlines(), 1):
            for m in _SCHED_REF_RE.finditer(line):
                refs.setdefault(m.group(1), (rel, i))
    return refs


def check_sched_registry(root: Optional[str] = None
                         ) -> Dict[str, Tuple[str, int]]:
    """Fail loudly when the sched_point call sites and the registry's
    union of per-site hook names disagree: an annotated-but-
    unregistered hook parks an actor nobody steps (a guaranteed
    stall), a registered-but-unannotated hook means the registry
    describes a step that no longer exists. Returns the annotation
    locations (the audit rows' path/line source)."""
    refs = sched_annotations(root)
    names: Set[str] = set()
    for site in INTERLEAVE_SITES:
        names.update(site.sched)
    unregistered = sorted(set(refs) - names)
    unannotated = sorted(names - set(refs))
    problems = []
    if unregistered:
        problems.append(
            f"sched_point hooks in code but in no INTERLEAVE_SITES "
            f"entry (no schedule ever steps them): {unregistered}")
    if unannotated:
        problems.append(
            f"registered in INTERLEAVE_SITES but never annotated in "
            f"code (dangling registry entries): {unannotated}")
    if problems:
        raise RaceAuditError(
            "interleave-site registry mismatch: " + "; ".join(problems))
    return refs


# --------------------------------------------------------------------------
# the file-turnstile scheduler
# --------------------------------------------------------------------------
#: wall-clock and winner-identity fields two correct racing runs may
#: legitimately differ in — canonicalized away before byte comparison
_RACE_VOLATILE_KEYS = ("claimed_at", "rejected_at", "ts_unix", "worker",
                       "host")


def _race_canon(rel: str, data: bytes) -> bytes:
    if not rel.endswith(".json"):
        return data
    try:
        obj = json.loads(data.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return data                 # torn JSON: compare (and fail) raw
    if isinstance(obj, dict):
        for key in _RACE_VOLATILE_KEYS:
            obj.pop(key, None)
    return json.dumps(obj, sort_keys=True).encode("utf-8")


def _artifact_snapshot(root: str, rels: Sequence[str]
                       ) -> Dict[str, Optional[bytes]]:
    out: Dict[str, Optional[bytes]] = {}
    for rel in rels:
        path = os.path.join(root, rel)
        try:
            with open(path, "rb") as fh:
                out[rel] = _race_canon(rel, fh.read())
        except OSError:
            out[rel] = None
    return out


class _ActorPool:
    """Two RESIDENT actor subprocesses for the whole audit: each polls
    a job spool, runs its side of the named site with the turnstile
    armed, publishes its result, and waits for the next round —
    amortizing interpreter+import startup over hundreds of schedules."""

    def __init__(self, base: str):
        self.base = base
        self.jobs = os.path.join(base, "jobs")
        os.makedirs(self.jobs, exist_ok=True)
        env = dict(os.environ)
        env.pop(SCHED_ENV, None)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (_pkg_root(), env.get("PYTHONPATH")) if p)
        self.procs = []
        self.logs = []
        for idx in (0, 1):
            log = open(os.path.join(base, f"actor{idx}.log"), "w")
            self.logs.append(log)
            code = ("from avenir_tpu.analysis.race import _actor_main; "
                    f"_actor_main({idx}, {base!r})")
            self.procs.append(subprocess.Popen(
                [sys.executable, "-c", code], env=env,
                stdout=log, stderr=log))
        self.round_no = 0

    def dispatch(self, site_name: str, root: str,
                 turnstile: str) -> int:
        n = self.round_no
        self.round_no += 1
        for idx in (0, 1):
            job = os.path.join(self.jobs, f"j{n}.{idx}.json")
            wip = job + ".wip"
            with open(wip, "w") as fh:
                json.dump({"site": site_name, "root": root,
                           "turnstile": turnstile}, fh)
            os.replace(wip, job)
        return n

    def check_alive(self) -> None:
        for idx, proc in enumerate(self.procs):
            rc = proc.poll()
            if rc is not None:
                tail = ""
                try:
                    with open(os.path.join(self.base,
                                           f"actor{idx}.log")) as fh:
                        tail = fh.read().strip()[-400:]
                except OSError:
                    pass
                raise RaceAuditError(
                    f"actor child {idx} died rc={rc}: {tail}")

    def close(self) -> None:
        with open(os.path.join(self.base, "stop"), "w") as fh:
            fh.write("stop")
        for proc in self.procs:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        for log in self.logs:
            log.close()


def _actor_main(idx: int, base: str) -> None:
    """Resident actor child loop: job spool in, result file out."""
    extra = os.environ.get(SITE_MODULE_ENV, "")
    if extra:
        import importlib
        importlib.import_module(extra)
    jobs = os.path.join(base, "jobs")
    stop = os.path.join(base, "stop")
    n = 0
    idle_deadline = time.monotonic() + 600.0
    while True:
        job = os.path.join(jobs, f"j{n}.{idx}.json")
        spec = None
        try:
            with open(job) as fh:
                spec = json.load(fh)
        except (OSError, ValueError):
            spec = None
        if spec is None:
            if os.path.exists(stop) or time.monotonic() > idle_deadline:
                return
            time.sleep(0.001)
            continue
        idle_deadline = time.monotonic() + 600.0
        os.environ[SCHED_ENV] = f"{spec['turnstile']}:{idx}"
        out: dict = {"ok": True, "value": None}
        try:
            out["value"] = _drive_actor(spec["site"], idx, spec["root"])
        except BaseException as exc:  # noqa: BLE001 — verdict, not crash
            out = {"ok": False,
                   "error": f"{type(exc).__name__}: {exc}"}
        finally:
            os.environ.pop(SCHED_ENV, None)
        done = os.path.join(spec["turnstile"], f"done.{idx}")
        wip = done + ".wip"
        with open(wip, "w") as fh:
            json.dump(out, fh)
        os.replace(wip, done)
        n += 1


def _run_schedule(pool: _ActorPool, site: InterleaveSite,
                  decider: Callable[[int, List[int], List[int]], int],
                  round_dir: str, timeout_s: float = 90.0
                  ) -> Tuple[dict, dict, List[int], List[str]]:
    """Drive one schedule of one site: seed a fresh root, dispatch both
    resident actors, and grant turnstile steps per `decider` until both
    finish. Returns (result_a, result_b, trace, step names). The
    scheduler only decides once every unfinished actor is parked (or
    done), so the ready set — and therefore the trace — is a pure
    function of the decider and the actors' program structure."""
    root = os.path.join(round_dir, "root")
    os.makedirs(root, exist_ok=True)
    site.seed(root)
    turnstile = os.path.join(round_dir, "ts")
    os.makedirs(turnstile, exist_ok=True)
    pool.dispatch(site.name, root, turnstile)
    granted = [0, 0]
    results: List[Optional[dict]] = [None, None]
    trace: List[int] = []
    names: List[str] = []
    deadline = time.monotonic() + timeout_s
    while not all(r is not None for r in results):
        pool.check_alive()
        if time.monotonic() > deadline:
            raise RaceAuditError(
                f"site {site.name}: schedule stalled after grants "
                f"{''.join(map(str, trace))} (scheduler timeout)")
        ready: List[int] = []
        for idx in (0, 1):
            if results[idx] is not None:
                continue
            dpath = os.path.join(turnstile, f"done.{idx}")
            if os.path.exists(dpath):
                with open(dpath) as fh:
                    results[idx] = json.load(fh)
                continue
            rpath = os.path.join(turnstile,
                                 f"ready.{idx}.{granted[idx]:04d}")
            if os.path.exists(rpath):
                ready.append(idx)
        waiting = [i for i in (0, 1) if results[i] is None]
        if not waiting:
            break
        if len(ready) < len(waiting):
            time.sleep(0.0003)      # someone is still running
            continue
        pick = decider(len(trace), ready, trace)
        if pick not in ready:
            raise RaceAuditError(
                f"site {site.name}: replay trace diverged at step "
                f"{len(trace)} (trace wants actor {pick}, ready "
                f"{ready}) — the schedule does not belong to this "
                f"code")
        tag = f"{pick}.{granted[pick]:04d}"
        with open(os.path.join(turnstile, f"ready.{tag}")) as fh:
            names.append(fh.read().strip())
        go = os.path.join(turnstile, f"go.{tag}")
        with open(go + ".wip", "w") as fh:
            fh.write("go")
        os.replace(go + ".wip", go)
        granted[pick] += 1
        trace.append(pick)
    return results[0], results[1], trace, names


# ------------------------------------------------------------- deciders
def _exhaustive_decider(bits: Sequence[int]):
    """Enumerate the first ``len(bits)`` genuine (two-way) choices;
    beyond them, prefer the lowest ready actor. Forced steps (one
    actor ready) consume no bit."""
    state = {"used": 0}

    def decide(step: int, ready: List[int], trace: List[int]) -> int:
        if len(ready) == 1:
            return ready[0]
        i = state["used"]
        state["used"] += 1
        if i < len(bits):
            return ready[-1] if bits[i] else ready[0]
        return min(ready)

    return decide


def _seeded_decider(site_name: str, seed: int):
    rnd = random.Random(f"{site_name}:{seed}")

    def decide(step: int, ready: List[int], trace: List[int]) -> int:
        return rnd.choice(ready)

    return decide


def _replay_decider(steps: Sequence[int]):
    def decide(step: int, ready: List[int], trace: List[int]) -> int:
        if step < len(steps):
            return steps[step]
        return min(ready)

    return decide


def parse_schedule(spec: str) -> Tuple[str, List[int]]:
    """Parse a ``--schedule`` trace: ``<site>:<digits>`` where digit i
    names the actor granted at step i (e.g. ``ledger.claim:01101``)."""
    site, sep, digits = spec.rpartition(":")
    if not sep or not site or not re.fullmatch(r"[01]+", digits):
        raise ValueError(
            f"bad schedule {spec!r} (want <site>:<01-digits>, e.g. "
            f"ledger.claim:01101)")
    return site, [int(d) for d in digits]


# --------------------------------------------------------------------------
# the interleaving auditor
# --------------------------------------------------------------------------
def audit_interleavings(sites: Optional[Sequence[InterleaveSite]] = None,
                        locations: Optional[
                            Dict[str, Tuple[str, int]]] = None,
                        depth: int = 3, seeds: int = 64,
                        schedule: Optional[Tuple[str, List[int]]] = None
                        ) -> Tuple[List[dict], List[Finding]]:
    """Explore two-actor schedules for every registered interleave
    site: exhaustive over the first `depth` genuine choices, plus
    `seeds` seeded-random schedules — or exactly one replayed trace
    when `schedule` is given. Per schedule, assert: neither actor
    crashed, the site's invariants hold, zero stranded protocol tmps,
    and the declared artifacts are byte-identical to the solo run.
    Returns (rows, findings): one row per site with per-kind schedule
    counts, one ``race-interleaving`` finding (carrying a replayable
    trace) per failed site. Infrastructure failures raise
    :class:`RaceAuditError`."""
    sites = list(sites) if sites is not None else list(INTERLEAVE_SITES)
    if schedule is not None:
        want, steps = schedule
        sites = [s for s in sites if s.name == want]
        if not sites:
            raise RaceAuditError(f"unknown interleave site {want!r}")
    locations = locations or {}
    rows: List[dict] = []
    findings: List[Finding] = []
    base = tempfile.mkdtemp(prefix="graftlint_race_")
    pool = _ActorPool(base)
    try:
        for site in sites:
            loc = locations.get(site.name)
            site_dir = os.path.join(base, site.name.replace(".", "_"))
            solo_root = os.path.join(site_dir, "solo")
            os.makedirs(solo_root, exist_ok=True)
            try:
                site.seed(solo_root)
                solo_a = site.actors[0](solo_root)
                solo_b = site.actors[1](solo_root)
            except RaceAuditError:
                raise
            except Exception as exc:
                raise RaceAuditError(
                    f"interleave site {site.name}: solo driver "
                    f"failed: {type(exc).__name__}: {exc}") from exc
            solo_snap = _artifact_snapshot(solo_root, site.artifacts)
            deciders: List[Tuple[str, Callable]] = []
            if schedule is not None:
                deciders.append(("replay", _replay_decider(steps)))
            else:
                for bits in itertools.product((0, 1), repeat=depth):
                    deciders.append(
                        ("exhaustive", _exhaustive_decider(bits)))
                for s in range(seeds):
                    deciders.append(
                        ("seeded", _seeded_decider(site.name, s)))
            counts = {"exhaustive": 0, "seeded": 0, "replay": 0}
            problems: List[str] = []
            failing: Optional[str] = None
            for n, (kind, decider) in enumerate(deciders):
                round_dir = os.path.join(site_dir, f"r{n:04d}")
                os.makedirs(round_dir, exist_ok=True)
                ra, rb, trace, _names = _run_schedule(
                    pool, site, decider, round_dir)
                counts[kind] += 1
                sched_str = "".join(map(str, trace))
                rproblems: List[str] = []
                for idx, res in ((0, ra), (1, rb)):
                    if not res.get("ok"):
                        rproblems.append(
                            f"actor {idx} crashed: {res.get('error')}")
                root = os.path.join(round_dir, "root")
                if not rproblems:
                    rproblems.extend(site.verify(
                        root, ra["value"], rb["value"],
                        solo_a, solo_b) or [])
                leftovers = _tmp_leftovers(root)
                if leftovers:
                    rproblems.append(
                        f"stranded protocol tmps: {leftovers[:4]}")
                got = _artifact_snapshot(root, site.artifacts)
                if got != solo_snap:
                    drift = sorted(r for r in solo_snap
                                   if got.get(r) != solo_snap[r])
                    rproblems.append(
                        f"artifacts differ from the solo run "
                        f"(drifting: {drift[:4]})")
                shutil.rmtree(round_dir, ignore_errors=True)
                if rproblems:
                    failing = sched_str
                    problems.append(
                        f"schedule {site.name}:{sched_str} ({kind}): "
                        + "; ".join(rproblems))
                    break           # first failing schedule is THE repro
            validated = not problems
            rows.append({"site": site.name,
                         "path": loc[0] if loc else site.path,
                         "line": loc[1] if loc else 1,
                         "schedules": dict(counts),
                         "depth": depth,
                         "failing_schedule":
                             f"{site.name}:{failing}" if failing
                             else None,
                         "interleaving_validated": validated})
            if not validated:
                findings.append(Finding(
                    loc[0] if loc else site.path,
                    loc[1] if loc else 1,
                    RACE_AUDIT_RULE,
                    f"interleave site `{site.name}` failed schedule "
                    f"exploration: {'; '.join(problems)} — replay "
                    f"with: graftlint --race --schedule "
                    f"{site.name}:{failing}",
                    "make the losing actor recover (EAFP / link-CAS / "
                    "take-CAS) instead of acting on a stale check; "
                    "never allowlist an interleaving failure",
                    site.name))
    finally:
        pool.close()
        shutil.rmtree(base, ignore_errors=True)
    return rows, findings


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------
def run_race(paths: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[RaceRule]] = None,
             baseline: Optional[Sequence[BaselineEntry]] = None,
             root: Optional[str] = None, include_md: bool = True,
             audit: bool = True,
             sites: Optional[Sequence[InterleaveSite]] = None,
             depth: int = 3, seeds: int = 64,
             schedule: Optional[Tuple[str, List[int]]] = None) -> Report:
    """Lint `paths` (default: the multi-writer protocol surface) with
    the race rules, run the interleaving explorer over the registered
    sites (default: INTERLEAVE_SITES, after the sched_point registry
    cross-check), and apply the allowlist baseline to the RULE findings
    only — ``race-interleaving`` findings are appended after the
    baseline pass and can never be suppressed."""
    active = list(rules) if rules is not None else \
        [r() for r in ALL_RACE_RULES]
    root = os.path.abspath(root or os.getcwd())
    scan = list(paths) if paths else default_race_paths(root)
    report, raw = collect_findings(scan, active, root, include_md)
    audit_findings: List[Finding] = []
    if audit:
        locations: Dict[str, Tuple[str, int]] = {}
        if sites is None:
            locations = check_sched_registry()
        rows, audit_findings = audit_interleavings(
            sites=sites, locations=locations, depth=depth, seeds=seeds,
            schedule=schedule)
        report.race_audit.extend(rows)
    active_ids = {r.rule_id for r in active}
    apply_baseline(report, raw, baseline, active_ids)
    # the never-baselined contract: schedule failures join findings
    # AFTER the allowlist pass, so no entry can ever suppress one
    report.findings.extend(audit_findings)
    return report
