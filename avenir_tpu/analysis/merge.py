"""graftlint-merge: fold-state merge-algebra analysis of the streamed
jobs, plus the mechanical shard-merge/resume auditor.

The flow tier proves streamed folds *deterministic* under re-chunking;
the mem tier proves them *admissible*. Nothing yet proves the property
the two heaviest ROADMAP items — incremental/resumable analytics and
multi-host sharded streaming with straggler tolerance — both reduce to:
that every streamed job's fold state is a *mergeable, serializable*
algebra, i.e. ``merge(fold(shard_A), fold(shard_B)) == fold(A ++ B)``
byte-identically, and a mid-scan carry can be checkpointed and resumed
to the same bytes. MapReduce systems got this for free from the
combiner/reducer contract (arXiv:1801.09802); redundant-work straggler
designs (arXiv:1802.03049) additionally need to know whether
*overlapping* shard results merge idempotently. This tier checks all of
it mechanically, every round.

Two layers, mirroring the proven ir/flow/mem split:

- **Merge rules** — structural shapes over fold-SINK classes (a class
  defining both ``consume`` and ``finish``, the shared-scan sink
  protocol): a sink with no merge op at all (``merge-missing-op``), a
  float accumulator in a carry whose merge would reorder summands
  (``merge-order-sensitive-float``), a carry mutated in place while
  also aliased into a cache/closure so a restored checkpoint reads
  stale state (``merge-inplace-aliased-state``), and a carry holding
  threads/open files/generators with no declared host round-trip
  (``merge-unserializable-carry``).
- **Shard-merge/resume auditor** — for every streamed fold kernel in
  the manifest (``stream_entries()``, solo AND fused): (a) split the
  proxy corpus on block boundaries into P ∈ {2, 4} shards, fold each
  shard independently through the job's REGISTERED fold sink
  (``runner.stream_fold_ops``), merge via ``merge_states``, and assert
  the finished artifacts byte-identical to a cold full scan through
  the real runner; (b) checkpoint mid-scan — ``serialize_state`` the
  carry after ~half the chunks, ``restore_state`` into a fresh fold,
  finish, and assert byte-identity again; (c) an overlap probe that
  re-folds one boundary block into a shard and records whether the
  merge absorbed it (idempotent/dedup) or the family is
  non-idempotent — the contract straggler/redundant-work scan designs
  must consult before double-computing a block.

Findings flow through the shared engine (same ``path::rule::scope``
keys, same allowlist baseline); entry points: ``graftlint --merge``
(analysis/cli.py) or :func:`run_merge` in-process. A stream kernel that
fails to RUN raises :class:`MergeAuditError` — the CLI maps that to
exit code 2; a merge or resume that drifts a byte is a finding under
``merge-fold-algebra`` (exit 1): fix the fold's algebra, never
allowlist the drift.
"""

from __future__ import annotations

import ast
import os
import shutil
import tempfile
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from avenir_tpu.analysis.engine import (BaselineEntry, Finding, ModuleContext,
                                        Report, apply_baseline,
                                        collect_findings)
from avenir_tpu.analysis.flow import (OrderSensitiveFoldRule, _MUTATORS,
                                      default_flow_paths)
from avenir_tpu.analysis.mem import _bind_key

#: the audit's pseudo-rule id: a shard merge or checkpoint resume whose
#: output drifted a byte surfaces as a finding under it (never allowlist
#: one — a fold state that is not a merge algebra blocks both the
#: resumable-scan and the multi-host streaming work)
MERGE_AUDIT_RULE = "merge-fold-algebra"

#: block size (MB) the auditor shards and checkpoints at: small enough
#: that both proxy corpora cut into well over 4 blocks, so P=4 shards
#: and the mid-scan checkpoint all land on real boundaries
AUDIT_BLOCK_MB = 0.001

#: shard counts the merge is proven at; 2 exercises one merge, 4
#: exercises merge chaining (associativity of the registered op)
AUDIT_SHARDS = (2, 4)

#: the fold-sink protocol: a class with both methods is a shared-scan
#: sink (runner._STREAM_FOLDS registers them; SharedScan fans to them)
_SINK_METHODS = {"consume", "finish"}

#: method names that count as a declared merge op on a sink class
_MERGE_METHODS = {"merge", "merge_states", "merge_from"}

#: method names that count as a declared host round-trip for the
#: unserializable-carry rule
_ROUNDTRIP_METHODS = {"state_dict", "load_state", "serialize_state",
                      "__getstate__"}

#: constructors whose result cannot cross a serialize/restore boundary
_UNSERIALIZABLE_CTORS = {
    "open", "iter",
    "threading.Thread", "threading.Lock", "threading.RLock",
    "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Event",
    "subprocess.Popen", "socket.socket", "socket.create_connection",
}

#: shared float-init recognizer (the flow tier's, applied to carries)
_FLOAT_INIT = OrderSensitiveFoldRule()


class MergeAuditError(RuntimeError):
    """A streamed fold kernel could not be prepared, driven or merged."""


# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------
def _methods_of(node: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {m.name: m for m in node.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _fold_sink_classes(ctx: ModuleContext
                       ) -> Iterator[Tuple[ast.ClassDef,
                                           Dict[str, ast.FunctionDef]]]:
    """Classes implementing the fold-sink protocol (consume + finish) —
    the carries whose merge algebra this tier judges."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            methods = _methods_of(node)
            if _SINK_METHODS <= set(methods):
                yield node, methods


def _method_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Nodes of a method body, not descending into nested defs."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _self_attr(node: ast.AST) -> Optional[str]:
    """`attr` when `node` is a ``self.attr`` expression, else None."""
    key = _bind_key(node)
    return key[1:] if key is not None and key.startswith(".") else None


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------
class MergeRule:
    rule_id: str = ""
    description: str = ""
    hint: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(ctx.path, getattr(node, "lineno", 1), self.rule_id,
                       message, hint or self.hint, ctx.scope_of(node))


class MergeMissingOpRule(MergeRule):
    """A fold-sink class (defines both ``consume`` and ``finish``) with
    no declared merge op (no ``merge``/``merge_states``/``merge_from``
    method). Its carry can be folded but never combined: the job cannot
    shard across hosts, cannot fold an appended delta into a saved
    carry, and cannot survive the redundant-work straggler designs —
    every path the ROADMAP's two heaviest items need. Every sink in
    ``runner._STREAM_FOLDS`` carries one by construction."""

    rule_id = "merge-missing-op"
    description = "fold sink has no registered merge/serialize op"
    hint = ("implement `merge(other)` as an additive combine of the "
            "sufficient statistic (the NaiveBayesModel.merge pattern; "
            "miners use models.association.merge_support_counts), or "
            "allowlist only a sink whose state provably merges at "
            "another level (say which)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node, methods in _fold_sink_classes(ctx):
            if _MERGE_METHODS & set(methods):
                continue
            yield self.finding(
                ctx, node,
                f"fold sink `{node.name}` (consume + finish) declares no "
                f"merge op: its carry cannot combine across shards or "
                f"resume from a checkpoint")


class MergeOrderSensitiveFloatRule(MergeRule):
    """A fold-sink carry accumulating NON-integer floats: an attribute
    initialized to a float in ``__init__`` and ``+=``-folded in
    ``consume`` (or in the merge op itself). ``merge(A, B)`` computes
    ``(a1+...+an) + (b1+...+bm)`` — a different summation tree than the
    in-order fold — so float reassociation makes the merged result
    drift from ``fold(A++B)`` in the last bits, and the shard-merge
    audit's byte-identity is unprovable. Integer-dtype carries (and
    integer-valued float64 counts, the repo's standard) are exact under
    any grouping and stay silent."""

    rule_id = "merge-order-sensitive-float"
    description = "float accumulation in a carry whose merge reorders summands"
    hint = ("carry exact values (integer dtypes, or integer-valued "
            "float64 counts within the documented exactness bound — see "
            "NaiveBayesModel._FLUSH_ROWS), or use a compensated/"
            "fixed-order reduction and register the kernel's tolerance "
            "explicitly instead of claiming byte-identity")

    _FOLD_METHODS = ("consume",) + tuple(sorted(_MERGE_METHODS))

    def _float_attr_inits(self, ctx: ModuleContext,
                          init: ast.FunctionDef) -> Set[str]:
        out: Set[str] = set()
        for node in _method_nodes(init):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            attr = _self_attr(node.targets[0])
            if attr is None:
                continue
            if _FLOAT_INIT._is_float_init(ctx, node.value):
                out.add(attr)
        return out

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for cls, methods in _fold_sink_classes(ctx):
            init = methods.get("__init__")
            if init is None:
                continue
            floats = self._float_attr_inits(ctx, init)
            if not floats:
                continue
            seen: Set[str] = set()
            for mname in self._FOLD_METHODS:
                fn = methods.get(mname)
                if fn is None:
                    continue
                for node in _method_nodes(fn):
                    attr: Optional[str] = None
                    if isinstance(node, ast.AugAssign) \
                            and isinstance(node.op, ast.Add):
                        attr = _self_attr(node.target)
                    elif isinstance(node, ast.Assign) \
                            and len(node.targets) == 1 \
                            and isinstance(node.value, ast.BinOp) \
                            and isinstance(node.value.op, ast.Add):
                        tgt = _self_attr(node.targets[0])
                        left = _self_attr(node.value.left)
                        if tgt is not None and tgt == left:
                            attr = tgt
                    if attr in floats and attr not in seen:
                        seen.add(attr)
                        yield self.finding(
                            ctx, node,
                            f"float carry `self.{attr}` accumulates in "
                            f"`{cls.name}.{mname}`: a shard merge "
                            f"re-groups its summands, so merged output "
                            f"drifts from the in-order fold's bytes")


class MergeInplaceAliasedStateRule(MergeRule):
    """A fold-sink carry mutated IN PLACE while also aliased outside the
    sink — stored into a module/cache container or captured by a nested
    function. After ``restore_state`` builds a fresh carry, the alias
    still points at the pre-checkpoint object: the cache serves stale
    state and the closure mutates an orphan. Reassignment
    (``self.x = self.x + d``) rebinds instead of mutating and stays
    silent, as does state that never escapes the sink."""

    rule_id = "merge-inplace-aliased-state"
    description = "carry mutated in place while aliased by a cache/closure"
    hint = ("keep the carry private to the sink (hand copies outward), "
            "or rebind on every fold (`self.x = self.x + d`) so an old "
            "alias can never observe post-checkpoint mutation")

    def _inplace_attrs(self, methods) -> Dict[str, ast.AST]:
        out: Dict[str, ast.AST] = {}
        for fn in methods.values():
            for node in _method_nodes(fn):
                attr: Optional[str] = None
                if isinstance(node, ast.AugAssign):
                    attr = _self_attr(node.target)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATORS:
                    attr = _self_attr(node.func.value)
                if attr is not None and attr not in out:
                    out[attr] = node
        return out

    def _escaped_attrs(self, methods) -> Set[str]:
        out: Set[str] = set()
        for fn in methods.values():
            for node in _method_nodes(fn):
                # CACHE[key] = self.attr — stored into a container that
                # is not the sink's own attribute
                if isinstance(node, ast.Assign):
                    attr = _self_attr(node.value)
                    if attr is not None and any(
                            isinstance(t, ast.Subscript)
                            and _self_attr(t.value) is None
                            for t in node.targets):
                        out.add(attr)
                # self.attr captured by a nested def/lambda
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    for sub in ast.walk(node):
                        attr = _self_attr(sub)
                        if attr is not None:
                            out.add(attr)
        return out

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for cls, methods in _fold_sink_classes(ctx):
            inplace = self._inplace_attrs(methods)
            escaped = self._escaped_attrs(methods)
            for attr in sorted(set(inplace) & escaped):
                yield self.finding(
                    ctx, inplace[attr],
                    f"carry `self.{attr}` of `{cls.name}` is mutated in "
                    f"place AND aliased outside the sink: a restored "
                    f"checkpoint leaves the alias pointing at stale "
                    f"pre-checkpoint state")


class MergeUnserializableCarryRule(MergeRule):
    """A fold-sink carry binding resources that cannot cross a
    serialize/restore boundary — open files, threads, processes,
    sockets, locks, or live generators/iterators — in a class that
    declares no host round-trip (``state_dict``/``load_state``/
    ``serialize_state``/``__getstate__``). Checkpointing such a sink
    either fails outright or silently drops the resource's position.
    A sink that DOES declare the round-trip owns the problem (its
    state_dict must re-derive the resource) and stays silent."""

    rule_id = "merge-unserializable-carry"
    description = "carry holds threads/files/generators with no round-trip"
    hint = ("carry plain data (paths, offsets, count arrays) and "
            "re-open/re-derive the resource after restore, or declare "
            "the round-trip by implementing state_dict()/load_state() "
            "so the checkpoint contract is explicit")

    def _bad_value(self, ctx: ModuleContext, value: ast.AST
                   ) -> Optional[str]:
        if isinstance(value, ast.GeneratorExp):
            return "a live generator"
        if isinstance(value, ast.Call):
            name = ctx.dotted(value.func)
            if name in _UNSERIALIZABLE_CTORS:
                return f"`{name}(...)`"
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for cls, methods in _fold_sink_classes(ctx):
            if _ROUNDTRIP_METHODS & set(methods):
                continue
            for fn in methods.values():
                for node in _method_nodes(fn):
                    if not isinstance(node, ast.Assign) \
                            or len(node.targets) != 1:
                        continue
                    attr = _self_attr(node.targets[0])
                    if attr is None:
                        continue
                    what = self._bad_value(ctx, node.value)
                    if what is not None:
                        yield self.finding(
                            ctx, node,
                            f"carry `self.{attr}` of `{cls.name}` holds "
                            f"{what}: it cannot cross a checkpoint "
                            f"serialize/restore boundary and no host "
                            f"round-trip is declared")


ALL_MERGE_RULES = [MergeMissingOpRule, MergeOrderSensitiveFloatRule,
                   MergeInplaceAliasedStateRule,
                   MergeUnserializableCarryRule]


def merge_rule_ids() -> List[str]:
    return [r.rule_id for r in ALL_MERGE_RULES] + [MERGE_AUDIT_RULE]


# --------------------------------------------------------------------------
# shard-merge / resume auditor
# --------------------------------------------------------------------------
def _job_contexts(spec, ctx: dict, block_mb: float) -> List[tuple]:
    """[(job, prefix, props, cfg, ops)] for every fold the spec
    registers, conf values formatted against the prepared corpus ctx
    exactly like manifest._job_runner does. `props` is the raw prefixed
    dict the incremental leg re-feeds to runner.run_incremental."""
    from avenir_tpu.runner import _job_cfg, stream_fold_ops

    if not getattr(spec, "fold_specs", ()):
        raise MergeAuditError(
            f"{spec.name}: stream entry carries no fold_specs; the "
            f"merge auditor drives registered fold sinks directly")
    out = []
    for job, prefix, conf in spec.fold_specs:
        props = {k: (v.format(**ctx) if isinstance(v, str) else v)
                 for k, v in conf.items()}
        props[f"{prefix}.stream.block.size.mb"] = repr(float(block_mb))
        canonical, _prefix, cfg = _job_cfg(job, props)
        out.append((canonical, prefix, props, cfg,
                    stream_fold_ops(canonical)))
    kinds = {ops.kind for _j, _p, _pr, _c, ops in out}
    if len(kinds) != 1:
        raise MergeAuditError(f"{spec.name}: mixed fold kinds {kinds}")
    return out


def _load_schema(ctx: dict):
    if "schema" not in ctx:
        return None
    from avenir_tpu.core.schema import FeatureSchema

    return FeatureSchema.from_file(ctx["schema"])


def _chunk_list(kind: str, cfg, paths: Sequence[str], schema) -> list:
    """The REAL runner chunk feed (stream_job_inputs /
    stream_job_byte_blocks), materialized — the audit corpora are a few
    tens of KB, and the checkpoint split needs random access."""
    from avenir_tpu.core.stream import (stream_job_byte_blocks,
                                        stream_job_inputs)

    if kind == "dataset":
        return list(stream_job_inputs(cfg, list(paths), schema))
    return list(stream_job_byte_blocks(cfg, list(paths)))


def _drive(jobs_ctx: List[tuple], paths: Sequence[str], schema) -> list:
    """Build every job's registered fold sink over `paths` and drive
    them through ONE SharedScan of the real chunk feed — the exact
    fan-out the fused runner uses — returning the fed folds."""
    from avenir_tpu.core.stream import SharedScan

    kind = jobs_ctx[0][-1].kind
    folds = [ops.factory(cfg, list(paths), schema)
             for _job, _pfx, _props, cfg, ops in jobs_ctx]
    chunks = _chunk_list(kind, jobs_ctx[0][3], paths, schema)
    scan = SharedScan(iter(chunks))
    for fold in folds:
        scan.add_sink(fold)
    scan.run()
    return folds


def _tagged_outputs(job: str, outputs: Sequence[str], out: str,
                    multi: bool) -> List[bytes]:
    """Name-tagged artifact blobs of one job's output files — the same
    rendering _job_runner/_finish_artifact use, so every leg of the
    audit compares byte-for-byte against spec.run() baselines."""
    blobs = []
    for p in sorted(outputs):
        rel = os.path.relpath(p, out)
        tag = f"{job}:{rel}" if multi else rel
        with open(p, "rb") as fh:
            blobs.append(tag.encode() + b"\0" + fh.read())
    return blobs


def _finish_artifact(jobs_ctx: List[tuple], folds: list, out_base: str
                     ) -> bytes:
    """finish() every fold and render the same name-tagged artifact the
    manifest runners produce (job-prefixed tags when the entry fuses
    multiple jobs), so comparisons against spec.run() baselines are
    byte-for-byte."""
    multi = len(jobs_ctx) > 1
    blobs = []
    for (job, _pfx, _props, _cfg, _ops), fold in zip(jobs_ctx, folds):
        out = f"{out_base}_{job}"
        res = fold.finish(out)
        blobs.extend(_tagged_outputs(job, res.outputs, out, multi))
    return b"\n".join(blobs)


def _shard_files(workdir: str, blocks: List[bytes], P: int, tag: str,
                 overlap: bool = False) -> List[str]:
    """Write P shard files of consecutive block runs covering the corpus
    exactly once (row-aligned: blocks come from iter_byte_blocks, which
    cuts at line boundaries). With `overlap`, shard 0 additionally
    re-contains shard 1's first block — the redundant-work probe."""
    bounds = [round(i * len(blocks) / P) for i in range(P + 1)]
    paths = []
    for i in range(P):
        part = blocks[bounds[i]:bounds[i + 1]]
        if overlap and i == 0 and bounds[1] < len(blocks):
            part = part + [blocks[bounds[1]]]
        p = os.path.join(workdir, f"shard_{tag}_{P}_{i}.csv")
        with open(p, "wb") as fh:
            fh.write(b"".join(part))
        paths.append(p)
    return paths


class _AuditInterrupt(Exception):
    """Injected mid-scan kill of the incremental leg's append run."""


def _incremental_leg(workdir: str, jobs_ctx: List[tuple],
                     blocks: List[bytes], baseline: bytes) -> dict:
    """(d) incremental + crash-resume leg, through the REAL driver
    (runner.run_incremental): cold-scan a PREFIX corpus (writing the
    final fold-state checkpoint + block fingerprints), append the
    remaining blocks, and re-run — the driver must restore the carry,
    fold only the delta blocks, and reproduce the cold full scan's
    bytes. The append run is additionally killed right after its first
    MID-SCAN checkpoint (the core.incremental._checkpoint_hook) and
    re-run, so a genuine mid-corpus kill-and-resume crosses the auditor
    every round. Fused entries drive each registered job's driver
    separately (the delta-scan driver is per-job; fusion stays a
    SharedScan concern)."""
    from avenir_tpu.core import incremental as incr
    from avenir_tpu.runner import run_incremental

    grow = os.path.join(workdir, "grow.csv")
    half = max(1, len(blocks) // 2)
    with open(grow, "wb") as fh:
        fh.write(b"".join(blocks[:half]))

    multi = len(jobs_ctx) > 1

    def run_all(tag: str):
        blobs: List[bytes] = []
        results = []
        for job, prefix, props, _cfg, _ops in jobs_ctx:
            out = os.path.join(workdir, f"incr_{tag}_{job}")
            p = dict(props)
            # checkpoint every block so the kill probe has a mid-delta
            # watermark to die at (and resume from)
            p[f"{prefix}.stream.checkpoint.interval.mb"] = "0.00001"
            res = run_incremental(
                job, p, [grow], out,
                state_dir=os.path.join(workdir, f"incr_state_{job}"))
            results.append(res)
            blobs.extend(_tagged_outputs(job, res.outputs, out, multi))
        return b"\n".join(blobs), results

    run_all("cold")                       # seeds the checkpoints
    with open(grow, "ab") as fh:
        fh.write(b"".join(blocks[half:]))

    def interrupter(meta: dict) -> None:
        if not meta.get("complete"):
            raise _AuditInterrupt()

    prev = incr._checkpoint_hook
    incr._checkpoint_hook = interrupter
    interrupted = False
    try:
        run_all("kill")                   # dies after one delta block
    except _AuditInterrupt:
        interrupted = True
    finally:
        incr._checkpoint_hook = prev

    art, results = run_all("resume")
    # min across the entry's jobs: EVERY registered driver (fused
    # entries run one per job) must have restored a carry and skipped
    # its prefix, or the verdict gate fails — a single job regressing
    # to always-cold cannot hide behind its sibling's counters
    cs = [r.counters for r in results]
    return {
        "blocks": len(blocks), "prefix_blocks": half,
        "hit_blocks": min(int(c["Cache:HitBlocks"]) for c in cs),
        "delta_blocks": min(int(c["Cache:DeltaBlocks"]) for c in cs),
        "skipped_bytes": min(int(c["Resume:SkippedBytes"]) for c in cs),
        "resume_interrupted": interrupted,
        "byte_identical": art == baseline,
        "fused": _fused_incremental_leg(workdir, jobs_ctx, blocks,
                                        baseline),
    }


def _fused_incremental_leg(workdir: str, jobs_ctx: List[tuple],
                           blocks: List[bytes], baseline: bytes) -> dict:
    """(e) FUSED incremental leg, through the batched delta-scan driver
    (runner.run_incremental_shared — the job server's refresh path):
    cold-seed ALL the entry's jobs' checkpoints with one fused call
    over a prefix corpus, append the remaining blocks, kill the fused
    refresh right after its first mid-delta checkpoint, and re-run —
    every job must restore its carry, the group must fold the delta
    through ONE SharedScan, and the finished artifacts must reproduce
    the cold full scan's bytes. Single-job entries run the same driver
    with a one-spec group, so the fused path is proven on all 8
    streamed kernels every round, not just the two fused entries."""
    from avenir_tpu.core import incremental as incr
    from avenir_tpu.runner import run_incremental_shared

    grow = os.path.join(workdir, "grow_fused.csv")
    half = max(1, len(blocks) // 2)
    with open(grow, "wb") as fh:
        fh.write(b"".join(blocks[:half]))
    multi = len(jobs_ctx) > 1
    state_dirs = {job: os.path.join(workdir, f"fincr_state_{job}")
                  for job, _p, _pr, _c, _o in jobs_ctx}

    def run_fused(tag: str):
        specs = []
        for job, prefix, props, _cfg, _ops in jobs_ctx:
            p = dict(props)
            # checkpoint every block so the kill probe has a mid-delta
            # watermark to die at (and resume from)
            p[f"{prefix}.stream.checkpoint.interval.mb"] = "0.00001"
            specs.append((job, p, os.path.join(workdir,
                                               f"fincr_{tag}_{job}")))
        shared = run_incremental_shared(specs, [grow],
                                        state_dirs=state_dirs)
        blobs: List[bytes] = []
        for job, _prefix, _props, _cfg, _ops in jobs_ctx:
            res = shared[job]
            blobs.extend(_tagged_outputs(
                job, res.outputs, os.path.join(workdir,
                                               f"fincr_{tag}_{job}"),
                multi))
        return b"\n".join(blobs), [shared[j] for j, *_ in jobs_ctx]

    run_fused("cold")                     # seeds every job's checkpoint
    with open(grow, "ab") as fh:
        fh.write(b"".join(blocks[half:]))

    def interrupter(meta: dict) -> None:
        if not meta.get("complete"):
            raise _AuditInterrupt()

    prev = incr._checkpoint_hook
    incr._checkpoint_hook = interrupter
    interrupted = False
    try:
        run_fused("kill")                 # dies after one delta block
    except _AuditInterrupt:
        interrupted = True
    finally:
        incr._checkpoint_hook = prev

    art, results = run_fused("resume")
    cs = [r.counters for r in results]
    return {
        "jobs": len(jobs_ctx),
        "hit_blocks": min(int(c["Cache:HitBlocks"]) for c in cs),
        "skipped_bytes": min(int(c["Resume:SkippedBytes"]) for c in cs),
        "resume_interrupted": interrupted,
        "byte_identical": art == baseline,
    }


def _sharded_steal_leg(workdir: str, jobs_ctx: List[tuple], ctx: dict,
                       baseline: bytes) -> dict:
    """(f) sharded-steal leg, through the REAL dist primitives
    (avenir_tpu.dist): the shard planner cuts the corpus into
    newline-aligned blocks, worker 0 claims and commits EVERY block
    through the block ledger (the fast-worker steal shape: half of
    those blocks are worker 1's home run), then worker 1 redundantly
    folds the BOUNDARY block — the first block of its own stolen home
    run, the exact block a straggler and its mirror race over — and its
    duplicate commit must be REJECTED first-commit-wins. The
    plan-ordered merge of committed states must reproduce the cold
    scan's bytes: the ledger folded every block into the final state
    exactly once, although two workers computed one of them. This is
    the overlap probe's contract made mechanical — every family is
    NON-idempotent, so the dedup, not the fold, is what keeps redundant
    execution safe."""
    from avenir_tpu.dist.driver import merge_block_states
    from avenir_tpu.dist.ledger import BlockLedger
    from avenir_tpu.dist.plan import plan_shards
    from avenir_tpu.dist.worker import fold_block

    csv = ctx["csv"]
    schema = _load_schema(ctx)
    plan = plan_shards([csv], procs=2, factor=2)
    boundary = plan.blocks_for(1)[0]
    dup_rejected = True
    committed_once = True
    folds = []
    for job, _prefix, _props, cfg, ops in jobs_ctx:
        root = os.path.join(workdir, f"steal_{job}")
        ledger = BlockLedger(root)
        def close_src(f) -> None:
            # a serialized-then-discarded MINER fold still owns its
            # streaming source (spill cache, fds); drop it explicitly
            src = getattr(f, "src", None)
            if src is not None:
                src.close()

        for blk in plan.blocks:
            if not ledger.claim(blk.id, worker=0):
                raise MergeAuditError(
                    f"{job}: worker 0 lost an uncontended claim on "
                    f"block {blk.id}")
            fold = fold_block(job, cfg, ops, schema, [csv], csv,
                              blk.start, blk.end)
            committed = ledger.commit(blk.id, 0,
                                      ops.serialize_state(fold))
            close_src(fold)
            if not committed:
                raise MergeAuditError(
                    f"{job}: worker 0's first commit of block "
                    f"{blk.id} was rejected")
        # worker 1 redundantly computes the boundary block (the
        # straggler-mirror shape); its commit MUST lose
        fold = fold_block(job, cfg, ops, schema, [csv], csv,
                          boundary.start, boundary.end)
        won = ledger.commit(boundary.id, 1, ops.serialize_state(fold))
        close_src(fold)
        if won:
            dup_rejected = False
        if len(ledger.committed()) != len(plan.blocks) \
                or ledger.dup_count() < 1:
            committed_once = False
        states = {bid: ledger.load_state(bid)
                  for bid in ledger.committed()}
        folds.append(merge_block_states(job, cfg, ops, plan, states,
                                        [csv], root, schema=schema))
    art = _finish_artifact(jobs_ctx, folds,
                           os.path.join(workdir, "steal_merge"))
    return {
        "blocks": len(plan.blocks),
        "boundary_block": boundary.id,
        "dup_rejected": dup_rejected,
        "committed_once": committed_once,
        "byte_identical": art == baseline,
    }


def audit_merge(spec, shard_counts: Sequence[int] = AUDIT_SHARDS,
                block_mb: float = AUDIT_BLOCK_MB
                ) -> Tuple[dict, Optional[Finding]]:
    """Prove one stream entry's fold state is a merge algebra: shard
    folds merge to the cold full scan's bytes at every P, a mid-scan
    checkpoint resumes to the same bytes, the overlap probe records
    the family's idempotency contract, and the incremental leg
    re-proves append-refresh + crash-resume byte-identity through the
    real delta-scan driver. Returns (audit row, finding or None); a
    kernel that fails to RUN raises :class:`MergeAuditError`."""
    from avenir_tpu.core.stream import iter_byte_blocks

    workdir = tempfile.mkdtemp(prefix=f"graftlint_merge_{spec.name}_")
    try:
        ctx = spec.prepare(workdir)
        jobs_ctx = _job_contexts(spec, ctx, block_mb)
        kind = jobs_ctx[0][-1].kind
        baseline = spec.run(ctx, block_mb)

        block_bytes = max(int(block_mb * (1 << 20)), 64)
        blocks = list(iter_byte_blocks(ctx["csv"], block_bytes))
        enough = len(blocks) >= max(shard_counts)

        shard_rows: List[dict] = []
        checkpoint: Optional[dict] = None
        overlap: Optional[dict] = None
        incremental: Optional[dict] = None
        sharded: Optional[dict] = None
        if enough:
            for P in shard_counts:
                shards = _shard_files(workdir, blocks, P, "m")
                folds = []
                for shard in shards:
                    fed = _drive(jobs_ctx, [shard], _load_schema(ctx))
                    folds.append(fed)
                merged = folds[0]
                for nxt in folds[1:]:
                    merged = [ops.merge_states(a, b)
                              for (_j, _p, _pr, _c, ops), a, b
                              in zip(jobs_ctx, merged, nxt)]
                art = _finish_artifact(
                    jobs_ctx, merged, os.path.join(workdir, f"merge{P}"))
                shard_rows.append({
                    "P": P, "blocks": len(blocks),
                    "byte_identical": art == baseline,
                })

            # (b) checkpoint mid-scan: serialize after ~half the chunks,
            # restore into FRESH folds, finish, compare
            schema = _load_schema(ctx)
            chunks = _chunk_list(kind, jobs_ctx[0][3], [ctx["csv"]], schema)
            half = max(1, len(chunks) // 2)
            folds = [ops.factory(cfg, [ctx["csv"]], schema)
                     for _j, _p, _pr, cfg, ops in jobs_ctx]
            for chunk in chunks[:half]:
                for fold in folds:
                    fold.consume(chunk)
            states = [ops.serialize_state(fold)
                      for (_j, _p, _pr, _c, ops), fold
                      in zip(jobs_ctx, folds)]
            restored = [ops.restore_state(cfg, [ctx["csv"]], blob,
                                          schema=schema)
                        for (_j, _p, _pr, cfg, ops), blob
                        in zip(jobs_ctx, states)]
            for chunk in chunks[half:]:
                for fold in restored:
                    fold.consume(chunk)
            ck_art = _finish_artifact(jobs_ctx, restored,
                                      os.path.join(workdir, "resume"))
            checkpoint = {
                "chunks": len(chunks), "checkpoint_after": half,
                "state_bytes": int(sum(len(b) for b in states)),
                "byte_identical": ck_art == baseline,
            }

            # (c) overlap probe: shard 0 re-folds shard 1's first block;
            # additive count families MUST change their output (the
            # merge is not idempotent — redundant-work designs have to
            # dedup at block granularity BEFORE the fold), so the row
            # records the contract instead of asserting identity
            shards = _shard_files(workdir, blocks, 2, "o", overlap=True)
            folds = [_drive(jobs_ctx, [shard], _load_schema(ctx))
                     for shard in shards]
            merged = [ops.merge_states(a, b)
                      for (_j, _p, _pr, _c, ops), a, b
                      in zip(jobs_ctx, folds[0], folds[1])]
            ov_art = _finish_artifact(jobs_ctx, merged,
                                      os.path.join(workdir, "overlap"))
            overlap = {
                "output_changed": ov_art != baseline,
                "contract": ("non-idempotent" if ov_art != baseline
                             else "overlap-insensitive"),
            }

            # (d) incremental delta-scan + crash-resume, real driver
            incremental = _incremental_leg(workdir, jobs_ctx, blocks,
                                           baseline)

            # (f) sharded-steal: two workers race one boundary block
            # through the block ledger; first commit wins, the merge
            # sees the block exactly once
            sharded = _sharded_steal_leg(workdir, jobs_ctx, ctx,
                                         baseline)
    except MergeAuditError:
        raise
    except Exception as e:
        raise MergeAuditError(
            f"{spec.name}: fold kernel failed to drive/merge: {e!r}") from e
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    ok = enough and all(r["byte_identical"] for r in shard_rows) \
        and checkpoint is not None and checkpoint["byte_identical"]
    fused = incremental.get("fused") if incremental else None
    incr_ok = (incremental is not None
               and incremental["byte_identical"]
               and incremental["resume_interrupted"]
               and incremental["skipped_bytes"] > 0
               # the fused (batched) refresh driver must reproduce the
               # same bytes with a restored carry per job — the job
               # server's refresh path is gated here every round
               and fused is not None
               and fused["byte_identical"]
               and fused["resume_interrupted"]
               and fused["skipped_bytes"] > 0)
    # the sharded-steal leg: a boundary block folded by two workers'
    # redundant executions must commit exactly once through the block
    # ledger AND the plan-ordered merge must reproduce the cold bytes —
    # the dedup contract the multi-process sharded driver
    # (avenir_tpu.dist) rests on, re-proven per stream entry per round
    shard_ok = (sharded is not None
                and sharded["dup_rejected"]
                and sharded["committed_once"]
                and sharded["byte_identical"])
    row = {
        "kernel": spec.name,
        "jobs": [j for j, _p, _pr, _c, _o in jobs_ctx],
        "block_mb": float(block_mb),
        "shards": shard_rows,
        "checkpoint": checkpoint,
        "overlap": overlap,
        "incremental": incremental,
        "sharded": sharded,
        "merge_validated": ok,
        "incremental_validated": incr_ok,
        "shard_dedup_validated": shard_ok,
    }
    finding = None
    if not ok or not incr_ok or not shard_ok:
        if not enough:
            why = (f"corpus cut into only {len(blocks)} blocks at "
                   f"{block_mb:g}MB — too few for P={max(shard_counts)} "
                   f"shards (auditor corpus too small)")
        else:
            bad = [f"P={r['P']}" for r in shard_rows
                   if not r["byte_identical"]]
            if not checkpoint["byte_identical"]:
                bad.append("checkpoint-resume")
            if not incr_ok:
                solo_ok = (incremental is not None
                           and incremental["byte_identical"]
                           and incremental["resume_interrupted"]
                           and incremental["skipped_bytes"] > 0)
                bad.append("fused-incremental-append/resume" if solo_ok
                           else "incremental-append/resume")
            if not shard_ok:
                bad.append("sharded-steal-dedup")
            why = f"output bytes drifted under: {', '.join(bad)}"
        finding = Finding(
            spec.path, spec.line, MERGE_AUDIT_RULE,
            f"streamed kernel `{spec.name}` is not a merge algebra: {why}",
            "make the carry an exact additive sufficient statistic with "
            "a lossless state_dict (see runner.StreamFoldOps); never "
            "allowlist a merge drift",
            spec.name)
    return row, finding


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------
def run_merge(paths: Optional[Sequence[str]] = None,
              rules: Optional[Sequence[MergeRule]] = None,
              baseline: Optional[Sequence[BaselineEntry]] = None,
              root: Optional[str] = None, include_md: bool = True,
              audit: bool = True, entries: Optional[Sequence] = None,
              shard_counts: Sequence[int] = AUDIT_SHARDS) -> Report:
    """Lint `paths` (default: the gated repo surface) with the merge
    rules, run the shard-merge/resume auditor over the streamed-kernel
    manifest, and apply the allowlist baseline to both finding sets."""
    active = list(rules) if rules is not None else \
        [r() for r in ALL_MERGE_RULES]
    root = os.path.abspath(root or os.getcwd())
    scan = list(paths) if paths else default_flow_paths(root)
    report, raw = collect_findings(scan, active, root, include_md)
    if audit:
        specs = list(entries) if entries is not None else None
        if specs is None:
            from avenir_tpu.analysis.manifest import stream_entries
            specs = stream_entries()
        for spec in specs:
            # NOT added to report.scanned — same reasoning as the other
            # audit tiers: the audit drives the kernel, it does not lint
            # its file
            row, finding = audit_merge(spec, shard_counts=shard_counts)
            report.merge_audit.append(row)
            if finding is not None:
                raw.append(finding)
    active_ids = {r.rule_id for r in active}
    if audit:
        active_ids.add(MERGE_AUDIT_RULE)
    apply_baseline(report, raw, baseline, active_ids)
    return report
