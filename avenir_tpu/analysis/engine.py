"""graftlint engine: source discovery, AST context, baseline, rule runner.

The engine is deliberately dumb about semantics — every rule is a lexical
pattern over one module's AST plus a little import-alias resolution. That
is the Casper lesson (arXiv:1801.09802): the code shapes worth rewriting
for an accelerator are *syntactically* recognizable, so recognize them at
review time instead of re-deriving them from RSS graphs after the fact.

Findings are keyed ``path::rule::scope`` (scope = dotted enclosing
class/function, ``<module>`` at top level) rather than by line number, so
the allowlist baseline survives unrelated edits to the same file.
Markdown files contribute their ```python fences (the docs/ tutorials are
executable via tests/test_tutorials.py, so they are lintable surface —
the unseeded-stochastic-test rule exists because one of them flaked).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

_FENCE = re.compile(r"```python[ \t]*\n(.*?)```", re.DOTALL)

#: modules whose attribute calls the rules resolve through import aliases
_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While,
               ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


@dataclass(frozen=True)
class Finding:
    """One rule hit: location, rule id, message and a concrete fix hint."""

    path: str          # posix path relative to the scan root
    line: int
    rule: str
    message: str
    hint: str
    scope: str         # dotted enclosing def/class chain, '<module>' at top

    @property
    def key(self) -> str:
        """Baseline-matching identity (line numbers drift; scopes don't)."""
        return f"{self.path}::{self.rule}::{self.scope}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} [{self.scope}] "
                f"{self.message}\n    fix: {self.hint}")

    def to_json(self) -> dict:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "scope": self.scope, "message": self.message,
                "hint": self.hint, "key": self.key}


@dataclass
class BaselineEntry:
    key: str
    justification: str
    lineno: int
    used: int = 0


@dataclass
class Report:
    """One analyzer run: surviving findings + what the baseline absorbed.

    `payload_audit` is filled only by IR runs (analysis/ir.py): one entry
    per distributed family with its HLO-vs-analytic collective payload
    verdict. `invariance_audit` is filled only by flow runs
    (analysis/flow.py): one entry per streamed fold kernel with its
    chunk-layout/scheduler byte-identity verdict. `footprint_audit` is
    filled only by mem runs (analysis/mem.py): one entry per streamed
    job with its measured-RSS-vs-analytic-footprint verdict.
    `merge_audit` is filled only by merge runs (analysis/merge.py): one
    entry per streamed fold kernel with its shard-merge/checkpoint-
    resume byte-identity verdict. `proto_audit` is filled only by proto
    runs (analysis/proto.py): one entry per registered commit site with
    its kill-injection crash/recovery byte-identity verdict.
    `race_audit` is filled only by race runs (analysis/race.py): one
    entry per registered interleave site with its schedule-exploration
    verdict. `key_audit` is filled only by keys runs
    (analysis/keys.py): one entry per registered key site with its
    perturbation verdict. Other modes leave them empty — the keys are always
    present in the JSON so downstream tripwires can parse one
    schema."""

    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stale: List[BaselineEntry] = field(default_factory=list)
    scanned: List[str] = field(default_factory=list)
    errors: List[Finding] = field(default_factory=list)
    payload_audit: List[dict] = field(default_factory=list)
    invariance_audit: List[dict] = field(default_factory=list)
    footprint_audit: List[dict] = field(default_factory=list)
    merge_audit: List[dict] = field(default_factory=list)
    proto_audit: List[dict] = field(default_factory=list)
    race_audit: List[dict] = field(default_factory=list)
    key_audit: List[dict] = field(default_factory=list)

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    @property
    def clean(self) -> bool:
        return not self.findings and not self.stale and not self.errors

    def to_json(self) -> dict:
        return {
            "findings": [f.to_json() for f in self.findings],
            "counts": self.counts(),
            "suppressed": len(self.suppressed),
            "stale_baseline_entries": [e.key for e in self.stale],
            "errors": [f.to_json() for f in self.errors],
            "files_scanned": len(self.scanned),
            "payload_audit": self.payload_audit,
            "invariance_audit": self.invariance_audit,
            "footprint_audit": self.footprint_audit,
            "merge_audit": self.merge_audit,
            "proto_audit": self.proto_audit,
            "race_audit": self.race_audit,
            "key_audit": self.key_audit,
            "clean": self.clean,
        }


class ModuleContext:
    """Parsed module + the shared lookups every rule needs: parent links,
    import-alias resolution, loop/scope ancestry, jit-decoration info."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.aliases = self._collect_aliases(tree)
        self.module_names = self._module_level_names(tree)
        self.jitted_names = self._collect_jitted_names(tree)
        self.jitted_donating = self._collect_donating_names(tree)

    # ------------------------------------------------------------ imports
    @staticmethod
    def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
        aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of an expression like ``np.random.choice``
        (import aliases resolved), or None for non-name expressions."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])

    # ------------------------------------------------------------ ancestry
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def in_loop(self, node: ast.AST) -> bool:
        """True when `node` executes per-iteration of a lexical loop
        (for/while/comprehension), stopping at function boundaries — the
        analyzer's structural proxy for "hot path". A `for` statement's
        iterable and a comprehension's first source evaluate once, so
        they don't count for the loop they feed (an enclosing loop still
        does)."""
        path = [node]
        cur = self.parent(node)
        while cur is not None:
            path.append(cur)
            cur = self.parent(cur)
        for i in range(1, len(path)):
            anc, below = path[i], path[i - 1]
            if isinstance(anc, _SCOPE_NODES):
                return False
            if isinstance(anc, (ast.For, ast.AsyncFor)):
                if below is not anc.iter:
                    return True
            elif isinstance(anc, ast.While):
                return True
            elif isinstance(anc, (ast.ListComp, ast.SetComp, ast.DictComp,
                                  ast.GeneratorExp)):
                gens = anc.generators
                if gens and gens[0].iter in path[:i]:
                    continue
                return True
        return False

    def scope_of(self, node: ast.AST) -> str:
        names: List[str] = []
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            cur = self.parent(cur)
        return ".".join(reversed(names)) if names else "<module>"

    def enclosing_functions(self, node: ast.AST
                            ) -> List[ast.FunctionDef]:
        """Function defs lexically containing `node`, innermost first."""
        out: List[ast.FunctionDef] = []
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self.parent(cur)
        return out

    # ---------------------------------------------------------------- jit
    def jit_static_names(self, fn: ast.FunctionDef) -> Optional[Set[str]]:
        """None when `fn` is not jit-decorated; else the set of parameter
        names marked static (via static_argnums / static_argnames)."""
        for dec in getattr(fn, "decorator_list", ()):
            st = self._jit_call_static(dec, fn)
            if st is not None:
                return st
        return None

    def _jit_call_static(self, expr: ast.AST, fn: Optional[ast.FunctionDef]
                         ) -> Optional[Set[str]]:
        if self.dotted(expr) in ("jax.jit", "jit"):
            return set()
        if not isinstance(expr, ast.Call):
            return None
        callee = self.dotted(expr.func)
        if callee in ("jax.jit", "jit"):
            return self._static_names(expr, fn)
        if callee in ("functools.partial", "partial") and expr.args:
            if self.dotted(expr.args[0]) in ("jax.jit", "jit"):
                return self._static_names(expr, fn)
        return None

    @staticmethod
    def _static_names(call: ast.Call, fn: Optional[ast.FunctionDef]
                      ) -> Set[str]:
        static: Set[str] = set()
        params = ([a.arg for a in fn.args.posonlyargs + fn.args.args]
                  if fn is not None else [])
        for kw in call.keywords:
            vals = (kw.value.elts if isinstance(kw.value, ast.Tuple)
                    else [kw.value])
            if kw.arg == "static_argnums":
                for v in vals:
                    if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                            and v.value < len(params):
                        static.add(params[v.value])
            elif kw.arg == "static_argnames":
                for v in vals:
                    if isinstance(v, ast.Constant) and isinstance(v.value, str):
                        static.add(v.value)
        return static

    @staticmethod
    def _jit_donates(call: ast.Call) -> bool:
        """True when a jit(...) call donates at least one argument. An
        explicitly EMPTY donate_argnums=() donates nothing (the repo uses
        it to DOCUMENT a non-donating kernel) and counts as False."""
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                if isinstance(kw.value, (ast.Tuple, ast.List)) \
                        and not kw.value.elts:
                    continue
                return True
        return False

    def _jit_call_donates(self, expr: ast.AST) -> Optional[bool]:
        """None when `expr` is not a jit wrapper expression; else whether
        that wrapper donates any argument."""
        if self.dotted(expr) in ("jax.jit", "jit"):
            return False                       # bare @jax.jit: no donation
        if not isinstance(expr, ast.Call):
            return None
        callee = self.dotted(expr.func)
        if callee in ("jax.jit", "jit"):
            return self._jit_donates(expr)
        if callee in ("functools.partial", "partial") and expr.args:
            if self.dotted(expr.args[0]) in ("jax.jit", "jit"):
                return self._jit_donates(expr)
        return None

    def _collect_donating_names(self, tree: ast.Module) -> Set[str]:
        """The subset of jitted names whose jit wrapper donates at least
        one argument — the fold-undonated-carry rule's pass list."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(self._jit_call_donates(dec)
                       for dec in node.decorator_list):
                    names.add(node.name)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and self._jit_call_donates(node.value):
                names.add(node.targets[0].id)
        return names

    def _collect_jitted_names(self, tree: ast.Module) -> Set[str]:
        """Names bound (at any nesting level) to jit-compiled callables:
        ``@jax.jit def f`` and ``f = jax.jit(g)`` — the device-value
        producers the host-sync rule recognizes."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self.jit_static_names(node) is not None:
                    names.add(node.name)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and self._jit_call_static(node.value, None) is not None:
                names.add(node.targets[0].id)
        return names

    @staticmethod
    def _module_level_names(tree: ast.Module) -> Set[str]:
        names: Set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) \
                    and isinstance(node.target, ast.Name):
                names.add(node.target.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                names.add(node.name)
        return names


def assigned_names(fn: ast.FunctionDef) -> Set[str]:
    """Names bound inside `fn` (params, assignments, loop targets, withitems)
    — NOT descending into nested function defs."""
    out: Set[str] = {a.arg for a in
                     fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs}
    out.update(a.arg for a in (fn.args.vararg, fn.args.kwarg) if a)

    def collect_target(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect_target(e)
        elif isinstance(t, ast.Starred):
            collect_target(t.value)

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.add(child.name)
                continue
            if isinstance(child, ast.Assign):
                for t in child.targets:
                    collect_target(t)
            elif isinstance(child, (ast.AugAssign, ast.AnnAssign)):
                collect_target(child.target)
            elif isinstance(child, (ast.For, ast.AsyncFor)):
                collect_target(child.target)
            elif isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    if item.optional_vars is not None:
                        collect_target(item.optional_vars)
            visit(child)

    visit(fn)
    return out


# --------------------------------------------------------------- discovery
def iter_sources(paths: Sequence[str], include_md: bool = True
                 ) -> Iterator[Tuple[str, str, int]]:
    """Yield (file_path, python_source, line_offset) units to lint.

    Directories walk recursively; ``.py`` files are one unit each at
    offset 0; ``.md`` files contribute one unit per ```python fence at
    the fence's line offset (so findings point into the real file)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith((".", "__pycache__")))
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py")
                             or (include_md and f.endswith(".md")))
        else:
            files.append(p)
    for f in files:
        if f.endswith(".md"):
            if not include_md:
                continue
            text = open(f, encoding="utf-8").read()
            for m in _FENCE.finditer(text):
                offset = text[:m.start(1)].count("\n")
                yield f, m.group(1), offset
        else:
            yield f, open(f, encoding="utf-8").read(), 0


# ---------------------------------------------------------------- baseline
def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "graftlint_baseline.txt")


def load_baseline(path: Optional[str] = None) -> List[BaselineEntry]:
    """Parse the allowlist: one ``key -- justification`` per line, ``#``
    comments. A missing file is an empty baseline (fresh checkouts lint
    hard)."""
    path = path or default_baseline_path()
    entries: List[BaselineEntry] = []
    if not os.path.exists(path):
        return entries
    for i, raw in enumerate(open(path, encoding="utf-8"), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        key, sep, why = line.partition(" -- ")
        if not sep or not why.strip():
            raise ValueError(
                f"{path}:{i}: baseline entries need a ' -- justification' "
                f"suffix (got {line!r})")
        if key.count("::") != 2:
            raise ValueError(
                f"{path}:{i}: baseline key must be path::rule::scope "
                f"(got {key!r})")
        entries.append(BaselineEntry(key.strip(), why.strip(), i))
    return entries


# -------------------------------------------------------------------- run
def collect_findings(paths: Sequence[str], rules: Sequence,
                     root: Optional[str] = None, include_md: bool = True
                     ) -> Tuple[Report, List[Finding]]:
    """Parse and lint `paths` with `rules`, returning the partial report
    (scanned files + parse errors) and the RAW findings, before any
    baseline split. Shared by run_paths and the flow runner
    (analysis/flow.py), which appends its audit findings to the raw list
    so one apply_baseline pass governs both."""
    root = os.path.abspath(root or os.getcwd())
    report = Report()
    raw: List[Finding] = []
    for file_path, source, offset in iter_sources(paths, include_md):
        rel = os.path.relpath(os.path.abspath(file_path), root)
        rel = rel.replace(os.sep, "/")
        if rel.startswith("../"):
            rel = file_path.replace(os.sep, "/")
        if rel not in report.scanned:
            report.scanned.append(rel)
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            report.errors.append(Finding(
                rel, offset + (e.lineno or 1), "parse-error",
                f"could not parse: {e.msg}", "fix the syntax error",
                "<module>"))
            continue
        if offset:
            ast.increment_lineno(tree, offset)
        ctx = ModuleContext(rel, tree)
        for rule in rules:
            raw.extend(rule.check(ctx))
    return report, raw


def run_paths(paths: Sequence[str], rules: Optional[Sequence] = None,
              baseline: Optional[Sequence[BaselineEntry]] = None,
              root: Optional[str] = None, include_md: bool = True) -> Report:
    """Lint `paths` with `rules` (default: all), splitting findings into
    surviving vs baseline-suppressed; baseline entries pointing at scanned
    files that no longer fire are reported stale (the allowlist must
    shrink with the code it excuses)."""
    from avenir_tpu.analysis.rules import ALL_RULES

    active = list(rules) if rules is not None else [r() for r in ALL_RULES]
    report, raw = collect_findings(paths, active, root, include_md)
    apply_baseline(report, raw, baseline, {r.rule_id for r in active})
    return report


def apply_baseline(report: Report, raw: Sequence[Finding],
                   baseline: Optional[Sequence[BaselineEntry]],
                   active_ids: Set[str]) -> Report:
    """Split `raw` into surviving vs baseline-suppressed findings on
    `report` (which already carries `scanned` and any errors), and flag
    stale allowlist entries. Shared by the AST runner above and the IR
    runner (analysis/ir.py) so both honor one baseline contract:
    an entry is stale only when its file was scanned AND its rule was
    active this run — a --rules subset must not condemn the rest of the
    allowlist."""
    entries = list(baseline) if baseline is not None else []
    by_key: Dict[str, BaselineEntry] = {}
    for e in entries:
        by_key.setdefault(e.key, e)
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        hit = by_key.get(f.key)
        if hit is not None:
            hit.used += 1
            report.suppressed.append(f)
        else:
            report.findings.append(f)
    scanned = set(report.scanned)
    report.stale = [e for e in entries
                    if not e.used
                    and e.key.split("::")[0] in scanned
                    and e.key.split("::")[1] in active_ids]
    return report
