"""graftlint --keys: the cache-key completeness tier.

Every cache this repo grew — the sidecar directory (PR 16), the
incremental checkpoint (PR 9), the warm miner source and exec-coalesce
map (PR 12), the autotune profile (PR 14), the shard ledger's committed
states (PR 13) — stands on one claim: *the key is a proof of the
value*. Two reads agreeing on the key must see byte-identical served
bytes, and any input that can change the served bytes must change the
key. Each cache grew its own hand-maintained digest recipe, and a
recipe that silently under-covers its view is the worst bug class the
repo can have: not a crash, not a wrong answer once, but a cache that
*keeps serving yesterday's bytes* after the view moved. This tier makes
the claim mechanical, in the established graftlint shape:

**Static rules** (AST) over the cache surface (``native/sidecar.py``,
``core/incremental.py``, ``server/jobserver.py``, ``tune/store.py``,
``native/ingest.py``, ``dist/ledger.py``, ``core/keys.py``):

- ``keys-undigested-input`` — a function that builds a cache key AND
  consults a cache reads a config literal that the key function never
  folds (and that is not declared view-neutral): the classic
  under-keyed cache. The key function's ``key-covered:`` docstring
  declaration and a transitive ``conf_digest`` call (which folds every
  non-neutral property) are the sanctioned escape hatches.
- ``keys-overdigested-neutral`` — a key/digest function folds a
  config key declared view-neutral (:data:`~avenir_tpu.core.keys.
  VIEW_NEUTRAL_KEYS`): every state-dir move or tuner toggle then
  spuriously invalidates the cache.
- ``keys-mtime-validity`` — cache validity derived from an
  ``os.path.getmtime`` / ``st_mtime`` stat instead of content
  fingerprints, in a scope with no content re-proof machinery
  (``verified_prefix`` / ``block_hash`` / ``_content_coverage``) in
  reach: a touch or copy-back then serves stale bytes or torches a
  valid cache. Age arithmetic (``now - mtime``) is fine.
- ``keys-unversioned-format`` — a persisted cache manifest/blob
  written with no ``format_version`` field: the NEXT layout change
  ships a reader that silently misparses yesterday's caches.
- ``keys-digest-drift`` — two key functions in one module fold the
  same input dimension under different normalizations (one abspath,
  one bare; one file-bytes, one path string): the same view lands on
  different keys depending on which recipe a caller reached. The
  ``normalization:`` docstring declaration is the escape hatch.

**Mechanical auditor** (:func:`audit_keys`): every key function is
annotated ``key_site("<name>")`` (core/keys.py, beside the view-neutral
registry) and the :data:`KEY_SITES` registry drives a seed/perturb/
serve probe per site. Each registered input dimension is perturbed ONE
AT A TIME against a freshly seeded root holding a warm cache:

- a **view-affecting** perturbation MUST change the key, and the bytes
  served over the warm cache must equal a cold recompute of the
  perturbed view — same key + different cold bytes is a
  ``keys-stale-serve`` finding, the tier's pseudo-rule, applied AFTER
  the baseline pass and therefore NEVER allowlistable;
- a **view-neutral** perturbation MUST keep the key and warm-hit
  byte-identically (a key change is a spurious cold miss — the dual
  failure, also a finding);
- a **format** perturbation stamps a foreign ``format_version`` into
  the cache's persisted manifest and asserts the served bytes equal a
  cold recompute: the refuse-to-serve-and-go-cold proof.

A regex cross-check (:func:`check_key_registry`) greps the surface for
``key_site("<name>")`` annotations and fails loudly when code and
registry disagree in either direction, exactly like the commit-point
and sched-point registries of the proto and race tiers.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import shutil
import tempfile
from dataclasses import dataclass
from typing import (Callable, Dict, Iterator, List, Optional, Sequence,
                    Set, Tuple)

from avenir_tpu.analysis.engine import (BaselineEntry, Finding,
                                        ModuleContext, Report,
                                        apply_baseline, collect_findings)
from avenir_tpu.analysis.proto import (_calls, _functions, _pkg_root,
                                       _terminal_name)
from avenir_tpu.core.keys import is_view_neutral

#: the audit pseudo-rule: perturbation verdicts surface under this id
#: and are NEVER allowlisted (the runner applies them AFTER the
#: baseline pass, so no allowlist entry can suppress one)
KEYS_AUDIT_RULE = "keys-stale-serve"


class KeysAuditError(RuntimeError):
    """The key-perturbation auditor could not run (driver failure,
    registry mismatch, missing native machinery) — an environment or
    registry error, never a lint finding."""


def default_keys_paths(root: str) -> List[str]:
    """The cache surface this tier lints: every module that builds a
    cache key or persists a keyed artifact, plus the canonical digest
    home itself."""
    names = [os.path.join("avenir_tpu", "native", "sidecar.py"),
             os.path.join("avenir_tpu", "native", "ingest.py"),
             os.path.join("avenir_tpu", "core", "incremental.py"),
             os.path.join("avenir_tpu", "core", "keys.py"),
             os.path.join("avenir_tpu", "server", "jobserver.py"),
             os.path.join("avenir_tpu", "tune", "store.py"),
             os.path.join("avenir_tpu", "dist", "ledger.py")]
    return [p for p in (os.path.join(root, n) for n in names)
            if os.path.exists(p)]


# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------
#: the JobConfig getter surface: a literal first argument to one of
#: these on a config-shaped receiver is a config-key read
_CFG_GETTERS = {"get", "get_int", "get_float", "get_bool"}
_CFG_RECV_TOKENS = ("cfg", "conf", "config")
#: a function is a KEY FUNCTION when its name carries key/digest/
#: fingerprint vocabulary or it carries a key_site() annotation
_KEYFN_NAME_RE = re.compile(r"(^|_)(key|keys|digest|fingerprint)($|_|s$)")
#: the content-proof machinery whose reachability exempts an mtime read
_CONTENT_PROOF_CALLS = {"verified_prefix", "block_hash",
                        "block_fingerprint", "_content_coverage",
                        "_verified_blocks", "schema_digest",
                        "note_block", "note_fingerprint"}
_CONTENT_PROOF_METHOD_RE = re.compile(
    r"(coverage|verified|content|hash|fingerprint)")
_MTIME_ATTRS = {"st_mtime", "st_mtime_ns"}
#: persistence sinks whose dict payloads must carry a format_version
_DUMP_TERMINALS = {"publish_json", "dump"}
#: receiver-name evidence that a .get()/.pop()/subscript is a CACHE
#: consultation (vs an ordinary dict read)
_CACHE_RECV_TOKENS = ("cache", "store", "warm", "memo", "entries",
                      "profiles", "sources", "seen", "pinned", "table")
_CACHE_CONSULT_METHODS = {"get", "pop", "setdefault", "lookup"}
#: normalization wrappers rule 5 compares — a call OUTSIDE this
#: vocabulary is opaque delegation and records nothing
_NORM_WRAPPERS = {"abspath", "realpath", "basename", "dirname",
                  "normpath", "open", "read", "dumps", "sorted", "str",
                  "repr", "int", "float", "round", "lower", "encode",
                  "sha1", "sha256", "md5", "blake2b"}
#: the input dimensions rule 5 tracks, by identifier token
_DIM_TOKENS = {"schema": "schema", "delim": "delim", "corpus": "corpus",
               "input": "corpus", "inputs": "corpus", "skip": "skip",
               "block": "block", "marker": "marker"}


def _docstring(fn: ast.AST) -> str:
    try:
        return ast.get_docstring(fn) or ""
    except TypeError:
        return ""


def _covered_decl(fn: ast.AST) -> Tuple[Set[str], bool]:
    """The ``key-covered:`` docstring declaration of a key function:
    (declared config keys, covers-all flag)."""
    doc = _docstring(fn)
    m = re.search(r"key-covered:\s*(.{0,400})", doc, re.S)
    if not m:
        return set(), False
    blob = m.group(1)
    if re.match(r"\s*all\b", blob):
        return set(), True
    keys = set(re.findall(r"[a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+", blob))
    return keys, False


def _ident_soup(node: ast.AST) -> str:
    out: List[str] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.append(sub.attr)
    return " ".join(out).lower()


def _is_cfg_receiver(node: ast.AST) -> bool:
    soup = _ident_soup(node)
    return any(tok in soup for tok in _CFG_RECV_TOKENS)


def _literal_reads(ctx: ModuleContext, fn: ast.AST) -> Dict[str, int]:
    """Direct config-literal reads in `fn`: literal -> line. Covers the
    getter surface plus the ``field_delim_regex`` property (which reads
    the two delimiter keys)."""
    out: Dict[str, int] = {}
    for call in _calls(fn):
        f = call.func
        if not isinstance(f, ast.Attribute) \
                or f.attr not in _CFG_GETTERS \
                or not _is_cfg_receiver(f.value):
            continue
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            out.setdefault(call.args[0].value, call.args[0].lineno)
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) \
                and node.attr == "field_delim_regex" \
                and _is_cfg_receiver(node.value):
            out.setdefault("field.delim.regex", node.lineno)
            out.setdefault("field.delim.in", node.lineno)
    return out


def _local_fn_table(ctx: ModuleContext) -> Dict[str, List[ast.AST]]:
    table: Dict[str, List[ast.AST]] = {}
    for fn in _functions(ctx):
        table.setdefault(fn.name, []).append(fn)
    return table


def _callee_names(ctx: ModuleContext, fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for call in _calls(fn):
        name = _terminal_name(ctx, call)
        if name:
            out.add(name)
    return out


def _transitive_reads(ctx: ModuleContext, fn: ast.AST,
                      table: Dict[str, List[ast.AST]],
                      seen: Optional[Set[int]] = None,
                      depth: int = 4) -> Dict[str, int]:
    """Config-literal reads of `fn` plus its module-local callees, a
    few hops deep (matching the flow tier's interprocedural reach)."""
    seen = set() if seen is None else seen
    if id(fn) in seen or depth <= 0:
        return {}
    seen.add(id(fn))
    out = dict(_literal_reads(ctx, fn))
    for name in _callee_names(ctx, fn):
        for callee in table.get(name, ()):
            for lit, line in _transitive_reads(
                    ctx, callee, table, seen, depth - 1).items():
                out.setdefault(lit, line)
    return out


def _transitive_calls(ctx: ModuleContext, fn: ast.AST,
                      table: Dict[str, List[ast.AST]],
                      needles: Set[str],
                      seen: Optional[Set[int]] = None,
                      depth: int = 4) -> bool:
    """Whether `fn` (or a module-local callee, a few hops deep) calls
    any function named in `needles`."""
    seen = set() if seen is None else seen
    if id(fn) in seen or depth <= 0:
        return False
    seen.add(id(fn))
    names = _callee_names(ctx, fn)
    if names & needles:
        return True
    return any(_transitive_calls(ctx, callee, table, needles, seen,
                                 depth - 1)
               for name in names for callee in table.get(name, ()))


def _is_key_fn(ctx: ModuleContext, fn: ast.AST) -> bool:
    if _KEYFN_NAME_RE.search(fn.name):
        return True
    return any(_terminal_name(ctx, c) == "key_site" for c in _calls(fn))


def _enclosing_class(ctx: ModuleContext, node: ast.AST
                     ) -> Optional[ast.ClassDef]:
    cur = node
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = ctx.parent(cur)
    return None


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------
class KeysRule:
    rule_id: str = ""
    description: str = ""
    hint: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(ctx.path, getattr(node, "lineno", 1),
                       self.rule_id, message, hint or self.hint,
                       ctx.scope_of(node))


class UndigestedInputRule(KeysRule):
    """A function that builds a cache key (calls a module-local key
    function) AND consults a cache reads a config literal the key
    function never folds: the served bytes depend on an input the key
    cannot see — the under-keyed cache, the exact shape the live
    stale-serve probe exists to catch. Exempt when the key function
    (transitively) calls ``conf_digest`` (every non-neutral property
    folds in), when the literal is declared view-neutral, or when the
    key function's ``key-covered:`` docstring names the literal as
    deliberately excluded (with the reason)."""

    rule_id = "keys-undigested-input"
    description = "cached path reads a cfg key its cache key omits"
    hint = ("fold the key into the digest (or route through "
            "core.keys.conf_digest), or declare the deliberate "
            "exclusion in the key function's `key-covered:` docstring "
            "line — an input the key cannot see is a stale serve "
            "waiting for the first config change")

    def _consults_cache(self, ctx: ModuleContext, fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare) and any(
                    isinstance(op, (ast.In, ast.NotIn))
                    for op in node.ops):
                return True
            if isinstance(node, ast.Subscript):
                soup = _ident_soup(node.value)
                if any(t in soup for t in _CACHE_RECV_TOKENS):
                    return True
        for call in _calls(fn):
            f = call.func
            if isinstance(f, ast.Attribute) \
                    and f.attr in _CACHE_CONSULT_METHODS \
                    and not _is_cfg_receiver(f.value):
                soup = _ident_soup(f.value)
                if any(t in soup for t in _CACHE_RECV_TOKENS):
                    return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        table = _local_fn_table(ctx)
        key_fns = {fn.name: fn for fn in _functions(ctx)
                   if _is_key_fn(ctx, fn)}
        for fn in _functions(ctx):
            if _is_key_fn(ctx, fn):
                continue
            called = [key_fns[n] for n in _callee_names(ctx, fn)
                      if n in key_fns]
            if not called or not self._consults_cache(ctx, fn):
                continue
            covered: Set[str] = set()
            covers_all = False
            for kfn in called:
                decl, all_flag = _covered_decl(kfn)
                covered |= decl
                covered |= set(_transitive_reads(ctx, kfn, table))
                if all_flag or _transitive_calls(
                        ctx, kfn, table, {"conf_digest"}):
                    covers_all = True
            own_decl, own_all = _covered_decl(fn)
            covered |= own_decl
            if covers_all or own_all:
                continue
            reads = _transitive_reads(ctx, fn, table)
            for lit in sorted(reads):
                if lit in covered or is_view_neutral(lit):
                    continue
                yield Finding(
                    ctx.path, reads[lit], self.rule_id,
                    f"`{fn.name}` consults a cache keyed by "
                    f"`{', '.join(k.name for k in called)}` but reads "
                    f"config key `{lit}` that the key never folds — "
                    f"a change to it serves stale bytes",
                    self.hint, ctx.scope_of(fn.body[0]))


class OverdigestedNeutralRule(KeysRule):
    """A key/digest function folds a config key declared view-neutral
    in :data:`~avenir_tpu.core.keys.VIEW_NEUTRAL_KEYS`: the key then
    changes when a state directory moves or the tuner toggles
    recording, and every such non-view change spuriously invalidates
    the cache (the dual of the stale serve — cold cost, not wrong
    bytes, but it defeats the cache exactly when operators touch
    deployment knobs). A neutral literal inside a comparison or an
    ``if`` test is the skip GUARD (the sanctioned shape) and is
    exempt."""

    rule_id = "keys-overdigested-neutral"
    description = "view-neutral cfg key folded into a cache digest"
    hint = ("skip the key (guard with core.keys.is_view_neutral, the "
            "conf_digest shape) — view-neutral keys name WHERE state "
            "lives, not WHAT bytes are served; folding one makes every "
            "deployment change a spurious cold rescan")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _functions(ctx):
            if not _is_key_fn(ctx, fn):
                continue
            guarded: Set[int] = set()
            for node in ast.walk(fn):
                zone = None
                if isinstance(node, ast.Compare):
                    zone = node
                elif isinstance(node, (ast.If, ast.While)):
                    zone = node.test
                elif isinstance(node, ast.Call) and _terminal_name(
                        ctx, node) == "is_view_neutral":
                    zone = node
                if zone is not None:
                    guarded.update(id(s) for s in ast.walk(zone))
            for node in ast.walk(fn):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and id(node) not in guarded \
                        and is_view_neutral(node.value):
                    yield self.finding(
                        ctx, node,
                        f"key function `{fn.name}` folds view-neutral "
                        f"config key `{node.value}` into the digest — "
                        f"every state-dir/tuner change now spuriously "
                        f"invalidates the cache")


class MtimeValidityRule(KeysRule):
    """Cache validity derived from an mtime stat instead of content
    fingerprints. A touch, copy-back or clock skew then either torches
    a perfectly valid cache (spurious cold rescan) or — with a
    coarse-granularity filesystem — serves stale bytes for an in-place
    edit inside the mtime tick. The repo's standing contract (PR 8/16)
    is content re-proof: a scope is exempt when it (transitively)
    reaches the content machinery, when its class carries a
    content-proof method, or when the stat only feeds age arithmetic
    (durations are fine — they gate GC, not validity)."""

    rule_id = "keys-mtime-validity"
    description = "cache validity from mtime instead of content proof"
    hint = ("re-prove content (core.incremental.verified_prefix / "
            "block_hash) instead of trusting the stat — mtime is a "
            "hint, never a proof; see the `mtime-ok:` docstring "
            "declaration for deliberate non-cache uses")

    def _mtime_uses(self, ctx: ModuleContext,
                    fn: ast.AST) -> List[ast.AST]:
        out: List[ast.AST] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) \
                    and node.attr in _MTIME_ATTRS:
                out.append(node)
            elif isinstance(node, ast.Call):
                dotted = ctx.dotted(node.func) or ""
                if dotted.endswith("getmtime"):
                    out.append(node)
        return out

    def _age_only(self, ctx: ModuleContext, fn: ast.AST,
                  use: ast.AST) -> bool:
        cur = use
        while cur is not None and cur is not fn:
            if isinstance(cur, ast.BinOp) \
                    and isinstance(cur.op, ast.Sub):
                return True
            cur = ctx.parent(cur)
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        table = _local_fn_table(ctx)
        for fn in _functions(ctx):
            uses = self._mtime_uses(ctx, fn)
            if not uses:
                continue
            if "mtime-ok:" in _docstring(fn):
                continue
            if fn.name in _CONTENT_PROOF_CALLS or _transitive_calls(
                    ctx, fn, table, _CONTENT_PROOF_CALLS):
                continue
            cls = _enclosing_class(ctx, fn)
            if cls is not None and any(
                    isinstance(m, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))
                    and _CONTENT_PROOF_METHOD_RE.search(m.name)
                    for m in cls.body):
                continue
            for use in uses:
                if self._age_only(ctx, fn, use):
                    continue
                yield self.finding(
                    ctx, use,
                    f"`{fn.name}` derives validity from an mtime stat "
                    f"with no content re-proof in reach — a touch or "
                    f"copy-back serves stale bytes or torches a valid "
                    f"cache")


class UnversionedFormatRule(KeysRule):
    """A persisted cache manifest/record written with no
    ``format_version`` field: the next layout change ships a reader
    that silently misparses (or silently serves) yesterday's caches —
    the standing contract is stamp on write, refuse-and-go-cold on a
    PRESENT mismatched stamp, serve on a missing one (pre-versioning
    caches survive the upgrade). Flags dict literals flowing into the
    persistence sinks (``publish_json`` / ``json.dump``) and dict
    literals built by manifest-builder functions. Advisory non-cache
    records opt out with a ``not a cache`` docstring note."""

    rule_id = "keys-unversioned-format"
    description = "persisted cache manifest has no format_version"
    hint = ("stamp `format_version` at every writer and refuse-and-go-"
            "cold on a present mismatch — an unversioned layout makes "
            "the NEXT format change a silent misparse of every cache "
            "already on disk")

    _BUILDER_RE = re.compile(r"(manifest|fresh|meta)")

    def _dict_keys(self, d: ast.Dict) -> Set[str]:
        return {k.value for k in d.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, str)}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _functions(ctx):
            if "not a cache" in _docstring(fn):
                continue
            assigned: Dict[str, ast.Dict] = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Dict):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            assigned[tgt.id] = node.value
            flagged: Set[int] = set()
            for call in _calls(fn):
                term = _terminal_name(ctx, call)
                if term not in _DUMP_TERMINALS or not call.args:
                    continue
                obj = call.args[0]
                d = obj if isinstance(obj, ast.Dict) else (
                    assigned.get(obj.id)
                    if isinstance(obj, ast.Name) else None)
                if d is None:
                    continue
                keys = self._dict_keys(d)
                if not keys or "format_version" in keys:
                    continue
                flagged.add(id(d))
                yield self.finding(
                    ctx, d,
                    f"`{fn.name}` persists a manifest with keys "
                    f"{sorted(keys)[:5]} and no `format_version` "
                    f"stamp")
            if self._BUILDER_RE.search(fn.name):
                for node in ast.walk(fn):
                    if isinstance(node, ast.Dict) \
                            and id(node) not in flagged:
                        keys = self._dict_keys(node)
                        if len(keys) >= 3 \
                                and "format_version" not in keys:
                            yield self.finding(
                                ctx, node,
                                f"manifest builder `{fn.name}` emits a "
                                f"record with keys {sorted(keys)[:5]} "
                                f"and no `format_version` stamp")


class DigestDriftRule(KeysRule):
    """Two key functions in one module fold the same input dimension
    under DIFFERENT normalizations (one ``abspath``, one bare string;
    one reads file bytes, one hashes the path): the same view lands on
    different keys depending on which recipe a caller reached — the
    drift that scattering digest helpers across modules breeds, and
    the reason the recipes were unified into core/keys.py. A function
    whose docstring carries a ``normalization:`` declaration documents
    its choice and is exempt (the declaration is the reviewable
    contract)."""

    rule_id = "keys-digest-drift"
    description = "same dimension, different normalization, one module"
    hint = ("route both through one core.keys recipe, or declare the "
            "normalization in each docstring (`normalization: "
            "abspath`) so the difference is a reviewed contract, not "
            "drift")

    def _folds(self, ctx: ModuleContext, fn: ast.AST
               ) -> Dict[str, Set[str]]:
        out: Dict[str, Set[str]] = {}
        wrapped: Set[int] = set()
        for call in _calls(fn):
            term = _terminal_name(ctx, call) or ""
            in_vocab = term in _NORM_WRAPPERS
            for arg in call.args:
                for sub in ast.walk(arg):
                    wrapped.add(id(sub))
                if not in_vocab:
                    continue
                soup = _ident_soup(arg)
                for tok in soup.split():
                    dim = _DIM_TOKENS.get(tok)
                    if dim:
                        out.setdefault(dim, set()).add(term)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            elts = node.value.elts \
                if isinstance(node.value, ast.Tuple) else [node.value]
            for e in elts:
                if isinstance(e, ast.Call):
                    continue
                for sub in ast.walk(e):
                    if id(sub) in wrapped:
                        break
                else:
                    for tok in _ident_soup(e).split():
                        dim = _DIM_TOKENS.get(tok)
                        if dim:
                            out.setdefault(dim, set()).add("bare")
        return out

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        fns = [fn for fn in _functions(ctx) if _is_key_fn(ctx, fn)]
        folded = [(fn, self._folds(ctx, fn)) for fn in fns]
        for i, (fa, da) in enumerate(folded):
            for fb, db in folded[i + 1:]:
                if "normalization:" in _docstring(fa) \
                        or "normalization:" in _docstring(fb):
                    continue
                for dim in sorted(set(da) & set(db)):
                    if da[dim] and db[dim] and not (da[dim] & db[dim]):
                        yield self.finding(
                            ctx, fb,
                            f"`{fa.name}` and `{fb.name}` both fold "
                            f"dimension `{dim}` but normalize "
                            f"differently ({sorted(da[dim])} vs "
                            f"{sorted(db[dim])}) — the same view "
                            f"lands on different keys")


ALL_KEYS_RULES = [UndigestedInputRule, OverdigestedNeutralRule,
                  MtimeValidityRule, UnversionedFormatRule,
                  DigestDriftRule]


def keys_rule_ids() -> List[str]:
    return [r.rule_id for r in ALL_KEYS_RULES] + [KEYS_AUDIT_RULE]


# --------------------------------------------------------------------------
# registry cross-check
# --------------------------------------------------------------------------
_KEY_REF_RE = re.compile(r'key_site\(\s*"([a-z_.]+)"')


def key_annotations(root: Optional[str] = None
                    ) -> Dict[str, Tuple[str, int]]:
    """Every key_site name annotated on the cache surface, mapped to
    the (repo-relative path, line) of its first call site. The
    definition of ``key_site`` itself takes a bare parameter, so only
    real annotations (string-literal calls) match."""
    root = root or _pkg_root()
    refs: Dict[str, Tuple[str, int]] = {}
    for path in default_keys_paths(root):
        try:
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        for i, line in enumerate(text.splitlines(), 1):
            for m in _KEY_REF_RE.finditer(line):
                refs.setdefault(m.group(1), (rel, i))
    return refs


def check_key_registry(root: Optional[str] = None
                       ) -> Dict[str, Tuple[str, int]]:
    """Fail loudly when the key_site annotations and the KEY_SITES
    registry disagree: an annotated-but-unregistered key function is a
    cache the auditor never perturbs (an unproven key), a registered-
    but-unannotated site means the registry describes a key function
    that no longer exists. Returns the annotation locations (the audit
    rows' path/line source)."""
    refs = key_annotations(root)
    names = {site.name for site in KEY_SITES}
    unregistered = sorted(set(refs) - names)
    unannotated = sorted(names - set(refs))
    problems = []
    if unregistered:
        problems.append(
            f"key_site annotations in code but in no KEY_SITES entry "
            f"(caches whose key is never perturb-proven): "
            f"{unregistered}")
    if unannotated:
        problems.append(
            f"registered in KEY_SITES but never annotated in code "
            f"(dangling registry entries): {unannotated}")
    if problems:
        raise KeysAuditError(
            "key-site registry mismatch: " + "; ".join(problems))
    return refs


# --------------------------------------------------------------------------
# the perturbation auditor
# --------------------------------------------------------------------------
@dataclass(frozen=True)
class KeyPerturb:
    """One registered input dimension of a key site and how to move
    it: ``kind`` is ``affecting`` (must change the key; warm serve
    must equal a cold recompute), ``neutral`` (must keep the key;
    must warm-hit byte-identically) or ``format`` (a foreign
    format_version stamped into the persisted manifest; the serve
    must refuse and equal a cold recompute)."""

    name: str
    kind: str
    apply: Callable[[str], None]


@dataclass(frozen=True)
class KeySite:
    """One registered cache-key surface: ``seed`` populates a fresh
    root, ``key`` evaluates the real key function over the root's
    current view, ``serve`` produces the cache's served bytes (first
    call in a fresh root is the cold fill; later calls may warm-hit),
    and ``perturbs`` enumerates every registered input dimension.
    ``warm_proof``, when given, re-serves and returns True only if the
    serve was a warm hit — the spurious-miss probe for neutral
    perturbations."""

    name: str
    path: str
    seed: Callable[[str], None]
    key: Callable[[str], object]
    serve: Callable[[str], object]
    perturbs: Tuple[KeyPerturb, ...] = ()
    warm_proof: Optional[Callable[[str], bool]] = None


def _canon(value) -> str:
    return json.dumps(value, sort_keys=True, default=repr)


# ---------------------------------------------------------- driver infra
_DELIM = ","
_BLOCK = 2048


def _p(root: str, *names: str) -> str:
    return os.path.join(root, *names)


def _write(path: str, text: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(text)


def _file_sha(path: str) -> str:
    with open(path, "rb") as fh:
        return hashlib.sha1(fh.read()).hexdigest()


def _tree_sha(path: str) -> str:
    """Content digest of a job artifact that may be one file or a
    directory of them (the miner emits a directory)."""
    if not os.path.isdir(path):
        return _file_sha(path)
    h = hashlib.sha1()
    for dirpath, dirnames, filenames in sorted(os.walk(path)):
        dirnames.sort()
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            h.update(os.path.relpath(full, path).encode())
            with open(full, "rb") as fh:
                h.update(fh.read())
    return h.hexdigest()


def _conf(root: str) -> Dict[str, str]:
    with open(_p(root, "conf.json"), encoding="utf-8") as fh:
        return json.load(fh)


def _set_conf(root: str, key: str, value: str) -> None:
    conf = _conf(root)
    conf[key] = value
    _write(_p(root, "conf.json"), json.dumps(conf, indent=1))


def _meta(root: str) -> Dict[str, str]:
    with open(_p(root, "meta.json"), encoding="utf-8") as fh:
        return json.load(fh)


def _set_meta(root: str, key: str, value: str) -> None:
    meta = _meta(root)
    meta[key] = value
    _write(_p(root, "meta.json"), json.dumps(meta, indent=1))


def _corpus_path(root: str) -> str:
    return _p(root, _meta(root).get("corpus", "corpus.csv"))


def _churn_seed(root: str, conf: Dict[str, str],
                schema: bool = False) -> None:
    from avenir_tpu.data.generators import churn_schema, generate_churn

    _write(_p(root, "corpus.csv"),
           generate_churn(120, seed=11, as_csv=True))
    _write(_p(root, "meta.json"), json.dumps({"corpus": "corpus.csv"}))
    _write(_p(root, "conf.json"), json.dumps(conf, indent=1))
    if schema:
        churn_schema().save(_p(root, "schema.json"))


def _edit_corpus_row(root: str) -> None:
    """Perturb one content dimension: bump the first row's integer
    field in place (same schema vocabulary, different bytes from
    block 0 on)."""
    path = _corpus_path(root)
    with open(path, encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    fields = lines[0].split(_DELIM)
    fields[-2] = str(int(fields[-2]) + 1)
    lines[0] = _DELIM.join(fields)
    _write(path, "\n".join(lines) + "\n")


def _append_corpus_rows(root: str, rows: List[str]) -> None:
    with open(_corpus_path(root), "a", encoding="utf-8") as fh:
        fh.write("\n".join(rows) + "\n")


def _touch_corpus(root: str) -> None:
    os.utime(_corpus_path(root), (946684800, 946684800))


def _edit_schema(root: str) -> None:
    """Append an (unused) category to a non-discovered cardinality:
    parse-compatible, digest-visible."""
    path = _p(root, "schema.json")
    with open(path, encoding="utf-8") as fh:
        schema = json.load(fh)
    for f in schema["fields"]:
        if f.get("name") == "payment":
            f["cardinality"] = list(f["cardinality"]) + ["extracat"]
    _write(path, json.dumps(schema, indent=1))


def _stamp_manifest(path: str, version: int = 99) -> None:
    """Stamp a FOREIGN format_version into a persisted JSON manifest —
    the refuse-and-go-cold probe."""
    with open(path, encoding="utf-8") as fh:
        man = json.load(fh)
    man["format_version"] = version
    _write(path, json.dumps(man, indent=1))


def _memo_serve(root: str, fname: str, key, compute: Callable[[], object]):
    """A transparent micro-cache over the REAL key function under
    audit: serve from the entry when the key matches, recompute and
    store otherwise. A registered dimension the real key fails to fold
    leaves the key unchanged under perturbation, so the memo replays
    the pre-perturbation value — exactly the stale serve the auditor
    then catches against the cold recompute."""
    path = _p(root, fname)
    try:
        with open(path, encoding="utf-8") as fh:
            memo = json.load(fh)
    except (OSError, ValueError):
        memo = {"entries": {}, "hits": 0}
    kstr = _canon(key)
    if kstr in memo["entries"]:
        memo["hits"] += 1
        _write(path, json.dumps(memo))
        return memo["entries"][kstr]
    value = compute()
    memo["entries"][kstr] = value
    _write(path, json.dumps(memo))
    return value


def _memo_hits(root: str, fname: str) -> int:
    try:
        with open(_p(root, fname), encoding="utf-8") as fh:
            return int(json.load(fh).get("hits", 0))
    except (OSError, ValueError):
        return 0


def _memo_proof(fname: str, serve: Callable[[str], object]
                ) -> Callable[[str], bool]:
    def proof(root: str) -> bool:
        before = _memo_hits(root, fname)
        serve(root)
        return _memo_hits(root, fname) > before
    return proof


# ------------------------------------------------------- sidecar drivers
def _sc_opts(root: str) -> dict:
    return {"dir": _p(root, "sc"), "budget": 1 << 30}


def _sc_schema(root: str):
    from avenir_tpu.core.schema import FeatureSchema

    return FeatureSchema.from_file(_p(root, "schema.json"))


def _sc_tiling(feed) -> List[List[object]]:
    if feed is None:
        raise KeysAuditError(
            "sidecar machinery unavailable (native library not built)")
    return [[off, length, digest]
            for off, length, digest, _payload in feed]


def _sc_dataset_seed(root: str) -> None:
    _churn_seed(root, {"delim": _DELIM, "block": str(_BLOCK)},
                schema=True)


def _sc_dataset_dir(root: str) -> str:
    from avenir_tpu.native import sidecar as sc

    conf = _conf(root)
    return sc.dataset_dir(_sc_opts(root), _corpus_path(root),
                          _sc_schema(root), conf["delim"],
                          int(conf["block"]))


def _sc_dataset_key(root: str):
    return [os.path.basename(_sc_dataset_dir(root)),
            _file_sha(_corpus_path(root))]


def _sc_dataset_serve(root: str):
    from avenir_tpu.native import sidecar as sc

    conf = _conf(root)
    return _sc_tiling(sc.dataset_blocks(
        _sc_opts(root), _corpus_path(root), _sc_schema(root),
        conf["delim"], int(conf["block"])))


def _sc_dataset_stamp(root: str) -> None:
    _stamp_manifest(_p(_sc_dataset_dir(root), "MANIFEST.json"))


def _sc_warm_proof(serve: Callable[[str], object]
                   ) -> Callable[[str], bool]:
    def proof(root: str) -> bool:
        from avenir_tpu.native.sidecar import counters_snapshot

        before = counters_snapshot()["hit_blocks"]
        serve(root)
        return counters_snapshot()["hit_blocks"] > before
    return proof


def _sc_bytes_seed(root: str) -> None:
    _churn_seed(root, {"delim": _DELIM, "block": str(_BLOCK),
                       "skip": "2"})


def _sc_bytes_dir(root: str) -> str:
    from avenir_tpu.native import sidecar as sc

    conf = _conf(root)
    return sc.bytes_dir(_sc_opts(root), _corpus_path(root),
                        conf["delim"], int(conf["skip"]),
                        int(conf["block"]))


def _sc_bytes_key(root: str):
    return [os.path.basename(_sc_bytes_dir(root)),
            _file_sha(_corpus_path(root))]


def _sc_bytes_serve(root: str):
    from avenir_tpu.native import sidecar as sc

    conf = _conf(root)
    return _sc_tiling(sc.byte_blocks(
        _sc_opts(root), _corpus_path(root), conf["delim"],
        int(conf["skip"]), int(conf["block"])))


# ---------------------------------------------------- checkpoint driver
_MST_CONF = {"mst.model.states": "L,M,H",
             "mst.class.label.field.ord": "1",
             "mst.skip.field.count": "2",
             "mst.class.labels": "T,F"}


def _seq_rows(start: int, n: int) -> List[str]:
    states = ("L", "M", "H")
    rows = []
    for i in range(start, start + n):
        label = "T" if i % 3 else "F"
        toks = [states[(i + j) % 3] for j in range(6)]
        rows.append(f"c{i},{label}," + _DELIM.join(toks))
    return rows


def _ckpt_seed(root: str) -> None:
    _write(_p(root, "corpus.csv"), "\n".join(_seq_rows(0, 120)) + "\n")
    _write(_p(root, "meta.json"), json.dumps({"corpus": "corpus.csv"}))
    _write(_p(root, "conf.json"), json.dumps(dict(_MST_CONF), indent=1))


def _ckpt_key(root: str):
    from avenir_tpu.core.keys import conf_digest
    from avenir_tpu.server.jobserver import _scoped

    _canonical, _prefix, cfg = _scoped("markovStateTransitionModel",
                                       _conf(root))
    return [conf_digest(cfg), _file_sha(_corpus_path(root))]


def _ckpt_serve(root: str):
    from avenir_tpu.runner import run_incremental

    out = _p(root, "out.txt")
    run_incremental("markovStateTransitionModel", dict(_conf(root)),
                    [_corpus_path(root)], output=out,
                    state_dir=_p(root, "state"))
    return _file_sha(out)


def _ckpt_stamp(root: str) -> None:
    _stamp_manifest(_p(root, "state", "MANIFEST.json"))


# ------------------------------------------------- warm miner driver
_FIA_CONF = {"fia.support.threshold": "0.3",
             "fia.item.set.length": "2",
             "fia.skip.field.count": "2"}


def _fia_run_sha(root: str) -> str:
    from avenir_tpu.runner import run_job

    out = _p(root, "out.txt")
    # the miner emits a directory of artifacts; a previous run's files
    # must not leak into this view's digest
    shutil.rmtree(out, ignore_errors=True)
    run_job("frequentItemsApriori", dict(_conf(root)),
            [_corpus_path(root)], output=out)
    return _tree_sha(out)


def _miner_seed(root: str) -> None:
    _churn_seed(root, dict(_FIA_CONF))


def _miner_key(root: str):
    from avenir_tpu.server.jobserver import WarmStore, _scoped

    corpus = _corpus_path(root)
    canonical, _prefix, cfg = _scoped("frequentItemsApriori",
                                     _conf(root))
    return [list(WarmStore.source_key(canonical, [corpus], cfg)),
            _file_sha(corpus)]


def _miner_serve(root: str):
    return _memo_serve(root, "warmcache.json", _miner_key(root),
                       lambda: _fia_run_sha(root))


# ---------------------------------------------- exec / compat drivers
def _job_request(root: str):
    from avenir_tpu.server.jobserver import JobRequest

    return JobRequest(job="frequentItemsApriori", conf=_conf(root),
                      inputs=[_corpus_path(root)], output="")


def _exec_seed(root: str) -> None:
    _churn_seed(root, dict(_FIA_CONF))


def _exec_key(root: str):
    from avenir_tpu.server.jobserver import _exec_key as real_exec_key

    return [list(real_exec_key(_job_request(root))),
            _file_sha(_corpus_path(root))]


def _exec_serve(root: str):
    return _memo_serve(root, "execcache.json", _exec_key(root),
                       lambda: _fia_run_sha(root))


def _compat_seed(root: str) -> None:
    conf = dict(_FIA_CONF)
    conf["stream.block.size.mb"] = "0.002"
    _churn_seed(root, conf)


def _compat_key(root: str):
    from avenir_tpu.server.jobserver import compat_key

    key = compat_key(_job_request(root))
    if key is None:
        raise KeysAuditError("compat_key returned None for a "
                             "registered stream fold")
    return [list(key), _file_sha(_corpus_path(root))]


def _compat_scan(root: str):
    """The SharedScan view two equal compat keys ride: the byte tiling
    under the request's block size / delimiter / skip."""
    from avenir_tpu.native import sidecar as sc
    from avenir_tpu.server.jobserver import _scoped

    _canonical, _prefix, cfg = _scoped("frequentItemsApriori",
                                       _conf(root))
    block = int(cfg.get_float("stream.block.size.mb", 64.0) * (1 << 20))
    return _sc_tiling(sc.byte_blocks(
        _sc_opts(root), _corpus_path(root), cfg.field_delim_regex,
        cfg.get_int("skip.field.count", 1), block))


def _compat_serve(root: str):
    return _memo_serve(root, "compatcache.json", _compat_key(root),
                       lambda: _compat_scan(root))


# ------------------------------------------------- sidecar pin driver
def _pin_seed(root: str) -> None:
    conf = dict(_FIA_CONF)
    conf["stream.block.size.mb"] = "0.002"
    conf["stream.sidecar.dir"] = _p(root, "sc")
    _churn_seed(root, conf)


def _pin_keys(root: str):
    from avenir_tpu.server.jobserver import JobServer

    out = JobServer._sidecar_keys(None, [_job_request(root)])
    if not out:
        raise KeysAuditError("_sidecar_keys resolved no pinnable "
                             "sidecar for a streamed request")
    return [list(key) for key, _path, _dirpath in out]


def _pin_key(root: str):
    return [_pin_keys(root), _file_sha(_corpus_path(root))]


def _pin_serve(root: str):
    from avenir_tpu.native import sidecar as sc
    from avenir_tpu.server.jobserver import _scoped

    _canonical, _prefix, cfg = _scoped("frequentItemsApriori",
                                       _conf(root))
    block = int(cfg.get_float("stream.block.size.mb", 64.0) * (1 << 20))
    opts = sc.opts_from_cfg(cfg)
    return _sc_tiling(sc.byte_blocks(
        opts, _corpus_path(root), cfg.field_delim_regex,
        cfg.get_int("skip.field.count", 1), block))


# -------------------------------------------------- autotune driver
def _prof_store(root: str):
    from avenir_tpu.tune.store import ProfileStore

    return ProfileStore(_p(root, "tune"))


def _prof_digest(root: str) -> str:
    from avenir_tpu.core.keys import corpus_digest

    return corpus_digest([_corpus_path(root)])


def _prof_knobs(root: str) -> Dict[str, float]:
    """The 'learned' knob value, a deterministic function of the
    corpus content — so knobs recorded for one view are DISTINGUISHABLE
    from knobs the tuner would learn for another."""
    return {"stream.block.size.mb":
            float(2 + os.path.getsize(_corpus_path(root)) % 7)}


def _prof_seed(root: str) -> None:
    _write(_p(root, "corpus.csv"), "a,b,c\nd,e,f\n")
    _write(_p(root, "meta.json"),
           json.dumps({"corpus": "corpus.csv",
                       "job": "mutualInformation"}))
    _write(_p(root, "conf.json"), json.dumps({}, indent=1))
    _prof_store(root).set_knobs(
        "mutualInformation", _prof_digest(root), _prof_knobs(root),
        ["seeded by graftlint --keys"])


def _prof_key(root: str):
    return [_meta(root)["job"], _prof_digest(root)]


def _prof_serve(root: str):
    store = _prof_store(root)
    job, digest = _meta(root)["job"], _prof_digest(root)
    prof = store.load(job, digest)
    if prof is None:
        # the real recovery for a missed/refused profile: the tuner
        # re-learns over the current view and re-records (set_knobs
        # overwrites a version-skewed file — the go-cold half)
        store.set_knobs(job, digest, _prof_knobs(root),
                        ["re-learned after refused load"])
        prof = store.load(job, digest)
    return None if prof is None else prof.get("knobs")


def _prof_move_corpus(root: str) -> None:
    os.rename(_p(root, "corpus.csv"), _p(root, "moved.csv"))
    _set_meta(root, "corpus", "moved.csv")


def _prof_stamp(root: str) -> None:
    store = _prof_store(root)
    _stamp_manifest(store.path(_meta(root)["job"], _prof_digest(root)))


# -------------------------------------------- encoded cache driver
_ENC_CACHES: Dict[str, object] = {}
_ENC_BUILDS: Dict[str, int] = {}


def _enc_reset() -> None:
    for cache in _ENC_CACHES.values():
        try:
            cache.abort()
        except Exception:
            pass
    _ENC_CACHES.clear()
    _ENC_BUILDS.clear()


def _enc_seed(root: str) -> None:
    _churn_seed(root, {})


def _enc_key(root: str):
    return [_file_sha(_corpus_path(root))]


def _enc_blocks(path: str) -> Iterator[Tuple[int, bytes]]:
    with open(path, "rb") as fh:
        data = fh.read()
    off = 0
    while off < len(data):
        end = data.find(b"\n", min(off + _BLOCK, len(data)) - 1)
        end = len(data) if end < 0 else end + 1
        yield off, data[off:end]
        off = end


def _enc_serve(root: str):
    import numpy as np

    from avenir_tpu.native.ingest import EncodedBlockCache

    corpus = _corpus_path(root)
    cache = _ENC_CACHES.get(root)
    if cache is None:
        cache = EncodedBlockCache([corpus], cache_dir=_p(root, "enc"),
                                  byte_budget=1 << 30)
        _ENC_CACHES[root] = cache
    if not cache.valid:
        _ENC_BUILDS[root] = _ENC_BUILDS.get(root, 0) + 1
        cache.begin()
        cache.set_source(0)
        for off, data in _enc_blocks(corpus):
            cache.note_block(off, data)
            rows = [r for r in data.split(b"\n") if r]
            counts = np.array([r.count(b",") + 1 for r in rows],
                              dtype=np.int32)
            codes = np.array([len(f) for r in rows
                              for f in r.split(b",")], dtype=np.int32)
            cache.add_block(counts, codes)
        if not cache.commit():
            raise KeysAuditError("encoded-block cache refused commit "
                                 "on an unchanged source")
    h = hashlib.sha1()
    for counts, codes in cache.blocks():
        h.update(counts.tobytes())
        h.update(codes.tobytes())
    return h.hexdigest()


def _enc_warm_proof(root: str) -> bool:
    before = _ENC_BUILDS.get(root, 0)
    _enc_serve(root)
    return _ENC_BUILDS.get(root, 0) == before


# ------------------------------------------------------ ledger driver
def _led_seed(root: str) -> None:
    _write(_p(root, "corpus.csv"), "r1,10,a\nr2,20,b\nr3,30,c\n")
    _write(_p(root, "meta.json"),
           json.dumps({"corpus": "corpus.csv", "worker": "0"}))
    _write(_p(root, "conf.json"), json.dumps({}, indent=1))
    _led_serve(root)


def _led_ns(root: str) -> str:
    return _file_sha(_corpus_path(root))[:8]


def _led_handle(root: str, name: str = "led"):
    from avenir_tpu.dist.ledger import BlockLedger

    return BlockLedger(_p(root, name)).level(_led_ns(root))


def _led_key(root: str):
    return [_led_ns(root), 1]


def _led_blob(root: str) -> bytes:
    with open(_corpus_path(root), "rb") as fh:
        return b"state:" + fh.read()


def _led_serve(root: str):
    # the documented version-skew recovery (ledger.load_state): a
    # states dir whose marker mismatches serves NOTHING and accepts no
    # commit the reader could trust — the driver starts a fresh ledger
    # root and recomputes there (the go-cold half of the contract)
    for name in ("led", "led.cold"):
        led = _led_handle(root, name)
        if 1 in led.committed():
            return hashlib.sha1(led.load_state(1)).hexdigest()
        blob = _led_blob(root)
        if led.commit(1, int(_meta(root).get("worker", "0")), blob):
            return hashlib.sha1(blob).hexdigest()
        if 1 in led.committed():    # lost to a racing winner: serve it
            return hashlib.sha1(led.load_state(1)).hexdigest()
    raise KeysAuditError(
        "ledger driver: commit refused in a fresh ledger root")


def _led_warm_proof(root: str) -> bool:
    return 1 in _led_handle(root).committed()


def _led_stamp(root: str) -> None:
    from avenir_tpu.dist.ledger import STATES_FORMAT

    _stamp_manifest(_p(root, "led", "ledger", _led_ns(root), "states",
                       STATES_FORMAT))


# -------------------------------------------------- score model driver
def _score_model_path(root: str) -> str:
    return _p(root, "mst_model.txt")


def _score_conf(root: str) -> Dict[str, str]:
    """The SCORING view of conf.json — the knobs model_tuple folds as
    kind dims (the same names the batch classifier reads)."""
    conf = _conf(root)
    return {"field.delim": ",",
            "class.labels": conf.get("class.labels", "T,F"),
            "log.odds.threshold": conf.get("log.odds.threshold", "0"),
            "skip.field.count": conf.get("skip.field.count", "2")}


def _score_train(root: str) -> None:
    from avenir_tpu.runner import run_job

    run_job("markovStateTransitionModel", dict(_MST_CONF),
            [_corpus_path(root)], output=_score_model_path(root))


def _score_seed(root: str) -> None:
    _write(_p(root, "corpus.csv"), "\n".join(_seq_rows(0, 120)) + "\n")
    _write(_p(root, "meta.json"), json.dumps({"corpus": "corpus.csv"}))
    _write(_p(root, "conf.json"),
           json.dumps({"class.labels": "T,F",
                       "log.odds.threshold": "0",
                       "skip.field.count": "2"}, indent=1))
    _score_train(root)


def _score_key(root: str):
    from avenir_tpu.server.score import model_cache_key

    return list(model_cache_key("markov", _score_model_path(root),
                                _score_conf(root)))


def _score_rows(root: str) -> List[str]:
    with open(_corpus_path(root), encoding="utf-8") as fh:
        return [ln.strip() for ln in fh if ln.strip()][:3]


def _score_serve(root: str):
    from avenir_tpu.models.artifact import ModelFormatSkew
    from avenir_tpu.server.score import score_once

    model, conf = _score_model_path(root), _score_conf(root)

    def compute():
        try:
            return [score_once("markov", model, row, conf)
                    for row in _score_rows(root)]
        except ModelFormatSkew:
            # the documented recovery for a version-skewed artifact:
            # REFUSE the load, go cold — retrain over the current
            # corpus (save restamps at this build's version) and score
            # the fresh artifact
            _score_train(root)
            return [score_once("markov", model, row, conf)
                    for row in _score_rows(root)]

    return _memo_serve(root, "scorecache.json", _score_key(root), compute)


def _score_retrain(root: str) -> None:
    # the seed walks L->M->H cyclically; these walk H->M->L, so the
    # transition mass actually moves and the artifact BYTES change
    # (an append that re-trains to the same matrix is not a retrain)
    rows = [f"x{i}," + ("T" if i % 2 else "F") + ","
            + _DELIM.join(("H", "M", "L")[(i + j) % 3]
                          for j in range(6))
            for i in range(30)]
    _append_corpus_rows(root, rows)
    _score_train(root)


def _score_touch_model(root: str) -> None:
    os.utime(_score_model_path(root), (946684800, 946684800))


def _score_stamp(root: str) -> None:
    from avenir_tpu.models.artifact import stamp_path

    _stamp_manifest(stamp_path(_score_model_path(root)))


# --------------------------------------------------------- the registry
def _perturb(name: str, kind: str,
             apply: Callable[[str], None]) -> KeyPerturb:
    return KeyPerturb(name=name, kind=kind, apply=apply)


def _set(key: str, value: str) -> Callable[[str], None]:
    return lambda root: _set_conf(root, key, value)


#: Every registered cache-key surface, one entry per annotated
#: ``key_site``. The perturbation lists are the REGISTERED input
#: dimensions: the auditor moves each one at a time and holds the key
#: to its contract. Deliberately excluded dimensions are documented at
#: the key function (``key-covered:`` lines), not here.
KEY_SITES: List[KeySite] = [
    # The sidecar dataset directory: parse view (delimiter, schema
    # content, block size) names the dir; content validity is the
    # manifest's per-block fingerprint re-proof. The budget knob and
    # an mtime touch are view-neutral.
    KeySite(
        name="sidecar.dataset",
        path="avenir_tpu/native/sidecar.py",
        seed=_sc_dataset_seed,
        key=_sc_dataset_key,
        serve=_sc_dataset_serve,
        perturbs=(
            _perturb("conf:block", "affecting", _set("block", "4096")),
            _perturb("schema:content", "affecting", _edit_schema),
            _perturb("corpus:content", "affecting", _edit_corpus_row),
            _perturb("corpus:mtime", "neutral", _touch_corpus),
            _perturb("manifest:format_version", "format",
                     _sc_dataset_stamp),
        ),
        warm_proof=_sc_warm_proof(_sc_dataset_serve)),
    # The sidecar bytes directory: skip count and delimiter shape the
    # parse view; the byte budget does not.
    KeySite(
        name="sidecar.bytes",
        path="avenir_tpu/native/sidecar.py",
        seed=_sc_bytes_seed,
        key=_sc_bytes_key,
        serve=_sc_bytes_serve,
        perturbs=(
            _perturb("conf:skip", "affecting", _set("skip", "1")),
            _perturb("conf:delim", "affecting", _set("delim", ";")),
            _perturb("corpus:content", "affecting", _edit_corpus_row),
            _perturb("corpus:mtime", "neutral", _touch_corpus),
        ),
        warm_proof=_sc_warm_proof(_sc_bytes_serve)),
    # The incremental checkpoint manifest: conf_digest (every
    # non-neutral property) + the corpus content the fingerprints
    # re-prove. The autotune control keys are the registered neutral
    # dimension — the reason VIEW_NEUTRAL_KEYS exists.
    KeySite(
        name="checkpoint.manifest",
        path="avenir_tpu/core/keys.py",
        seed=_ckpt_seed,
        key=_ckpt_key,
        serve=_ckpt_serve,
        perturbs=(
            _perturb("conf:mst.class.labels", "affecting",
                     _set("mst.class.labels", "F,T")),
            _perturb("corpus:append", "affecting",
                     lambda root: _append_corpus_rows(
                         root, _seq_rows(120, 30))),
            _perturb("conf:stream.autotune.dir", "neutral",
                     _set("stream.autotune.dir", "elsewhere")),
            _perturb("manifest:format_version", "format", _ckpt_stamp),
        )),
    # The warm miner source identity: scan-shaping config + corpus
    # paths; content validity is the encoded cache's own per-block
    # gate. Mining parameters are documented exclusions (key-covered:
    # at source_tuple), so they are not registered dimensions here.
    KeySite(
        name="warm.miner",
        path="avenir_tpu/core/keys.py",
        seed=_miner_seed,
        key=_miner_key,
        serve=_miner_serve,
        perturbs=(
            _perturb("conf:fia.skip.field.count", "affecting",
                     _set("fia.skip.field.count", "3")),
            _perturb("conf:fia.infreq.item.marker", "affecting",
                     _set("fia.infreq.item.marker", "RARE")),
            _perturb("corpus:content", "affecting", _edit_corpus_row),
            _perturb("conf:stream.autotune.dir", "neutral",
                     _set("stream.autotune.dir", "elsewhere")),
        ),
        warm_proof=_memo_proof("warmcache.json", _miner_serve)),
    # The warm sidecar pin key: the dir basename IS the parse-view
    # digest, so parse config changes repin; fold parameters and the
    # byte budget do not.
    KeySite(
        name="warm.sidecar.pin",
        path="avenir_tpu/server/jobserver.py",
        seed=_pin_seed,
        key=_pin_key,
        serve=_pin_serve,
        perturbs=(
            _perturb("conf:fia.skip.field.count", "affecting",
                     _set("fia.skip.field.count", "1")),
            _perturb("corpus:content", "affecting", _edit_corpus_row),
            _perturb("conf:fia.support.threshold", "neutral",
                     _set("fia.support.threshold", "0.5")),
            _perturb("conf:stream.sidecar.budget.mb", "neutral",
                     _set("stream.sidecar.budget.mb", "32")),
        ),
        warm_proof=_sc_warm_proof(_pin_serve)),
    # The exec-coalesce key: conf_digest means EVERY non-neutral
    # property is view-affecting; the two view-neutral families must
    # keep the key — the live proof of the VIEW_NEUTRAL_KEYS registry.
    KeySite(
        name="exec.coalesce",
        path="avenir_tpu/server/jobserver.py",
        seed=_exec_seed,
        key=_exec_key,
        serve=_exec_serve,
        perturbs=(
            _perturb("conf:fia.support.threshold", "affecting",
                     _set("fia.support.threshold", "0.5")),
            _perturb("corpus:content", "affecting", _edit_corpus_row),
            _perturb("conf:stream.autotune.dir", "neutral",
                     _set("stream.autotune.dir", "elsewhere")),
            _perturb("conf:stream.incremental.state.dir", "neutral",
                     _set("stream.incremental.state.dir",
                          "elsewhere")),
        ),
        warm_proof=_memo_proof("execcache.json", _exec_serve)),
    # The compat batching key: block size and delimiter split batches;
    # mining parameters deliberately do NOT (two different fold params
    # ride one SharedScan) — the mirror image of exec.coalesce.
    KeySite(
        name="compat.batch",
        path="avenir_tpu/core/keys.py",
        seed=_compat_seed,
        key=_compat_key,
        serve=_compat_serve,
        perturbs=(
            _perturb("conf:stream.block.size.mb", "affecting",
                     _set("stream.block.size.mb", "0.004")),
            _perturb("conf:field.delim.in", "affecting",
                     _set("field.delim.in", ";")),
            _perturb("corpus:content", "affecting", _edit_corpus_row),
            _perturb("conf:fia.support.threshold", "neutral",
                     _set("fia.support.threshold", "0.5")),
        ),
        warm_proof=_memo_proof("compatcache.json", _compat_serve)),
    # The autotune profile key: (job, corpus paths) — content-
    # independent BY DESIGN (the profile follows a corpus through
    # appends), so a content append is the registered neutral
    # dimension and a path move is affecting.
    KeySite(
        name="autotune.profile",
        path="avenir_tpu/core/keys.py",
        seed=_prof_seed,
        key=_prof_key,
        serve=_prof_serve,
        perturbs=(
            _perturb("corpus:path", "affecting", _prof_move_corpus),
            _perturb("meta:job", "affecting",
                     lambda root: _set_meta(
                         root, "job", "numericalAttrStats")),
            _perturb("corpus:append", "neutral",
                     lambda root: _append_corpus_rows(root,
                                                      ["g,h,i"])),
            _perturb("manifest:format_version", "format", _prof_stamp),
        )),
    # The encoded-block cache replay identity: per-block CONTENT
    # fingerprints — an mtime touch must replay (the PR 8 contract),
    # a content edit must rebuild.
    KeySite(
        name="cache.fingerprint",
        path="avenir_tpu/native/ingest.py",
        seed=_enc_seed,
        key=_enc_key,
        serve=_enc_serve,
        perturbs=(
            _perturb("corpus:content", "affecting", _edit_corpus_row),
            _perturb("corpus:mtime", "neutral", _touch_corpus),
        ),
        warm_proof=_enc_warm_proof),
    # The served-model warm identity (the score plane's ModelCache):
    # artifact CONTENT digest + stamped format version + classifier
    # dims — a retrain or a conf change misses, an mtime touch hits,
    # a foreign restamp refuses-and-goes-cold (retrain + restamp).
    KeySite(
        name="score.model",
        path="avenir_tpu/core/keys.py",
        seed=_score_seed,
        key=_score_key,
        serve=_score_serve,
        perturbs=(
            _perturb("model:retrain", "affecting", _score_retrain),
            _perturb("conf:log.odds.threshold", "affecting",
                     _set("log.odds.threshold", "5")),
            _perturb("model:mtime", "neutral", _score_touch_model),
            _perturb("stamp:format_version", "format", _score_stamp),
        ),
        warm_proof=_memo_proof("scorecache.json", _score_serve)),
    # The ledger committed-state identity: the path IS the key
    # (namespace + block id), first-commit-wins pins the bytes; the
    # committing worker's id is the registered neutral dimension.
    KeySite(
        name="ledger.committed",
        path="avenir_tpu/dist/ledger.py",
        seed=_led_seed,
        key=_led_key,
        serve=_led_serve,
        perturbs=(
            _perturb("corpus:content", "affecting", _edit_corpus_row),
            _perturb("meta:worker", "neutral",
                     lambda root: _set_meta(root, "worker", "7")),
            _perturb("states:format_version", "format", _led_stamp),
        ),
        warm_proof=_led_warm_proof),
]


# --------------------------------------------------------------------------
# the audit
# --------------------------------------------------------------------------
def audit_keys(sites: Optional[Sequence[KeySite]] = None,
               locations: Optional[Dict[str, Tuple[str, int]]] = None
               ) -> Tuple[List[dict], List[Finding]]:
    """Drive the seed/perturb/serve probe for every registered key
    site. Per site: seed a fresh root, prove the driver re-serves its
    own bytes deterministically, then per registered perturbation —
    seed, cold-fill, perturb IN PLACE (the warm cache stays), key and
    serve again, and cold-recompute the perturbed view in a separate
    root. A view-affecting perturbation must change the key and the
    warm-path serve must equal the cold recompute (same key +
    different cold bytes = ``keys-stale-serve``); a view-neutral one
    must keep the key and warm-hit byte-identically; a format
    perturbation must refuse-and-go-cold. Returns (rows, findings):
    one row per site with per-kind perturbation counts, one finding
    per failed site. Infrastructure failures raise
    :class:`KeysAuditError`."""
    sites = list(sites) if sites is not None else list(KEY_SITES)
    locations = locations or {}
    rows: List[dict] = []
    findings: List[Finding] = []
    base = tempfile.mkdtemp(prefix="graftlint_keys_")
    try:
        for site in sites:
            loc = locations.get(site.name)
            site_dir = os.path.join(base, site.name.replace(".", "_"))
            broot = os.path.join(site_dir, "base")
            os.makedirs(broot, exist_ok=True)
            try:
                site.seed(broot)
                k0 = _canon(site.key(broot))
                b0 = _canon(site.serve(broot))
                b0w = _canon(site.serve(broot))
            except KeysAuditError:
                raise
            except Exception as exc:
                raise KeysAuditError(
                    f"key site {site.name}: driver failed: "
                    f"{type(exc).__name__}: {exc}") from exc
            if b0w != b0:
                raise KeysAuditError(
                    f"key site {site.name}: driver does not re-serve "
                    f"its own bytes deterministically (key {k0})")
            counts = {"affecting": 0, "neutral": 0, "format": 0}
            problems: List[str] = []
            failing: Optional[str] = None
            for n, p in enumerate(site.perturbs):
                warm = os.path.join(site_dir, f"p{n:02d}_warm")
                cold = os.path.join(site_dir, f"p{n:02d}_cold")
                os.makedirs(warm, exist_ok=True)
                os.makedirs(cold, exist_ok=True)
                try:
                    site.seed(warm)
                    ka = _canon(site.key(warm))
                    sa = _canon(site.serve(warm))    # the cold fill
                    p.apply(warm)
                    kb = _canon(site.key(warm))
                    sb = _canon(site.serve(warm))    # over the warm cache
                    site.seed(cold)
                    if p.kind != "format":
                        # a format perturbation corrupts the WARM
                        # cache's manifest; the view is unchanged, so
                        # the cold reference is a plain cold serve
                        p.apply(cold)
                    sc_ = _canon(site.serve(cold))   # the cold recompute
                except KeysAuditError:
                    raise
                except Exception as exc:
                    raise KeysAuditError(
                        f"key site {site.name}: perturbation "
                        f"{p.name} ({p.kind}) crashed the driver: "
                        f"{type(exc).__name__}: {exc}") from exc
                counts[p.kind] += 1
                pproblems: List[str] = []
                if p.kind == "affecting":
                    if kb == ka:
                        pproblems.append(
                            "view-affecting perturbation left the key "
                            "unchanged — the key cannot see this "
                            "dimension")
                    if sb != sc_:
                        pproblems.append(
                            "stale serve: bytes served over the warm "
                            "cache differ from a cold recompute of "
                            "the perturbed view")
                elif p.kind == "neutral":
                    if kb != ka:
                        pproblems.append(
                            "spurious miss: view-neutral perturbation "
                            "changed the key — every such change "
                            "re-scans cold for nothing")
                    if sb != sa:
                        pproblems.append(
                            "view-neutral perturbation changed the "
                            "served bytes")
                    elif site.warm_proof is not None \
                            and not site.warm_proof(warm):
                        pproblems.append(
                            "spurious miss: view-neutral perturbation "
                            "forced a cold recompute (warm hit not "
                            "proven)")
                else:                                # format
                    if sb != sc_:
                        pproblems.append(
                            "version-skewed cache still served: bytes "
                            "differ from a cold recompute (the "
                            "refuse-and-go-cold contract)")
                shutil.rmtree(warm, ignore_errors=True)
                shutil.rmtree(cold, ignore_errors=True)
                if pproblems:
                    failing = p.name
                    problems.append(
                        f"perturbation {p.name} ({p.kind}): "
                        + "; ".join(pproblems))
                    break        # first failing perturbation is THE repro
            validated = not problems
            rows.append({"site": site.name,
                         "path": loc[0] if loc else site.path,
                         "line": loc[1] if loc else 1,
                         "perturbations": dict(counts),
                         "failing_perturbation":
                             f"{site.name}:{failing}" if failing
                             else None,
                         "key_validated": validated})
            if not validated:
                findings.append(Finding(
                    loc[0] if loc else site.path,
                    loc[1] if loc else 1,
                    KEYS_AUDIT_RULE,
                    f"key site `{site.name}` failed perturbation "
                    f"audit: {'; '.join(problems)}",
                    "fold the failing dimension into the key (or "
                    "re-prove content before serving); never "
                    "allowlist a stale serve",
                    site.name))
    finally:
        _enc_reset()
        shutil.rmtree(base, ignore_errors=True)
    return rows, findings


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------
def run_keys(paths: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[KeysRule]] = None,
             baseline: Optional[Sequence[BaselineEntry]] = None,
             root: Optional[str] = None, include_md: bool = True,
             audit: bool = True,
             sites: Optional[Sequence[KeySite]] = None) -> Report:
    """Lint `paths` (default: the cache surface) with the keys rules,
    drive the perturbation auditor over the registered sites (default:
    KEY_SITES, after the key_site registry cross-check), and apply the
    allowlist baseline to the RULE findings only —
    ``keys-stale-serve`` findings are appended after the baseline pass
    and can never be suppressed."""
    active = list(rules) if rules is not None else \
        [r() for r in ALL_KEYS_RULES]
    root = os.path.abspath(root or os.getcwd())
    scan = list(paths) if paths else default_keys_paths(root)
    report, raw = collect_findings(scan, active, root, include_md)
    audit_findings: List[Finding] = []
    if audit:
        locations: Dict[str, Tuple[str, int]] = {}
        if sites is None:
            locations = check_key_registry()
        rows, audit_findings = audit_keys(sites=sites,
                                          locations=locations)
        report.key_audit.extend(rows)
    active_ids = {r.rule_id for r in active}
    apply_baseline(report, raw, baseline, active_ids)
    # the never-baselined contract: stale-serve findings join findings
    # AFTER the allowlist pass, so no entry can ever suppress one
    report.findings.extend(audit_findings)
    return report
